"""Group BatchNorm (NHWC) with optional fused ReLU / add-ReLU.

Reference parity: apex.contrib.groupbn.BatchNorm2d_NHWC
(contrib/groupbn/batch_norm.py:101 — CUDA-IPC cross-GPU group BN with
bn_group ranks sharing statistics, optional fused relu and residual
add-relu) and apex.contrib.cudnn_gbn.GroupBatchNorm2d
(contrib/cudnn_gbn/batch_norm.py:44 — the cudnn-frontend flavor of the
same thing).

TPU design: "a BN whose statistics span a group of devices" is exactly
SyncBatchNorm over a mesh axis; the IPC peer-memory machinery is a psum.
``bn_group`` semantics (stats shared by groups of ranks along the dp axis)
are expressed by choosing which mesh axes to reduce over; the fused
relu/add-relu epilogues are XLA fusions.
"""

from typing import Optional, Sequence

import flax.linen as nn
import jax

from apex_tpu.parallel.sync_batch_norm import SyncBatchNorm


class GroupBatchNorm2d(nn.Module):
    """(ref: groupbn/batch_norm.py:101 constructor — num_features, eps,
    momentum, fuse_relu, bn_group). ``axis_names`` names the mesh axes the
    statistics group spans (the bn_group); () = plain local BN."""

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    fuse_relu: bool = False
    axis_names: Sequence[str] = ("dp",)

    @nn.compact
    def __call__(self, x, z=None, train: bool = False):
        """``z``: optional residual fused as add-relu (ref: the bn_addrelu
        kernels, batch_norm.py fwd/bwd _addrelu paths)."""
        if x.shape[-1] != self.num_features:
            raise ValueError(
                f"expected {self.num_features} channels, got {x.shape[-1]}"
            )
        y = SyncBatchNorm(
            axis_names=tuple(self.axis_names),
            momentum=self.momentum,
            epsilon=self.eps,
            name="bn",
        )(x, use_running_average=not train)
        if z is not None:
            # the reference asserts fuse_relu for the add-relu path
            # (groupbn/batch_norm.py:197-198)
            assert self.fuse_relu, "residual add requires fuse_relu=True"
            return jax.nn.relu(y + z)
        if self.fuse_relu:
            return jax.nn.relu(y)
        return y
