"""Contrib zoo parity (ref: apex/contrib — SURVEY.md §2.3).

Each module re-designs one reference contrib extension for TPU. Where the
reference ships a CUDA kernel, the TPU path is either a Pallas kernel or an
XLA-fused jnp composition (the fusion the CUDA kernel hand-codes is exactly
what XLA does to elementwise chains on TPU).
"""

from apex_tpu.contrib.bottleneck import (
    Bottleneck,
    SpatialBottleneck,
    halo_exchange_1d,
)
from apex_tpu.contrib.conv_bias_relu import (
    conv_bias,
    conv_bias_mask_relu,
    conv_bias_relu,
    conv_frozen_scale_bias_relu,
)
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.groupbn import GroupBatchNorm2d
from apex_tpu.contrib.group_norm import GroupNorm, group_norm
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from apex_tpu.contrib import sparsity
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)
from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss

__all__ = [
    "conv_bias",
    "conv_bias_mask_relu",
    "conv_bias_relu",
    "conv_frozen_scale_bias_relu",
    "GroupBatchNorm2d",
    "Bottleneck",
    "SpatialBottleneck",
    "halo_exchange_1d",
    "EncdecMultiheadAttn",
    "SelfMultiheadAttn",
    "sparsity",
    "focal_loss",
    "GroupNorm",
    "group_norm",
    "index_mul_2d",
    "TransducerJoint",
    "TransducerLoss",
    "transducer_joint",
    "transducer_loss",
    "SoftmaxCrossEntropyLoss",
]
