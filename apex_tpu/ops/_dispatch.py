"""Implementation dispatch for fused ops.

Every op in apex_tpu.ops has (a) a pure-jnp reference implementation that XLA
already fuses well, and (b) optionally a Pallas TPU kernel for the cases where
hand control of VMEM tiling wins. ``resolve_impl`` picks between them:

- ``"auto"``   : Pallas on a real TPU backend, XLA elsewhere.
- ``"pallas"`` : force Pallas (interpreted off-TPU — used by tests to
                 exercise kernel code paths on the CPU mesh).
- ``"xla"``    : force the jnp reference implementation.
"""

import functools

import jax


@functools.lru_cache(maxsize=None)
def on_tpu() -> bool:
    # The axon relay exposes the real chip under platform name "axon" with a
    # TPU device_kind; treat any TPU-kind device as TPU so "auto" dispatches
    # to compiled Mosaic kernels instead of silently falling back to XLA.
    try:
        dev = jax.devices()[0]
        return dev.platform in ("tpu", "axon") or "TPU" in (dev.device_kind or "")
    except Exception:  # pragma: no cover
        return False


def resolve_impl(impl: str):
    """Returns (use_pallas: bool, interpret: bool)."""
    if impl == "auto":
        impl = "pallas" if on_tpu() else "xla"
    if impl == "pallas":
        return True, not on_tpu()
    if impl == "xla":
        return False, False
    raise ValueError(f"unknown impl {impl!r}; expected auto|pallas|xla")
