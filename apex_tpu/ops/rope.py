"""Rotary position embedding.

Reference parity: ``fused_rotary_positional_embedding``
(csrc/megatron/fused_rotary_positional_embedding.cpp:126-133) and the autograd
wrappers FusedRoPEFunc / FusedRoPECachedFunc
(transformer/functional/fused_rope.py:19,80).

On TPU the rotate-half + cos/sin multiply is a pure VPU elementwise chain that
XLA fuses into the surrounding attention projections, so no Pallas kernel is
needed; the "cached" variant is just precomputing cos/sin once per step
(rope_frequencies), which jit hoists automatically.

Layout follows the reference: ``t`` is (seq, batch, heads, head_dim) and
``freqs`` is (seq, 1, 1, rot_dim).
"""

import jax.numpy as jnp


def rope_frequencies(dim: int, seq_len: int, base: float = 10000.0, dtype=jnp.float32):
    """Build the (seq, 1, 1, dim) angle tensor (ref: RotaryEmbedding in
    testing/standalone_transformer_lm.py; freqs duplicated across halves)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (seq, dim/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # (seq, dim)
    return emb[:, None, None, :].astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb_cached(t, cos_, sin_):
    """Cached-cos/sin RoPE (ref: fused_apply_rotary_pos_emb_cached,
    transformer/functional/fused_rope.py:121 — t (s, b, h, d), cos_/sin_
    (s, 1, 1, rot_dim)).  ``transpose_output_memory`` is a CUDA memory-
    format knob with no XLA meaning and is intentionally absent."""
    rot_dim = cos_.shape[-1]
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    tr = t_rot.astype(jnp.float32)
    out = (tr * cos_.astype(jnp.float32)
           + _rotate_half(tr) * sin_.astype(jnp.float32)).astype(t.dtype)
    if t_pass.shape[-1] == 0:
        return out
    return jnp.concatenate([out, t_pass], axis=-1)


def apply_rotary_pos_emb(t, freqs):
    """Apply RoPE to the first ``rot_dim`` channels of ``t``.

    Matches the reference semantics (fused_rope.py:19-78): channels beyond
    freqs.shape[-1] pass through; math in fp32, output keeps t.dtype.
    """
    rot_dim = freqs.shape[-1]
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    f = freqs.astype(jnp.float32)
    tr = t_rot.astype(jnp.float32)
    out = tr * jnp.cos(f) + _rotate_half(tr) * jnp.sin(f)
    out = out.astype(t.dtype)
    if t_pass.shape[-1] == 0:
        return out
    return jnp.concatenate([out, t_pass], axis=-1)
