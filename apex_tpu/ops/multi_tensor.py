"""Multi-tensor fused-update engine.

Reference parity: ``apex_C.flatten/unflatten`` (csrc/flatten_unflatten.cpp:16-17)
and the ``amp_C.multi_tensor_*`` kernel family driven by
``multi_tensor_applier`` (apex/multi_tensor_apply/multi_tensor_apply.py:25-31,
csrc/multi_tensor_apply.cuh:19-133).

TPU-native design: instead of chunked CUDA kernel launches over lists of
device pointers, we either

1. operate directly on the pytree — XLA fuses elementwise math across leaves
   inside one jit, which is exactly what multi_tensor_apply buys on GPU; or
2. for the optimizer hot loop, flatten the pytree into one contiguous padded
   1-D buffer per dtype (``FlatBuffer``) and run a single Pallas kernel over
   it (see apex_tpu/optimizers/_fused_kernels.py).

The overflow ``noop_flag`` becomes a pure ``isfinite`` reduction
(``tree_any_non_finite``) that the caller threads through ``lax.cond``.
"""

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.pytree import tree_any_non_finite

# Matches the reference chunk size used by multi_tensor_applier
# (apex/multi_tensor_apply/__init__.py:5). On TPU this is the Pallas grid
# chunk for flat-buffer kernels; it is a multiple of the (8,128) f32 tile.
CHUNK_SIZE = 2048 * 32


def flatten(tensors: Sequence[jax.Array]) -> jax.Array:
    """Concatenate 1-D views of ``tensors`` (ref: apex_C.flatten)."""
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat: jax.Array, like: Sequence[jax.Array]) -> List[jax.Array]:
    """Split ``flat`` back into tensors shaped like ``like`` (ref: apex_C.unflatten)."""
    sizes = [int(np.prod(t.shape)) if t.ndim else 1 for t in like]
    offsets = np.cumsum([0] + sizes)
    return [
        jnp.reshape(flat[offsets[i] : offsets[i + 1]], like[i].shape)
        for i in range(len(like))
    ]


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static description of a flattened pytree: shapes/offsets/padding."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]  # start offset of each leaf in the flat buffer
    total: int  # unpadded total element count
    padded_total: int  # total rounded up to a multiple of CHUNK_SIZE

    @property
    def num_leaves(self) -> int:
        return len(self.shapes)


def flatten_pytree(tree: Any, dtype=None, chunk: int = CHUNK_SIZE):
    """Flatten a pytree of arrays into one padded 1-D buffer + FlatSpec.

    The pad-to-chunk means downstream Pallas kernels see a static grid with
    no remainder handling (the reference handles remainders per-chunk in
    multi_tensor_apply.cuh; padding is cheaper than dynamic shapes on TPU).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
    offsets = tuple(int(o) for o in np.cumsum([0] + sizes)[:-1])
    total = int(sum(sizes))
    padded_total = max(chunk, ((total + chunk - 1) // chunk) * chunk)
    out_dtype = dtype or (dtypes[0] if dtypes else jnp.float32)
    if leaves:
        flat = jnp.concatenate([jnp.ravel(l).astype(out_dtype) for l in leaves])
    else:
        flat = jnp.zeros((0,), out_dtype)
    flat = jnp.pad(flat, (0, padded_total - total))
    spec = FlatSpec(treedef, shapes, dtypes, offsets, total, padded_total)
    return flat, spec


def unflatten_pytree(flat: jax.Array, spec: FlatSpec, cast_back: bool = True) -> Any:
    leaves = []
    for shape, dtype, offset in zip(spec.shapes, spec.dtypes, spec.offsets):
        size = int(np.prod(shape)) if len(shape) else 1
        leaf = jnp.reshape(flat[offset : offset + size], shape)
        if cast_back:
            leaf = leaf.astype(dtype)
        leaves.append(leaf)
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


# ---------------------------------------------------------------------------
# multi_tensor_* functional ops (ref: csrc/amp_C_frontend.cpp:192-225)
# ---------------------------------------------------------------------------


def multi_tensor_scale(tree: Any, scale) -> Tuple[Any, jax.Array]:
    """out = tree * scale; returns (out, overflow_flag).

    Ref: multi_tensor_scale_kernel.cu — copy-with-scale + noop_flag on
    non-finite. XLA fuses the scale into neighbouring ops for free.
    """
    out = jax.tree_util.tree_map(lambda x: x * jnp.asarray(scale, x.dtype), tree)
    return out, tree_any_non_finite(tree)


def multi_tensor_axpby(a, b, x_tree: Any, y_tree: Any) -> Tuple[Any, jax.Array]:
    """out = a*x + b*y; returns (out, overflow_flag) (ref: multi_tensor_axpby_kernel.cu)."""
    out = jax.tree_util.tree_map(
        lambda x, y: jnp.asarray(a, x.dtype) * x + jnp.asarray(b, x.dtype) * y,
        x_tree,
        y_tree,
    )
    flag = jnp.logical_or(tree_any_non_finite(x_tree), tree_any_non_finite(y_tree))
    return out, flag


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False):
    """Global L2 norm over all leaves, optionally per-leaf norms too.

    Ref: multi_tensor_l2norm_kernel.cu (two-stage block reduction). On TPU a
    tree-wide sum-of-squares is a handful of fused reductions.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        z = jnp.zeros((), jnp.float32)
        return (z, jnp.zeros((0,), jnp.float32)) if per_tensor else z
    sq = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves]
    total = jnp.sqrt(jnp.sum(jnp.stack(sq)))
    if per_tensor:
        return total, jnp.sqrt(jnp.stack(sq))
    return total


def multi_tensor_applier(op, noop_flag, tensor_lists, *args):
    """Compatibility shim mirroring the reference call convention.

    ``op`` is a function taking (noop_flag, tensor_lists, *args) and returning
    (new_tensor_lists, new_noop_flag). Unlike the CUDA version nothing is
    mutated; callers use the returned trees.
    Ref: apex/multi_tensor_apply/multi_tensor_apply.py:25-31.
    """
    return op(noop_flag, tensor_lists, *args)
