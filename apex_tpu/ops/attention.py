"""Flash attention — flagship Pallas kernel #2.

Reference parity: supersedes both ``fmhalib`` (contrib/fmha — seq<=512,
head_dim 64 MLPerf BERT kernel) and ``fast_multihead_attn``
(contrib/multihead_attn — CUTLASS fused MHA): a single blockwise
online-softmax attention kernel with no sequence-length cap.

Design: forward is a Pallas kernel — grid over (batch*heads, q_blocks), K/V
resident in VMEM per (b,h), online softmax accumulation in fp32, causal
blocks skipped entirely via a data-dependent ``fori_loop`` bound. The
backward is two Pallas kernels (dq over q blocks; dk/dv over kv blocks)
that recompute probabilities from the saved logsumexp per block pair —
the standard flash recompute strategy, O(seq x block) memory in both
directions.

Single-chip long context: K/V residency caps the kernel at
``_KV_RESIDENT_BYTES`` (below 16k bf16 / 8k fp32 keys at head_dim 128).
Beyond
it — or when the XLA fallback's full (sq, sk) score tensor would blow
``_SCORE_BYTES`` — dispatch switches to ``_attn_blockwise``: an XLA-level
(cq, ck)-tiled online softmax with a custom lse-recompute VJP, the same
math as the kernel one tile size up, supporting GQA, key-padding masks,
sliding windows, and rectangular causal. ``impl="blockwise"`` forces it.

Long-context across chips is handled one level up by
``apex_tpu.parallel.ring_attention``, which rotates K/V chunks over the
cp ring with this same online-softmax structure per visiting chunk.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import resolve_impl

_NEG_INF = -1e30


def _causal_hi(qi, bq: int, bk: int, num_kv, offs: int = 0):
    """Last kv block (exclusive) participating for q block ``qi`` under the
    causal mask — shared by the fwd/bwd kernels (offs=0) and the blockwise
    path (offs = sk - sq, bottom-right alignment)."""
    return jnp.minimum(jax.lax.div((qi + 1) * bq + offs - 1, bk) + 1, num_kv)


def _causal_keep(qi, kj, bq: int, bk: int, window=None, offs: int = 0):
    """(bq, bk) keep-mask (True = attend) for block pair (qi, kj); with a
    sliding ``window`` W, each row attends to cols in (row - W, row]. Query
    row r sits at global key position r + offs."""
    row = qi * bq + offs + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = col <= row
    if window is not None:
        keep = jnp.logical_and(keep, col > row - window)
    return keep


def _window_lo(qi, bq: int, bk: int, window, offs: int = 0):
    """First kv block (inclusive) a windowed-causal q block touches."""
    return jnp.maximum(0, jax.lax.div(qi * bq + offs - window + 1, bk))


def _q_band(kj, bq: int, bk: int, num_q, causal: bool, window, offs: int = 0):
    """[lo, hi) q-block range whose band intersects kv block ``kj`` — the
    transpose of (_window_lo, _causal_hi); shared by the dkv kernel
    (offs=0) and the blockwise dk/dv pass."""
    lo = (
        jnp.maximum(0, jax.lax.div(kj * bk - offs, bq)) if causal else 0
    )
    hi = (
        jnp.minimum(num_q, jax.lax.div(kj * bk + bk + window - 2 - offs, bq) + 1)
        if window is not None
        else num_q
    )
    return lo, hi


def window_mask(sq: int, sk: int, window: int):
    """(sq, sk) bool mask, True = BEYOND the sliding window's lower edge
    (col <= row - window, bottom-right aligned like causal_mask). The single
    source of the band formula for the fused kernels' XLA fallback and the
    unfused CoreAttention path."""
    return (
        jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None] + (sk - sq) - window
    )


def causal_mask(sq: int, sk: int):
    """(sq, sk) bool mask, True = masked out. Bottom-right aligned for
    rectangular scores (sk > sq ⇒ the query block sits at the end of the
    key sequence — the KV-cache / blockwise convention)."""
    return jnp.arange(sk)[None, :] > jnp.arange(sq)[:, None] + (sk - sq)


def _attn_ref(q, k, v, scale, causal, mask=None, window=None):
    """Plain XLA attention; q: (B, H, S, D); k/v: (B, H_kv, S, D) with
    H % H_kv == 0 (GQA: each kv head serves H/H_kv query heads)."""
    h, h_kv = q.shape[1], k.shape[1]
    if h_kv != h:
        k = jnp.repeat(k, h // h_kv, axis=1)
        v = jnp.repeat(v, h // h_kv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        s = jnp.where(causal_mask(s.shape[-2], s.shape[-1]), _NEG_INF, s)
    if window is not None:
        s = jnp.where(window_mask(s.shape[-2], s.shape[-1], window), _NEG_INF, s)
    if mask is not None:
        s = jnp.where(mask, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)
    # fully-masked rows (e.g. the whole sliding window padded out) must be
    # ZERO, not uniform-softmax leakage over equal -1e30 scores — the same
    # dead-row contract as the Pallas kernel and the blockwise/ring paths
    dead = jnp.all(s <= _NEG_INF * 0.5, axis=-1, keepdims=True)
    return jnp.where(dead, jnp.zeros((), out.dtype), out)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, bq, bk,
                      has_kpm, window=None):
    # dot operands KEEP the input dtype (bf16 stays bf16) with fp32
    # accumulation via preferred_element_type — upcasting operands to fp32
    # before the dot forces the MXU's slow fp32 path and was the dominant
    # cost of this kernel; softmax math stays fp32 throughout
    kpm_ref = refs[0] if has_kpm else None  # (1, SK) int32, 1 = padded key
    o_ref, lse_ref = refs[-2:]
    q = q_ref[0]  # (BQ, D)
    seq_k = k_ref.shape[1]
    qi = pl.program_id(1)
    num_kv = seq_k // bk
    hi = _causal_hi(qi, bq, bk, num_kv) if causal else num_kv
    lo = _window_lo(qi, bq, bk, window) if window is not None else 0

    # the m/l running stats are carried (bq, 1) 2-D, not (bq,): Mosaic
    # tiles the last two dims and 1-D loop carries are the classic
    # interpret-passes/compile-rejects hazard (r2 verdict weak #3)
    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * bk, bk), :]  # (BK, D)
        vb = v_ref[0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (BQ, BK), fp32
        if causal:
            s = jnp.where(_causal_keep(qi, j, bq, bk, window), s, _NEG_INF)
        if has_kpm:
            s = jnp.where(kpm_ref[:, pl.ds(j * bk, bk)] == 0, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    d = q_ref.shape[2]
    init = (
        jnp.zeros((bq, d), jnp.float32),
        jnp.full((bq, 1), _NEG_INF, jnp.float32),
        jnp.zeros((bq, 1), jnp.float32),
    )
    acc, m, l = jax.lax.fori_loop(lo, hi, body, init)
    # fully-masked rows (every key padded): the finite -1e30 mask means the
    # loop accumulated a spurious uniform softmax (p = exp(0) = 1 per key).
    # Emit ZEROS and a +1e30 lse sentinel instead: output-zero rows make the
    # backward's p = exp(s - lse) underflow to exactly 0, so the custom VJP
    # is self-consistent (o = 0 constant => dq = dk = dv = 0 for that row)
    # and no padded v values leak into the output. The XLA kpm path zeroes
    # dead rows identically (flash_attention wrapper).
    dead = m <= _NEG_INF * 0.5
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = jnp.where(dead, 0.0, acc / l).astype(o_ref.dtype)
    lse_ref[0, 0, :] = jnp.where(dead, -_NEG_INF, m + jnp.log(l))[:, 0]


def _kpm_spec(heads, sk):
    """Key-padding-mask block: the (b, sk) int32 mask row for this (b*h)
    grid step — heads is static, so b = bh // heads is an index-map affine."""
    return pl.BlockSpec((1, sk), lambda b_h, i, heads=heads: (b_h // heads, 0))


def _kv_spec(group, sk, d):
    """K/V block for GQA: q-head row bh maps to kv row bh // group (group =
    h // h_kv, static). group == 1 recovers plain MHA indexing."""
    return pl.BlockSpec(
        (1, sk, d), lambda b_h, i, group=group: (b_h // group, 0, 0)
    )


def _flash_fwd(q3, kv3, kpm, heads, group, scale, causal, interpret, bq, bk, window):
    k3, v3 = kv3
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    grid = (bh, sq // bq)
    has_kpm = kpm is not None
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        _kv_spec(group, sk, d),
        _kv_spec(group, sk, d),
    ]
    inputs = [q3, k3, v3]
    if has_kpm:
        in_specs.append(_kpm_spec(heads, sk))
        inputs.append(kpm)
    o, lse = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
            has_kpm=has_kpm, window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
            # lse carries a singleton middle dim so its block (1, 1, bq)
            # satisfies the TPU (8, 128) tiling rule on the last two dims
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
        ),
        interpret=interpret,
    )(*inputs)
    return o, lse.reshape(bh, sq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q3, kv3, kpm, heads, group, scale, causal, interpret, bq, bk, window):
    o, _ = _flash_fwd_res(
        q3, kv3, kpm, heads, group, scale, causal, interpret, bq, bk, window
    )
    return o


def _flash_fwd_res(q3, kv3, kpm, heads, group, scale, causal, interpret, bq, bk, window):
    o, lse = _flash_fwd(
        q3, kv3, kpm, heads, group, scale, causal, interpret, bq, bk, window
    )
    return o, (q3, kv3, kpm, o, lse)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *refs, scale, causal, bq, bk, has_kpm, window=None):
    """dq for one q block: loop over participating kv blocks (the exact
    recompute-from-lse strategy of the standard flash backward)."""
    kpm_ref = refs[0] if has_kpm else None
    dq_ref = refs[-1]
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0, :]
    delta = delta_ref[0, 0, :]
    seq_k = k_ref.shape[1]
    num_kv = seq_k // bk
    hi = _causal_hi(qi, bq, bk, num_kv) if causal else num_kv
    lo = _window_lo(qi, bq, bk, window) if window is not None else 0

    def body(j, acc):
        # operands keep the input dtype; fp32 accumulation (see fwd kernel)
        kb = k_ref[0, pl.ds(j * bk, bk), :]
        vb = v_ref[0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - lse[:, None])
        if causal:
            p = jnp.where(_causal_keep(qi, j, bq, bk, window), p, 0.0)
        if has_kpm:
            p = jnp.where(kpm_ref[:, pl.ds(j * bk, bk)] == 0, p, 0.0)
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        return acc + jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    d = q_ref.shape[2]
    dq = jax.lax.fori_loop(lo, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *refs, scale, causal, bq, bk, has_kpm, window=None):
    """dk/dv for one kv block: loop over participating q blocks."""
    kpm_ref = refs[0] if has_kpm else None
    dk_ref, dv_ref = refs[-2:]
    kj = pl.program_id(1)
    kb = k_ref[0]  # (BK, D)
    vb = v_ref[0]
    seq_q = q_ref.shape[1]
    num_q = seq_q // bq
    lo, hi_q = _q_band(kj, bq, bk, num_q, causal, window)

    def body(i, carry):
        # operands keep the input dtype; fp32 accumulation (see fwd kernel)
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * bq, bq), :]
        dob = do_ref[0, pl.ds(i * bq, bq), :]
        lse_b = lse_ref[0, 0, pl.ds(i * bq, bq)]
        delta_b = delta_ref[0, 0, pl.ds(i * bq, bq)]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - lse_b[:, None])
        if causal:
            p = jnp.where(_causal_keep(i, kj, bq, bk, window), p, 0.0)
        if has_kpm:
            # this kv block's slice of the padding row: keys of THIS block
            p = jnp.where(kpm_ref[:, pl.ds(kj * bk, bk)] == 0, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_b[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    d = q_ref.shape[2]
    init = (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32))
    dk, dv = jax.lax.fori_loop(lo, hi_q, body, init)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(heads, group, scale, causal, interpret, bq, bk, window, res, do):
    """Pallas flash backward: recompute p from the saved logsumexp per
    block pair — O(seq x block) memory like the forward, never the full
    (sq, sk) score matrix (previously an XLA einsum chain).

    GQA (group > 1): both kernels run per Q head with grouped K/V indexing;
    dk/dv come out as per-q-head partials and are group-summed afterwards."""
    q3, (k3, v3), kpm, o, lse = res
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    has_kpm = kpm is not None
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (BH, SQ)
    lse3 = lse.reshape(bh, 1, sq)
    delta3 = delta.reshape(bh, 1, sq)

    full_q = pl.BlockSpec((1, sq, d), lambda b, i: (b, 0, 0))
    full_k = _kv_spec(group, sk, d)
    row_q = pl.BlockSpec((1, 1, sq), lambda b, i: (b, 0, 0))
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),  # q block
        full_k, full_k,                                    # k, v resident
        pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),  # do block
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),  # lse block
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),  # delta block
    ]
    inputs = [q3, k3, v3, do, lse3, delta3]
    if has_kpm:
        in_specs.append(_kpm_spec(heads, sk))
        inputs.append(kpm)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
            has_kpm=has_kpm, window=window,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q3.dtype),
        grid=(bh, sq // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(*inputs)

    in_specs_kv = [
        full_q,                                            # q resident
        pl.BlockSpec((1, bk, d),                           # k block (grouped)
                     lambda b, j, g=group: (b // g, j, 0)),
        pl.BlockSpec((1, bk, d),
                     lambda b, j, g=group: (b // g, j, 0)),
        full_q,                                            # do resident
        row_q,                                             # lse full row
        row_q,                                             # delta full row
    ]
    if has_kpm:
        in_specs_kv.append(_kpm_spec(heads, sk))
    # per-Q-HEAD partials: grid still runs over all bh q-head rows, so two
    # q heads sharing a kv head never race on one output block
    dk_p, dv_p = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
            has_kpm=has_kpm, window=window,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v3.dtype),
        ),
        grid=(bh, sk // bk),
        in_specs=in_specs_kv,
        out_specs=(
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        ),
        interpret=interpret,
    )(*inputs)
    if group > 1:
        # q-head row r = b*heads + kv*group + j  ->  sum over j
        bhkv = bh // group
        dk = dk_p.reshape(bhkv, group, sk, d).sum(axis=1).astype(k3.dtype)
        dv = dv_p.reshape(bhkv, group, sk, d).sum(axis=1).astype(v3.dtype)
    else:
        dk, dv = dk_p, dv_p
    # kpm is an int mask: no cotangent (None == symbolic zero)
    return dq, (dk, dv), None


_flash.defvjp(_flash_fwd_res, _flash_bwd)


# ---------------------------------------------------------------------------
# Blockwise long-context path (single chip)
# ---------------------------------------------------------------------------

# The Pallas kernels keep K/V fully VMEM-resident per (batch, head) — the
# fastest layout while K+V fit (8 MB leaves room for q/do blocks, fp32
# accumulators, and double-buffering inside the 16 MB scoped-VMEM limit).
# Past that, attention switches to the blockwise-XLA path below.
_KV_RESIDENT_BYTES = 8 * 1024 * 1024
# XLA fallback budget: the reference implementation materializes the full
# (b, h, sq, sk) fp32 score tensor; beyond this it pages through HBM or
# OOMs, so the blockwise path takes over.
_SCORE_BYTES = 1 << 30


def _bw_chunk(n: int, target: int) -> int:
    c = min(target, n)
    while n % c:
        c -= 1
    return c


def _blockwise_masks(i, j, cq, ck, offs, causal, window):
    """(cq, ck) keep-mask or None — the kernels' band mask at chunk
    granularity with the bottom-right offset (window implies causal at the
    API layer, so non-causal chunks are unmasked)."""
    if not causal:
        return None
    return _causal_keep(i, j, cq, ck, window, offs)


def _blockwise_kv_bounds(i, cq, ck, nk, offs, causal, window):
    """[lo, hi) kv-chunk range intersecting q chunk ``i``'s band."""
    hi = _causal_hi(i, cq, ck, nk, offs) if causal else nk
    lo = _window_lo(i, cq, ck, window, offs) if window is not None else 0
    return lo, hi


def _bw_score(qi, kc, scale):
    # operands keep the input dtype, fp32 accumulation (same MXU policy as
    # the Pallas kernels)
    return (
        jnp.einsum(
            "bGgqd,bGkd->bGgqk", qi, kc, preferred_element_type=jnp.float32
        )
        * scale
    )


def _kpm_chunk_keep(kpm, j, ck):
    """(b, 1, 1, 1, ck) keep-mask slice of the key-padding mask."""
    sl = jax.lax.dynamic_slice_in_dim(kpm, j * ck, ck, axis=1)
    return (sl == 0)[:, None, None, None, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _blockwise(q5, kv, kpm, scale, causal, window, cq, ck):
    o, _ = _blockwise_fwd_res(q5, kv, kpm, scale, causal, window, cq, ck)
    return o


def _blockwise_fwd_res(q5, kv, kpm, scale, causal, window, cq, ck):
    """q5: (b, h_kv, g, sq, d); k/v: (b, h_kv, sk, d). Outer scan over q
    chunks, inner fori over the kv chunks in the band — memory is one
    (cq, ck) score tile per (b, h) instead of (sq, sk)."""
    k, v = kv
    b, h_kv, g, sq, d = q5.shape
    sk = k.shape[2]
    nq, nk = sq // cq, sk // ck
    offs = sk - sq
    has_kpm = kpm is not None

    def q_chunk_step(_, i):
        qi = jax.lax.dynamic_slice_in_dim(q5, i * cq, cq, axis=3)
        lo, hi = _blockwise_kv_bounds(i, cq, ck, nk, offs, causal, window)

        def kv_step(j, state):
            acc, m, l = state
            kc = jax.lax.dynamic_slice_in_dim(k, j * ck, ck, axis=2)
            vc = jax.lax.dynamic_slice_in_dim(v, j * ck, ck, axis=2)
            s = _bw_score(qi, kc, scale)
            keep = _blockwise_masks(i, j, cq, ck, offs, causal, window)
            if keep is not None:
                s = jnp.where(keep, s, _NEG_INF)
            if has_kpm:
                s = jnp.where(_kpm_chunk_keep(kpm, j, ck), s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bGgqk,bGkd->bGgqd", p.astype(vc.dtype), vc,
                preferred_element_type=jnp.float32,
            )
            return acc_new, m_new, l_new

        init = (
            jnp.zeros((b, h_kv, g, cq, d), jnp.float32),
            jnp.full((b, h_kv, g, cq), _NEG_INF, jnp.float32),
            jnp.zeros((b, h_kv, g, cq), jnp.float32),
        )
        acc, m, l = jax.lax.fori_loop(lo, hi, kv_step, init)
        # fully-masked rows -> zeros + lse sentinel (same contract as the
        # Pallas kernel, see _flash_fwd_kernel)
        dead = m <= _NEG_INF * 0.5
        l = jnp.maximum(l, 1e-30)
        o_i = jnp.where(dead[..., None], 0.0, acc / l[..., None])
        lse_i = jnp.where(dead, -_NEG_INF, m + jnp.log(l))
        return None, (o_i.astype(q5.dtype), lse_i)

    _, (o_chunks, lse_chunks) = jax.lax.scan(
        q_chunk_step, None, jnp.arange(nq)
    )
    # (nq, b, G, g, cq, ...) -> (b, G, g, sq, ...)
    o = jnp.moveaxis(o_chunks, 0, 3).reshape(b, h_kv, g, sq, d)
    lse = jnp.moveaxis(lse_chunks, 0, 3).reshape(b, h_kv, g, sq)
    return o, (q5, kv, kpm, o, lse)


def _blockwise_bwd(scale, causal, window, cq, ck, res, do):
    q5, (k, v), kpm, o, lse = res
    b, h_kv, g, sq, d = q5.shape
    sk = k.shape[2]
    nq, nk = sq // cq, sk // ck
    offs = sk - sq
    has_kpm = kpm is not None
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (b, G, g, sq)

    def recompute_p(qi, kc, i, j):
        s = _bw_score(qi, kc, scale)
        keep = _blockwise_masks(i, j, cq, ck, offs, causal, window)
        lse_i = jax.lax.dynamic_slice_in_dim(lse, i * cq, cq, axis=3)
        p = jnp.exp(s - lse_i[..., None])
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        if has_kpm:
            p = jnp.where(_kpm_chunk_keep(kpm, j, ck), p, 0.0)
        return p

    # dq: per q chunk, accumulate over its kv band
    def dq_step(_, i):
        qi = jax.lax.dynamic_slice_in_dim(q5, i * cq, cq, axis=3)
        doi = jax.lax.dynamic_slice_in_dim(do, i * cq, cq, axis=3)
        di = jax.lax.dynamic_slice_in_dim(delta, i * cq, cq, axis=3)
        lo, hi = _blockwise_kv_bounds(i, cq, ck, nk, offs, causal, window)

        def kv_step(j, dq_i):
            kc = jax.lax.dynamic_slice_in_dim(k, j * ck, ck, axis=2)
            vc = jax.lax.dynamic_slice_in_dim(v, j * ck, ck, axis=2)
            p = recompute_p(qi, kc, i, j)
            dp = jnp.einsum(
                "bGgqd,bGkd->bGgqk", doi, vc, preferred_element_type=jnp.float32
            )
            ds = p * (dp - di[..., None]) * scale
            return dq_i + jnp.einsum(
                "bGgqk,bGkd->bGgqd", ds.astype(kc.dtype), kc,
                preferred_element_type=jnp.float32,
            )

        dq_i = jax.lax.fori_loop(
            lo, hi, kv_step, jnp.zeros((b, h_kv, g, cq, d), jnp.float32)
        )
        return None, dq_i

    _, dq_chunks = jax.lax.scan(dq_step, None, jnp.arange(nq))
    dq = jnp.moveaxis(dq_chunks, 0, 3).reshape(b, h_kv, g, sq, d)

    # dk/dv: per kv chunk, accumulate over the q band (group summed)
    def dkv_step(_, j):
        kc = jax.lax.dynamic_slice_in_dim(k, j * ck, ck, axis=2)
        vc = jax.lax.dynamic_slice_in_dim(v, j * ck, ck, axis=2)
        lo, hi = _q_band(j, cq, ck, nq, causal, window, offs)

        def q_step(i, carry):
            dk_j, dv_j = carry
            qi = jax.lax.dynamic_slice_in_dim(q5, i * cq, cq, axis=3)
            doi = jax.lax.dynamic_slice_in_dim(do, i * cq, cq, axis=3)
            di = jax.lax.dynamic_slice_in_dim(delta, i * cq, cq, axis=3)
            p = recompute_p(qi, kc, i, j)
            dv_j = dv_j + jnp.einsum(
                "bGgqk,bGgqd->bGkd", p.astype(doi.dtype), doi,
                preferred_element_type=jnp.float32,
            )
            dp = jnp.einsum(
                "bGgqd,bGkd->bGgqk", doi, vc, preferred_element_type=jnp.float32
            )
            ds = p * (dp - di[..., None]) * scale
            dk_j = dk_j + jnp.einsum(
                "bGgqk,bGgqd->bGkd", ds.astype(qi.dtype), qi,
                preferred_element_type=jnp.float32,
            )
            return dk_j, dv_j

        init = (
            jnp.zeros((b, h_kv, ck, d), jnp.float32),
            jnp.zeros((b, h_kv, ck, d), jnp.float32),
        )
        dk_j, dv_j = jax.lax.fori_loop(lo, hi, q_step, init)
        return None, (dk_j, dv_j)

    _, (dk_chunks, dv_chunks) = jax.lax.scan(dkv_step, None, jnp.arange(nk))
    dk = jnp.moveaxis(dk_chunks, 0, 2).reshape(b, h_kv, sk, d).astype(k.dtype)
    dv = jnp.moveaxis(dv_chunks, 0, 2).reshape(b, h_kv, sk, d).astype(v.dtype)
    return dq.astype(q5.dtype), (dk, dv), None


_blockwise.defvjp(_blockwise_fwd_res, _blockwise_bwd)


def _attn_blockwise(q, k, v, scale, causal, window, kpm, chunk_q, chunk_k):
    """Long-context attention by (cq, ck) tiles: O(sq·d) state + one score
    tile live at a time. GQA-grouped, key-padding aware, rectangular-causal
    (bottom-right) like the rest of this module.

    Non-multiple sequence lengths are FRONT-padded up to the target chunk
    instead of shrinking the chunk toward a divisor (a prime 16k+1 length
    would otherwise degrade to chunk 1 and run thousands of tiny tiles).
    Front padding preserves the bottom-right causal/window alignment for
    any pad amounts: real row i maps to i+pq, real key j to j+pk, and the
    band bound j' <= i' + (sk'-sq') reduces exactly to j <= i + (sk-sq);
    padded keys are masked through the key-padding path and padded query
    rows are sliced off the output (their grads vanish through the same
    pad/slice AD)."""
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    cq_t = max(1, min(chunk_q, sq))
    ck_t = max(1, min(chunk_k, sk))
    pq = (-sq) % cq_t
    pk = (-sk) % ck_t
    if pk:
        k = jnp.pad(k, ((0, 0), (0, 0), (pk, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (pk, 0), (0, 0)))
        base = kpm if kpm is not None else jnp.zeros((b, sk), bool)
        kpm = jnp.concatenate([jnp.ones((b, pk), bool), base], axis=1)
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (pq, 0), (0, 0)))
    sq_p, sk_p = sq + pq, sk + pk
    cq = _bw_chunk(sq_p, cq_t)  # sq_p % cq_t == 0, so this is cq_t
    ck = _bw_chunk(sk_p, ck_t)
    q5 = q.reshape(b, h_kv, group, sq_p, d)
    o = _blockwise(q5, (k, v), kpm, scale, causal, window, cq, ck)
    o = o.reshape(b, h, sq_p, d)
    return o[:, :, pq:, :] if pq else o


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    scale: float = None,
    mask=None,
    key_padding_mask=None,
    window: int = None,
    impl: str = "auto",
    block_q: int = 128,
    block_k: int = 128,
):
    """Multi-head attention; q,k,v: (batch, heads, seq, head_dim).

    ``key_padding_mask`` ((b, sk) bool, True = padded-out key) stays on the
    Pallas fast path — the reference fmha's variable-seqlen capability
    (contrib/fmha: cu_seqlens) expressed as a mask. An arbitrary ``mask``
    (True = masked out, broadcastable to (b, h, sq, sk)) forces the XLA
    path; the Pallas kernel covers the unmasked / causal / key-padded fast
    paths that the reference's fmha/fast_multihead_attn accelerate.

    ``window`` (sliding-window attention, mistral-style; requires
    ``causal=True``): each query attends only to the last ``window`` keys.
    The kernels skip kv/q blocks fully outside the band, so compute scales
    O(seq * window) instead of O(seq^2).

    GQA: k/v may carry ``h_kv`` heads with ``h % h_kv == 0`` — query head
    ``g * (h // h_kv) + j`` attends through kv head ``g`` (consecutive
    grouping, the llama convention). The kernels index K/V by
    ``q_head // group`` so no materialized head broadcast is needed.
    """
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    if h % h_kv != 0:
        raise ValueError(f"q heads ({h}) not a multiple of kv heads ({h_kv})")
    group = h // h_kv
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (mistral semantics)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    kpm_i = (
        None
        if key_padding_mask is None
        else key_padding_mask.astype(jnp.int32)  # (b, sk), 1 = padded
    )
    if impl == "blockwise":
        if mask is not None:
            raise ValueError("blockwise path takes key_padding_mask, not mask")
        return _attn_blockwise(
            q, k, v, scale, causal, window, kpm_i, 8 * block_q, 8 * block_k
        )
    use_pallas, interpret = resolve_impl(impl)
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    esize = jnp.dtype(q.dtype).itemsize
    kv_resident = 2 * sk * d * esize < _KV_RESIDENT_BYTES
    pallas_ok = (
        use_pallas
        and mask is None
        and sq % bq == 0
        and sk % bk == 0
        and (not causal or sq == sk)
        and kv_resident
    )
    # long-context autodispatch: whenever the kernel is out (K/V past the
    # VMEM-residency budget, or any other pallas_ok reason) AND the dense
    # fallback's full fp32 score tensor would blow its budget, tile instead
    if mask is None and not pallas_ok and (
        (use_pallas and not kv_resident)
        or 4 * b * h * sq * sk > _SCORE_BYTES
    ):
        return _attn_blockwise(
            q, k, v, scale, causal, window, kpm_i, 8 * block_q, 8 * block_k
        )
    if not pallas_ok:
        if key_padding_mask is not None:
            # _attn_ref's dead-row zeroing covers fully-padded rows
            kp = key_padding_mask[:, None, None, :]  # (b, 1, 1, sk)
            mask = kp if mask is None else jnp.logical_or(mask, kp)
        return _attn_ref(q, k, v, scale, causal, mask, window)
    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h_kv, sk, d)
    v3 = v.reshape(b * h_kv, sk, d)
    o = _flash(
        q3, (k3, v3), kpm_i, h, group, scale, causal, interpret, bq, bk, window
    )
    return o.reshape(b, h, sq, d)
