"""Fused LayerNorm / RMSNorm — flagship Pallas kernel #1.

Reference parity: ``fused_layer_norm_cuda`` (csrc/layer_norm_cuda.cpp:446-458,
layer_norm_cuda_kernel.cu — Welford rowwise stats) and the Python wrappers in
apex/normalization/fused_layer_norm.py (affine / non-affine / RMS / mixed-dtype
/ memory_efficient variants).

TPU design notes:
- math is always fp32 internally, inputs/outputs keep their dtype; parameters
  may have a different dtype than the input (this subsumes the reference's
  "Mixed" variants, fused_layer_norm.py:94-117 — no separate code path
  needed).
- the backward kernel recomputes row statistics from the saved input instead
  of saving mean/rstd: the block is already in VMEM and recompute is cheaper
  than the extra HBM traffic (the reference saves mean/invvar instead because
  CUDA blocks re-read from HBM).
- ``memory_efficient=True`` maps to ``jax.checkpoint`` (recompute-in-backward),
  the TPU idiom for the reference's recompute-from-output mode
  (fused_layer_norm.py ``memory_efficient`` arg).
- rows are padded to the Pallas block; hidden sizes that are not multiples of
  128 lanes fall back to the XLA path automatically.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._dispatch import resolve_impl


def _pick_block_rows(rows: int, hidden: int) -> int:
    # Sized from a measured v5e failure, not theory: at 1<<20 elements/block
    # (4MB fp32) the bwd kernel's fp32 temporaries (x, dy, xhat, dyw, dx —
    # Mosaic stack-allocates each) blew the 16MB scoped-vmem limit by 32KB at
    # hidden=4096.  1<<18 (1MB fp32 per operand block) keeps the ~10-copy
    # working set near 10MB with double-buffering headroom; LN is HBM-bound,
    # so narrower blocks cost nothing measurable.
    budget = 1 << 18  # elements of fp32 per block operand
    br = max(8, min(512, budget // max(hidden, 1)))
    br = (br // 8) * 8
    return max(8, min(br, ((rows + 7) // 8) * 8))


# ---------------------------------------------------------------------------
# XLA reference implementations (autodiff provides the backward)
# ---------------------------------------------------------------------------


def _ln_ref(x, w, b, eps):
    # stats-in-f32 contract: mean/variance of bf16 activations lose all
    # significance in an 8-bit mantissa, so the reduction runs in f32 and
    # casts back (precision-auditor allowlist entry
    # "apex_tpu/ops/layer_norm.py", apex_tpu/analysis/allowlist.py)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def _rms_ref(x, w, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(x_ref, w_ref, dy_ref, dx_ref, dg_ref, db_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    dyw = dy * w
    m1 = jnp.mean(dyw, axis=1, keepdims=True)
    m2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
    dx_ref[:] = ((dyw - m1 - xhat * m2) * rstd).astype(dx_ref.dtype)
    # dgamma/dbeta accumulate across the (sequential) TPU grid into one
    # (1, hidden) block: a per-step (grid, hidden) partials array would need
    # a 1-sublane output block, which Mosaic rejects for grid > 1 (measured
    # on v5e: "last two dimensions ... divisible by 8 and 128")
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dg_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


def _rms_fwd_kernel(x_ref, w_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=1, keepdims=True) + eps)
    y_ref[:] = (x * rstd * w_ref[:].astype(jnp.float32)).astype(y_ref.dtype)


def _rms_bwd_kernel(x_ref, w_ref, dy_ref, dx_ref, dg_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=1, keepdims=True) + eps)
    xhat = x * rstd
    dyw = dy * w
    m2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
    dx_ref[:] = ((dyw - xhat * m2) * rstd).astype(dx_ref.dtype)
    # accumulated across the sequential grid (see _ln_bwd_kernel)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)

    dg_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)


def _pad_rows(x2d, block_rows):
    rows = x2d.shape[0]
    padded = ((rows + block_rows - 1) // block_rows) * block_rows
    if padded != rows:
        x2d = jnp.pad(x2d, ((0, padded - rows), (0, 0)))
    return x2d, padded


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln_pallas(x2d, w, b, eps, interpret):
    y, _ = _ln_pallas_fwd(x2d, w, b, eps, interpret)
    return y


def _ln_pallas_fwd(x2d, w, b, eps, interpret):
    rows, hidden = x2d.shape
    br = _pick_block_rows(rows, hidden)
    xp, padded = _pad_rows(x2d, br)
    grid = padded // br
    y = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((padded, hidden), x2d.dtype),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        interpret=interpret,
    )(xp, w.reshape(1, -1), b.reshape(1, -1))
    return y[:rows], (x2d, w, b)


def _ln_pallas_bwd(eps, interpret, res, dy):
    x2d, w, b = res
    rows, hidden = x2d.shape
    br = _pick_block_rows(rows, hidden)
    xp, padded = _pad_rows(x2d, br)
    dyp, _ = _pad_rows(dy, br)
    grid = padded // br
    dx, dgp, dbp = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        out_shape=(
            jax.ShapeDtypeStruct((padded, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ),
        interpret=interpret,
    )(xp, w.reshape(1, -1), dyp)
    dg = dgp.reshape(-1).astype(w.dtype)
    db = dbp.reshape(-1).astype(b.dtype)
    return dx[:rows], dg, db


_ln_pallas.defvjp(_ln_pallas_fwd, _ln_pallas_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_pallas(x2d, w, eps, interpret):
    y, _ = _rms_pallas_fwd(x2d, w, eps, interpret)
    return y


def _rms_pallas_fwd(x2d, w, eps, interpret):
    rows, hidden = x2d.shape
    br = _pick_block_rows(rows, hidden)
    xp, padded = _pad_rows(x2d, br)
    grid = padded // br
    y = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((padded, hidden), x2d.dtype),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        interpret=interpret,
    )(xp, w.reshape(1, -1))
    return y[:rows], (x2d, w)


def _rms_pallas_bwd(eps, interpret, res, dy):
    x2d, w = res
    rows, hidden = x2d.shape
    br = _pick_block_rows(rows, hidden)
    xp, padded = _pad_rows(x2d, br)
    dyp, _ = _pad_rows(dy, br)
    grid = padded // br
    dx, dgp = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps),
        out_shape=(
            jax.ShapeDtypeStruct((padded, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ),
        interpret=interpret,
    )(xp, w.reshape(1, -1), dyp)
    dg = dgp.reshape(-1).astype(w.dtype)
    return dx[:rows], dg


_rms_pallas.defvjp(_rms_pallas_fwd, _rms_pallas_bwd)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def layer_norm(
    x,
    weight=None,
    bias=None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
    impl: str = "auto",
):
    """Fused layer normalization over the last dimension.

    Ref: apex.normalization.FusedLayerNorm (normalization/fused_layer_norm.py:230)
    and fused_layer_norm_cuda.forward_affine (layer_norm_cuda.cpp:446).
    """
    hidden = x.shape[-1]
    use_pallas, interpret = resolve_impl(impl)
    affine = weight is not None
    if use_pallas and hidden % 128 == 0 and affine:
        w = weight
        b = bias if bias is not None else jnp.zeros((hidden,), w.dtype)
        fn = lambda xx, ww, bb: _ln_pallas(
            xx.reshape(-1, hidden), ww, bb, eps, interpret
        ).reshape(xx.shape)
    else:
        fn = lambda xx, ww, bb: _ln_ref(xx, ww, bb, eps)
        w, b = weight, bias
    if memory_efficient:
        fn = jax.checkpoint(fn)
    return fn(x, w, b)


def rms_norm(
    x,
    weight=None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
    impl: str = "auto",
):
    """Fused RMS normalization (ref: FusedRMSNorm, fused_layer_norm.py:329)."""
    hidden = x.shape[-1]
    use_pallas, interpret = resolve_impl(impl)
    if use_pallas and hidden % 128 == 0 and weight is not None:
        fn = lambda xx, ww: _rms_pallas(
            xx.reshape(-1, hidden), ww, eps, interpret
        ).reshape(xx.shape)
        w = weight
    else:
        fn = lambda xx, ww: _rms_ref(xx, ww, eps)
        w = weight
    if memory_efficient:
        fn = jax.checkpoint(fn)
    return fn(x, w)
