"""Fused softmax cross-entropy with label smoothing.

Reference parity: ``xentropy_cuda`` / apex.contrib.xentropy.SoftmaxCrossEntropyLoss
(contrib/xentropy/softmax_xentropy.py:6) — fused softmax+CE forward with
in-place bprop.

TPU design: a logsumexp-based formulation that XLA fuses into two passes; the
backward produced by autodiff is the standard (softmax - onehot) form and
never materializes a second copy of the logits (the "in-place bprop" of the
reference corresponds to XLA buffer donation here).
"""

import jax
import jax.numpy as jnp


def softmax_cross_entropy_loss(
    logits, labels, smoothing: float = 0.0, half_to_float: bool = False
):
    """Per-example CE loss with optional label smoothing.

    ``logits``: (..., vocab); ``labels``: (...) int. Returns losses shaped like
    ``labels`` in fp32 (the reference's half_to_float=True behavior; for
    parity the flag is accepted — fp32 is always used for the loss).
    """
    del half_to_float
    vocab = logits.shape[-1]
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    target_logit = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    nll = lse - target_logit
    if smoothing > 0.0:
        # uniform label smoothing: (1-s)*nll + s/K * sum_k (lse - x_k)
        smooth_loss = lse - jnp.mean(lf, axis=-1)
        nll = (1.0 - smoothing) * nll + smoothing * smooth_loss
    del vocab
    return nll
