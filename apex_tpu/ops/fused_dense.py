"""Fused dense (GEMM + bias [+ GeLU + GEMM]) layers.

Reference parity: ``fused_dense_cuda`` (csrc/fused_dense.cpp:188-191,
cublasLt epilogue fusion) and apex.fused_dense.{FusedDense,FusedDenseGeluDense}
(fused_dense/fused_dense.py:8-96).

On TPU the MXU + XLA fusion already executes bias/GeLU as epilogues of the
matmul — these wrappers exist for API parity and to pin the preferred
bf16-in/fp32-accumulate contract via ``preferred_element_type``.
"""

import jax
import jax.numpy as jnp


def fused_dense(x, weight, bias=None):
    """y = x @ W^T + b with fp32 MXU accumulation.

    ``weight`` is (out, in) like the reference's torch convention.
    """
    y = jax.lax.dot_general(
        x,
        weight,
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def fused_dense_gelu_dense(x, weight1, bias1, weight2, bias2):
    """y = GeLU(x @ W1^T + b1) @ W2^T + b2 (ref: fused_dense.py:36-60)."""
    h = fused_dense(x, weight1, bias1)
    h = jax.nn.gelu(h.astype(jnp.float32), approximate=True).astype(x.dtype)
    return fused_dense(h, weight2, bias2)
