"""Fused ops for TPU (Pallas kernels + XLA-fused compositions).

Reference parity: the native kernel layer csrc/ + apex/normalization +
apex/mlp + apex/fused_dense + apex/transformer/functional (see SURVEY.md
section 2.4). Each op ships a pure-jnp reference implementation and, where a
custom kernel pays off on TPU, a Pallas kernel with a custom_vjp; dispatch is
automatic (Pallas on TPU, interpreted Pallas or jnp elsewhere).
"""

from apex_tpu.ops.multi_tensor import (
    CHUNK_SIZE,
    flatten,
    unflatten,
    flatten_pytree,
    unflatten_pytree,
    multi_tensor_applier,
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
)
from apex_tpu.ops.layer_norm import layer_norm, rms_norm
from apex_tpu.ops.softmax import (
    scaled_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    generic_scaled_masked_softmax,
    fused_scale_mask_softmax,
)
from apex_tpu.ops.rope import (
    apply_rotary_pos_emb,
    apply_rotary_pos_emb_cached,
    rope_frequencies,
)
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.ops.fused_dense import fused_dense, fused_dense_gelu_dense
from apex_tpu.ops.mlp import mlp_apply, mlp_init
from apex_tpu.ops.attention import flash_attention

__all__ = [
    "CHUNK_SIZE",
    "flatten",
    "unflatten",
    "flatten_pytree",
    "unflatten_pytree",
    "multi_tensor_applier",
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "layer_norm",
    "rms_norm",
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
    "fused_scale_mask_softmax",
    "apply_rotary_pos_emb",
    "rope_frequencies",
    "apply_rotary_pos_emb_cached",
    "softmax_cross_entropy_loss",
    "fused_dense",
    "fused_dense_gelu_dense",
    "mlp_apply",
    "mlp_init",
    "flash_attention",
]
