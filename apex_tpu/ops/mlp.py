"""Fused MLP.

Reference parity: ``mlp_cuda`` (csrc/mlp.cpp:163-164 — cuBLAS GEMM chain with
fused bias/ReLU/sigmoid epilogues) and apex.mlp.MLP (mlp/mlp.py:33).

The TPU version is a chain of MXU matmuls whose bias+activation epilogues XLA
fuses; parameters live in a plain pytree so the whole chain sits in one jit.
"""

from typing import Sequence

import jax
import jax.numpy as jnp

_ACTIVATIONS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_init(rng, mlp_sizes: Sequence[int], dtype=jnp.float32):
    """Initialize weights/biases for layer sizes ``mlp_sizes`` (ref
    mlp/mlp.py:41-53: uniform(-1/sqrt(fan_in), 1/sqrt(fan_in)))."""
    params = {"weights": [], "biases": []}
    for i in range(len(mlp_sizes) - 1):
        fan_in, fan_out = mlp_sizes[i], mlp_sizes[i + 1]
        rng, wk, bk = jax.random.split(rng, 3)
        bound = 1.0 / jnp.sqrt(fan_in)
        params["weights"].append(
            jax.random.uniform(wk, (fan_out, fan_in), dtype, -bound, bound)
        )
        params["biases"].append(jax.random.uniform(bk, (fan_out,), dtype, -bound, bound))
    return params


def mlp_apply(params, x, activation: str = "relu"):
    """Forward through the fused MLP chain (ref: mlp/mlp.py:56-76).

    Hidden layers get ``activation``; the final layer is linear, matching the
    reference (activation applied to all but the last GEMM).
    """
    act = _ACTIVATIONS[activation]
    n = len(params["weights"])
    h = x
    for i, (w, b) in enumerate(zip(params["weights"], params["biases"])):
        h = jax.lax.dot_general(
            h, w, (((h.ndim - 1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        h = h + b.astype(jnp.float32)
        if i < n - 1:
            h = act(h)
        h = h.astype(x.dtype)
    return h
