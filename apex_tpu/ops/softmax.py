"""Scaled (masked) softmax family.

Reference parity: the four megatron softmax CUDA modules —
``scaled_upper_triang_masked_softmax_cuda``, ``scaled_masked_softmax_cuda``,
``generic_scaled_masked_softmax_cuda``, ``scaled_softmax_cuda``
(csrc/megatron/*.cpp) and their autograd wrappers + the
``FusedScaleMaskSoftmax`` dispatcher (transformer/functional/fused_softmax.py).

On TPU, XLA fuses scale+mask+softmax into a single VPU pass out of the box,
so these are jnp compositions with fp32 softmax math; the attention-fused
variant (which on GPUs motivated fmha) is ``apex_tpu.ops.flash_attention``.
The kernel-availability heuristics of the reference dispatcher (seq <= 2048,
dims divisible by 4/8, fused-kernel only for fp16/bf16) are irrelevant here;
``fused_scale_mask_softmax`` keeps the same call surface but always fuses.
"""

from typing import Callable, Optional

import jax.numpy as jnp


def _is_causal(attn_mask_type) -> bool:
    """Accepts AttnMaskType or its string name; avoids importing
    apex_tpu.transformer at module scope (cycle: transformer/__init__ ->
    layer -> ops.softmax)."""
    return getattr(attn_mask_type, "name", attn_mask_type) == "causal"

# padding-mask fill matches the reference wrappers' -10000 semantics; the
# causal mask uses a true -inf surrogate so future positions get exactly
# zero probability regardless of logit scale (the reference kernel writes
# exact zeros to the masked region).
_MASK_VALUE = -10000.0
_CAUSAL_MASK_VALUE = -1e30


def _softmax_fp32(x, dtype):
    xf = x.astype(jnp.float32)
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    p = jnp.exp(xf)
    return (p / jnp.sum(p, axis=-1, keepdims=True)).astype(dtype)


def scaled_softmax(x, scale: float = 1.0):
    """softmax(x * scale) (ref: scaled_softmax.cpp:68-73)."""
    return _softmax_fp32(x * scale, x.dtype)


def scaled_masked_softmax(x, mask, scale: float = 1.0):
    """softmax(mask_fill(x*scale)); ``mask`` is True where masked OUT.

    Ref: scaled_masked_softmax.cpp:93-103 — mask shape broadcastable to x
    (b, 1, sq, sk) against (b, np, sq, sk).
    """
    xf = x.astype(jnp.float32) * scale
    if mask is not None:
        xf = jnp.where(mask, _MASK_VALUE, xf)
    return _softmax_fp32(xf, x.dtype)


def generic_scaled_masked_softmax(x, mask, scale: float = 1.0):
    """Arbitrary-size variant (ref: generic_scaled_masked_softmax.cpp:76-82).

    On TPU there is no size specialization; identical to scaled_masked_softmax.
    """
    return scaled_masked_softmax(x, mask, scale)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal softmax over the last two dims (sq, sk).

    Ref: scaled_upper_triang_masked_softmax.cpp:66-71 — input (attn_batches,
    sq, sk), upper triangle (key index > query index) masked out.
    """
    sq, sk = x.shape[-2], x.shape[-1]
    row = jnp.arange(sq)[:, None]
    col = jnp.arange(sk)[None, :]
    causal = col > row + (sk - sq)
    xf = jnp.where(causal, _CAUSAL_MASK_VALUE, x.astype(jnp.float32) * scale)
    return _softmax_fp32(xf, x.dtype)


class FusedScaleMaskSoftmax:
    """Dispatcher mirroring transformer.functional.FusedScaleMaskSoftmax.

    Args follow the reference constructor (fused_softmax.py:~160): the
    ``*_fusion`` flags are accepted for API parity but fusion always happens
    (XLA), and ``softmax_in_fp32`` is always honored internally.
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type="padding",
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        self.attn_mask_type = attn_mask_type
        self.mask_func = mask_func
        self.scale = 1.0 if scale is None else scale
        del input_in_fp16, input_in_bf16, scaled_masked_softmax_fusion, softmax_in_fp32

    def __call__(self, x, mask=None):
        if _is_causal(self.attn_mask_type):
            # ref wrappers assert mask is None on the causal kernel path
            # (fused_softmax.py ScaledUpperTriangMasked*) — fail loudly
            # instead of silently dropping a padding mask.
            assert mask is None, (
                "FusedScaleMaskSoftmax(attn_mask_type=causal) does not accept "
                "an explicit mask; fold padding into the mask and use the "
                "padding mask type instead"
            )
            b, np_, sq, sk = x.shape
            out = scaled_upper_triang_masked_softmax(
                x.reshape(b * np_, sq, sk), self.scale
            )
            return out.reshape(b, np_, sq, sk)
        if mask is not None and self.mask_func is not None:
            xf = self.mask_func(x.astype(jnp.float32) * self.scale, mask)
            return _softmax_fp32(xf, x.dtype)
        return scaled_masked_softmax(x, mask, self.scale)


def fused_scale_mask_softmax(x, mask=None, scale: float = 1.0, causal: bool = False):
    """Functional form of the dispatcher."""
    if causal:
        assert mask is None, (
            "fused_scale_mask_softmax(causal=True) does not accept an "
            "explicit mask; fold padding into the mask and pass causal=False"
        )
        shape = x.shape
        return scaled_upper_triang_masked_softmax(
            x.reshape(-1, shape[-2], shape[-1]), scale
        ).reshape(shape)
    return scaled_masked_softmax(x, mask, scale)
