"""Cross-version jax shims.

The codebase targets current jax, where ``shard_map`` is a top-level API
(``jax.shard_map``) and checked mode tracks varying-manual-axes (vma)
types via the ``check_vma`` flag. Older jax (<= 0.4.x, what some CI and
dev images carry) only has ``jax.experimental.shard_map.shard_map`` with
the predecessor ``check_rep`` flag and no vma tracking.

Import ``shard_map`` from here instead of from jax so one tree runs on
both:

- ``check_vma=``/``check_rep=`` are translated to whatever the running
  jax accepts (the semantics of *False* — tracking off — are identical;
  ``True`` selects whichever checker the jax build has).
- ``HAS_VMA`` gates code and tests that need real vma types (e.g.
  ``jax.eval_shape(...).vma``); on pre-vma jax those must skip or fall
  back (``apex_tpu.parallel.utils.vma_cond`` already falls back on its
  own).
"""

import functools
import inspect

try:  # current jax
    from jax import shard_map as _shard_map
except ImportError:  # pre-0.5 jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)

#: True when this jax tracks varying-manual-axes types under shard_map
#: (the ``check_vma`` era); False on check_rep-only jax.
HAS_VMA = "check_vma" in _PARAMS

__all__ = ["shard_map", "HAS_VMA"]


def shard_map(f=None, *args, **kwargs):
    """``jax.shard_map`` portable across jax versions.

    Accepts either ``check_vma`` (current jax) or ``check_rep`` (older
    jax) and forwards the flag under the name the running jax expects.
    Usable directly or as ``functools.partial(shard_map, mesh=..., ...)``
    exactly like the real API.
    """
    if f is None:
        return functools.partial(shard_map, *args, **kwargs)
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, *args, **kwargs)
