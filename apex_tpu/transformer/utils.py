"""Training-loop utilities (ref: apex/transformer/pipeline_parallel/utils.py).

- ``average_losses_across_data_parallel_group`` (:242) — dp-mean of losses;
- ``calc_params_l2_norm`` (:213) — TP-aware global parameter norm (TP-
  duplicated params counted once);
- ``get_ltor_masks_and_position_ids`` (:303) — GPT input preprocessing;
- ``report_memory`` (:253) — device memory stats via jax;
- ``print_params_min_max_norm`` (:265).
"""

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.monitor.xray import ledger as xlax


def average_losses_across_data_parallel_group(losses, axis_name: str = "dp"):
    """(ref :242) — call inside shard_map; stacks then dp-means."""
    stacked = jnp.stack([jnp.asarray(l, jnp.float32) for l in losses])
    return xlax.pmean(stacked, axis_name)


def calc_params_l2_norm(
    params: Any,
    tp_duplicate_predicate=None,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Global L2 norm of all params (ref :213).

    With ``axis_name`` (model-parallel axis, inside shard_map), per-rank
    partial sums are psum-combined; ``tp_duplicate_predicate(path)`` marks
    params replicated across TP (e.g. layernorm scales) so they are
    counted on rank 0 only — the reference's ``tensor_model_parallel``
    attribute check.
    """
    rank = jax.lax.axis_index(axis_name) if axis_name else 0

    def leaf_sq(path, p):
        sq = jnp.sum(jnp.square(p.astype(jnp.float32)))
        if axis_name and tp_duplicate_predicate is not None:
            pathname = "/".join(str(getattr(k, "key", k)) for k in path)
            if tp_duplicate_predicate(pathname):
                sq = jnp.where(rank == 0, sq, 0.0)
        return sq

    total = sum(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map_with_path(leaf_sq, params)
        )
    )
    if axis_name:
        total = xlax.psum(total, axis_name)
    return jnp.sqrt(total)


def get_ltor_masks_and_position_ids(
    data,
    eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Left-to-right LM masks (ref :303).

    data: (b, s) int tokens. Returns (attention_mask, loss_mask,
    position_ids) where attention_mask is True = MASKED (our convention;
    the reference returns <0.5 after building a tril of ones).
    Document-reset variants rebuild positions/masks after each EOD token —
    implemented with cumulative counts (scan-free, jit-friendly).
    """
    b, s = data.shape
    causal = jnp.triu(jnp.ones((s, s), bool), 1)  # True above diagonal

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
    is_eod = data == eod_token
    # docs[i] = number of EODs strictly before position i
    docs = jnp.cumsum(is_eod, axis=1) - is_eod.astype(jnp.int32)

    if reset_position_ids:
        # positions restart after each EOD: doc_start[i] = (index of the
        # last EOD strictly before i) + 1, via a shifted cummax
        idx = jnp.broadcast_to(jnp.arange(s), (b, s))
        marker = jnp.where(is_eod, idx, -1)
        last_eod = jax.lax.cummax(marker, axis=1)
        prev_last = jnp.concatenate(
            [jnp.full((b, 1), -1, last_eod.dtype), last_eod[:, :-1]], axis=1
        )
        position_ids = idx - (prev_last + 1)

    if reset_attention_mask:
        # tokens attend only within their document
        same_doc = docs[:, :, None] == docs[:, None, :]
        attention_mask = jnp.logical_or(causal[None], ~same_doc)
    else:
        attention_mask = jnp.broadcast_to(causal, (1, s, s))
    # add the head broadcast dim: (b or 1, 1, s, s)
    attention_mask = attention_mask[:, None, :, :]
    return attention_mask, loss_mask, position_ids


def report_memory(name: str) -> str:
    """(ref :253) — per-device live/peak bytes via the blessed
    ``xray.hbm.live`` watermark probe (CPU reports no stats -> 0.0)."""
    from apex_tpu.monitor.xray.hbm.live import device_watermarks

    mb = 1024.0 * 1024.0
    parts = [f"{name} memory (MB)"]
    for d in jax.local_devices():
        wm = device_watermarks(d) or {}
        parts.append(
            f"| {d.platform}:{d.id} in_use: "
            f"{(wm.get('bytes_in_use') or 0) / mb:.1f} peak: "
            f"{(wm.get('peak_bytes_in_use') or 0) / mb:.1f}"
        )
    s = " ".join(parts)
    print(s, flush=True)
    return s


def print_params_min_max_norm(params: Any, iteration: int) -> str:
    """(ref :265) — min/max/norm per param leaf."""
    lines = ["iteration, index, min, max, norm"]
    for i, (path, p) in enumerate(
        jax.tree_util.tree_flatten_with_path(params)[0]
    ):
        pf = jnp.asarray(p, jnp.float32)
        lines.append(
            f"{iteration:7d}, {i:4d}, {float(pf.min()):.6E}, "
            f"{float(pf.max()):.6E}, {float(jnp.linalg.norm(pf)):.6E}"
        )
    s = "\n".join(lines)
    print(s, flush=True)
    return s
