"""Mixture-of-Experts layer with expert parallelism.

No reference counterpart (the reference has no MoE/EP — SURVEY.md §2.5
lists EP as absent); this is the expert-parallelism extension the TPU
framework makes first-class, in the Switch/GShard capacity-based style
that maps cleanly onto static XLA shapes:

- a router scores tokens against E experts (top-1 "switch" or top-2
  "gshard" gating) with the standard load-balancing auxiliary loss
  ``E * Σ_e fraction_e * prob_e``;
- tokens are packed into a (E, capacity, h) dispatch tensor via the
  cumsum position trick (overflow tokens are dropped, pass through the
  residual path);
- experts are sharded over a mesh axis (``expert_axis``): one
  ``all_to_all`` ships each rank's per-expert slots to the expert's owner,
  the expert FFNs run as one batched einsum over the local experts, and a
  second ``all_to_all`` ships results home — the EP dispatch pattern over
  ICI;
- with expert_axis size 1 (or outside shard_map) everything degrades to a
  local MoE.
"""

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.transformer.config import TransformerConfig


def _axis_size_or_1(axis_name: Optional[str]) -> int:
    if axis_name is None:
        return 1
    try:
        return xlax.axis_size(axis_name)
    except NameError:
        return 1


def router_probs(logits, num_experts: int, top_k: int):
    """Softmax gate probabilities + top-k expert assignment."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, top_k)
    return probs, gate_vals, expert_idx


def total_moe_aux_loss(intermediates, config) -> jnp.ndarray:
    """Sum every sown ``moe_aux_loss`` scaled by
    ``config.moe_aux_loss_coeff`` — add this to the training loss:

        out, inter = model.apply(vars, x, mutable=["intermediates"])
        loss = task_loss + total_moe_aux_loss(inter, cfg)
    """
    total = jnp.asarray(0.0, jnp.float32)
    count = 0

    def visit(node):
        nonlocal total, count
        if isinstance(node, dict):
            for k, v in node.items():
                if k == "moe_aux_loss":
                    for leaf in jax.tree_util.tree_leaves(v):
                        total = total + leaf
                        count += 1
                else:
                    visit(v)

    visit(intermediates)
    return config.moe_aux_loss_coeff * total


def load_balancing_loss(probs, expert_idx, num_experts: int):
    """Switch aux loss: E * Σ_e (token fraction to e) * (mean prob of e)."""
    f = jnp.mean(
        jax.nn.one_hot(expert_idx[..., 0], num_experts, dtype=jnp.float32),
        axis=0,
    )
    p = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(f * p)


def _dispatch_indices(expert_idx, num_experts: int, capacity: int):
    """Position of each token inside its expert's capacity buffer (cumsum
    trick); tokens beyond capacity get position -1 (dropped)."""
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based within expert
    pos_in_expert = jnp.sum(pos, axis=-1) - 1
    keep = pos_in_expert < capacity
    return jnp.where(keep, pos_in_expert, -1)


class MoEMLP(nn.Module):
    """Expert-parallel MoE FFN block (Switch top-1 / GShard top-2).

    Input (tokens, hidden) — callers flatten (s, b). ``num_experts`` is the
    GLOBAL expert count and must divide by the expert-axis size; each rank
    owns ``num_experts / ep`` experts. Returns (output, aux_loss).
    """

    config: TransformerConfig
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    expert_axis: Optional[str] = "dp"
    activation: Callable = jax.nn.gelu

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        tokens, h = x.shape
        e = self.num_experts
        ep = _axis_size_or_1(self.expert_axis)
        assert e % ep == 0, f"num_experts ({e}) not divisible by ep ({ep})"
        local_e = e // ep
        ffn = cfg.ffn_hidden_size
        # per-assignment-pass capacity: each of the top_k passes dispatches
        # one assignment per token, so per-pass slots are cf*tokens/e and
        # TOTAL slots per expert are cf*tokens*top_k/e — the GShard
        # convention for the capacity_factor knob
        capacity = max(1, int(self.capacity_factor * tokens / e))

        gate_w = self.param(
            "router", nn.initializers.normal(stddev=0.02), (h, e),
            cfg.params_dtype,
        )
        # router math in fp32 (standard MoE stability practice)
        logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        probs, gate_vals, expert_idx = router_probs(logits, e, self.top_k)
        aux = load_balancing_loss(probs, expert_idx, e)

        # per-rank experts: (local_e, h, ffn) / (local_e, ffn, h)
        w_in = self.param(
            "w_in",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (local_e, h, ffn),
            cfg.params_dtype,
        )
        w_out = self.param(
            "w_out",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (local_e, ffn, h),
            cfg.params_dtype,
        )

        out = jnp.zeros((tokens, h), jnp.float32)
        for k in range(self.top_k):
            idx_k = expert_idx[:, k]
            gate_k = gate_vals[:, k]
            pos = _dispatch_indices(idx_k, e, capacity)
            keep = pos >= 0
            # dispatch: (E, C, h) — scatter each kept token into its slot
            dispatch = jnp.zeros((e, capacity, h), x.dtype)
            dispatch = dispatch.at[
                jnp.where(keep, idx_k, 0),
                jnp.where(keep, pos, 0),
            ].add(jnp.where(keep[:, None], x, 0))

            if ep > 1:
                # (E, C, h) -> (ep, local_e, C, h); all_to_all swaps the ep
                # shards so each rank receives ITS experts' slots from all
                # ranks: result (ep_src, local_e, C, h)
                d = dispatch.reshape(ep, local_e, capacity, h)
                d = xlax.all_to_all(
                    d, self.expert_axis, split_axis=0, concat_axis=0,
                    tiled=False,
                )
            else:
                d = dispatch.reshape(1, local_e, capacity, h)

            # expert FFN over (src, local_e, C, h)
            hdn = jnp.einsum(
                "slch,lhf->slcf", d, w_in.astype(d.dtype),
                preferred_element_type=jnp.float32,
            )
            hdn = self.activation(hdn)
            y = jnp.einsum(
                "slcf,lfh->slch", hdn.astype(d.dtype), w_out.astype(d.dtype),
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)

            if ep > 1:
                y = xlax.all_to_all(
                    y, self.expert_axis, split_axis=0, concat_axis=0,
                    tiled=False,
                )
            y = y.reshape(e, capacity, h)

            # combine: gather each token's slot, weight by its gate
            gathered = y[jnp.where(keep, idx_k, 0), jnp.where(keep, pos, 0)]
            out = out + jnp.where(
                keep[:, None], gate_k[:, None] * gathered.astype(jnp.float32), 0.0
            )
        return out.astype(x.dtype), aux
