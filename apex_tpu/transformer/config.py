"""Transformer configuration.

The reference configures its Megatron-style transformer through the 188-flag
argparse namespace (testing/arguments.py:23) plus constructor kwargs threaded
through standalone_transformer_lm.py. Here the whole surface collapses into
one frozen dataclass that is hashable (so flax modules can hold it as a
static attribute) and carries the TPU-specific knobs (compute dtype, mesh
axis names, attention impl) alongside the reference's architectural ones.
"""

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Architecture + parallelism knobs for the Megatron-style stack.

    Field provenance (reference): hidden_size/num_layers/num_attention_heads/
    ffn_hidden_size/kv_channels mirror testing/arguments.py `_add_network_size_args`;
    hidden_dropout/attention_dropout ditto; layernorm_epsilon,
    apply_residual_connection_post_layernorm and fp32_residual_connection come
    from the transformer-layer flags used by standalone_transformer_lm.py.
    """

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    vocab_size: int = 0
    max_position_embeddings: int = 0
    ffn_hidden_size: Optional[int] = None  # defaults to 4*hidden_size
    kv_channels: Optional[int] = None  # defaults to hidden_size // heads
    # GQA (extension; absent in the reference): number of KV heads. None =
    # MHA. Must divide num_attention_heads; with tp>1 must also divide by
    # tp (KV heads are tensor-sharded like Q heads).
    num_query_groups: Optional[int] = None
    # sliding-window attention (extension; mistral-style). None = full
    # causal. Applied only to causal self-attention.
    attention_window: Optional[int] = None

    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layernorm_epsilon: float = 1e-5
    normalization: str = "layernorm"  # "layernorm" | "rmsnorm"
    # Megatron --disable-bias-linear: bias-free attention/MLP projections
    # (llama-family models). LayerNorm/RMSNorm params are unaffected.
    add_bias_linear: bool = True
    activation: str = "gelu"  # "gelu" | "geglu" | "relu" | "swiglu"
    apply_residual_connection_post_layernorm: bool = False
    fp32_residual_connection: bool = False
    apply_query_key_layer_scaling: bool = False
    # NOTE: softmax math is ALWAYS fp32 internally (ops/softmax.py,
    # ops/attention.py) — the reference's attention_softmax_in_fp32 flag has
    # no "off" position on TPU. The attention mask type is a property of the
    # model (GPT=causal, BERT=padding) and is passed to the modules directly.

    position_embedding_type: str = "learned"  # "learned" | "rope" | "none"
    rotary_percent: float = 1.0
    rotary_base: float = 10000.0  # RoPE theta (llama-3 uses 500000)

    # parallelism
    sequence_parallel: bool = False
    tensor_axis: str = "tp"
    # context parallelism (no reference counterpart — SURVEY.md §2.5):
    # shard the sequence over the 'cp' mesh axis inside attention.
    # None | "ring" (ppermute K/V ring) | "ulysses" (all-to-all head swap)
    context_parallel_mode: Optional[str] = None
    context_axis: str = "cp"
    # mixture-of-experts (no reference counterpart — EP extension):
    # num_moe_experts switches the MLP block to MoEMLP; experts shard over
    # moe_expert_axis (None = local experts)
    num_moe_experts: Optional[int] = None
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_expert_axis: Optional[str] = None
    moe_aux_loss_coeff: float = 0.01
    recompute_granularity: Optional[str] = None  # None | "full" | "selective"

    # telemetry (apex_tpu.monitor): sow a per-layer output-RMS tap
    # ("layer_out_rms" under the "intermediates" collection) from every
    # ParallelTransformerLayer. Off by default — readers must pass
    # mutable=["intermediates"] to apply() to collect it.
    collect_layer_metrics: bool = False

    # dtypes: params live in fp32, compute in bf16 by default (TPU-native
    # replacement for the reference's fp16 O2 regime)
    params_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16

    # attention backend: "auto" → Pallas flash attention on TPU
    attention_impl: str = "auto"

    share_embeddings_and_output_weights: bool = True

    def __post_init__(self):
        if self.context_parallel_mode not in (None, "ring", "ulysses"):
            raise ValueError(
                f"context_parallel_mode must be None, 'ring', or 'ulysses'; "
                f"got {self.context_parallel_mode!r}"
            )
        if self.ffn_hidden_size is None:
            object.__setattr__(self, "ffn_hidden_size", 4 * self.hidden_size)
        if self.kv_channels is None:
            assert self.hidden_size % self.num_attention_heads == 0
            object.__setattr__(
                self, "kv_channels", self.hidden_size // self.num_attention_heads
            )
