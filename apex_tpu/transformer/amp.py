"""``apex.transformer.amp`` import-surface alias (reference:
/root/reference/apex/transformer/amp/__init__.py — GradScaler with
found_inf synchronized over the model-parallel axes)."""

from apex_tpu.amp.grad_scaler import GradScaler

__all__ = ["GradScaler"]
