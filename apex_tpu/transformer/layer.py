"""Megatron-style parallel transformer layer, TPU-native.

Reference parity: the ParallelMLP / ParallelAttention / ParallelTransformerLayer
stack in apex/transformer/testing/standalone_transformer_lm.py (the reference's
canonical consumer of its TP/SP primitives), built on:
- ColumnParallelLinear / RowParallelLinear (tensor_parallel/layers.py:460,645)
- FusedScaleMaskSoftmax (functional/fused_softmax.py) → here a Pallas flash
  attention (ops/attention.py) with a fused-softmax fallback for masked paths
- FusedLayerNorm with sequence_parallel flags (transformer/layers/layer_norm.py:33)
- fused RoPE (functional/fused_rope.py) → ops/rope.py
- bias-GeLU fusion (the reference's bias_gelu_impl) → XLA epilogue fusion.

Layout: hidden states are (seq, batch, hidden) exactly like Megatron, so the
sequence-parallel scatter/gather mappings act on dim 0. All residual math can
be forced to fp32 (``fp32_residual_connection``); matmuls accumulate in fp32
on the MXU via ``preferred_element_type``.
"""

import dataclasses
import functools
import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm, rms_norm
from apex_tpu.ops.rope import apply_rotary_pos_emb, rope_frequencies
from apex_tpu.ops.softmax import fused_scale_mask_softmax
from apex_tpu.parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    _tp_size,
)
from apex_tpu.parallel.mappings import copy_to_tensor_model_parallel_region
from apex_tpu.transformer.config import TransformerConfig
from apex_tpu.transformer.enums import AttnMaskType, AttnType


class Norm(nn.Module):
    """LayerNorm/RMSNorm with sequence-parallel gradient synchronization.

    Ref: transformer/layers/layer_norm.py:26-51 marks LN params
    ``sequence_parallel_enabled`` so Megatron allreduces their grads over TP
    after backward — under SP each rank's scale/bias grad is a *partial* sum
    over its sequence shard. The SPMD equivalent is routing the params
    through ``copy_to_tensor_model_parallel_region`` (identity forward,
    psum backward), which makes autodiff emit exactly that allreduce.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = x.shape[-1]
        w = self.param("scale", nn.initializers.ones_init(), (h,), cfg.params_dtype)
        sp = cfg.sequence_parallel and _tp_size(cfg.tensor_axis) > 1
        if sp:
            w = copy_to_tensor_model_parallel_region(w, cfg.tensor_axis)
        if cfg.normalization == "rmsnorm":
            return rms_norm(x, w.astype(jnp.float32), eps=cfg.layernorm_epsilon).astype(
                x.dtype
            )
        b = self.param("bias", nn.initializers.zeros_init(), (h,), cfg.params_dtype)
        if sp:
            b = copy_to_tensor_model_parallel_region(b, cfg.tensor_axis)
        return layer_norm(
            x, w.astype(jnp.float32), b.astype(jnp.float32), eps=cfg.layernorm_epsilon
        ).astype(x.dtype)


def _activate(h, activation: str):
    # computed in h's dtype on purpose: gelu/silu/relu are pointwise and
    # bf16-stable (bf16 shares f32's exponent range, and activation
    # curvature tolerates the shorter mantissa). Upcasting here would
    # materialize the (s, b, ffn) tensor — the widest activation in the
    # network — in f32, doubling its bandwidth and remat footprint for
    # no accuracy return (flagged by apex_tpu.analysis precision pass).
    if activation == "gelu":
        return jax.nn.gelu(h, approximate=True)
    if activation == "relu":
        return jax.nn.relu(h)
    if activation in ("geglu", "swiglu"):
        a, b = jnp.split(h, 2, axis=-1)
        gate = jax.nn.gelu(a, approximate=True) if activation == "geglu" else jax.nn.silu(a)
        return gate * b
    raise ValueError(f"unknown activation {activation!r}")


class ParallelMLP(nn.Module):
    """Column(h→ffn) → activation → Row(ffn→h).

    Ref: ParallelMLP in standalone_transformer_lm.py; the bias+GeLU fusion
    (reference ``bias_gelu_impl`` custom autograd fn) is an XLA epilogue here.
    Gated activations (geglu/swiglu) double the column projection width.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden_states):
        cfg = self.config
        gated = cfg.activation in ("geglu", "swiglu")
        width = cfg.ffn_hidden_size * (2 if gated else 1)
        h = ColumnParallelLinear(
            output_size=width,
            gather_output=False,
            sequence_parallel_enabled=cfg.sequence_parallel,
            axis_name=cfg.tensor_axis,
            params_dtype=cfg.params_dtype,
            use_bias=cfg.add_bias_linear,
            name="dense_h_to_4h",
        )(hidden_states)
        h = _activate(h, cfg.activation)
        return RowParallelLinear(
            output_size=cfg.hidden_size,
            input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            axis_name=cfg.tensor_axis,
            params_dtype=cfg.params_dtype,
            use_bias=cfg.add_bias_linear,
            name="dense_4h_to_h",
        )(h)


class ShardAwareDropout(nn.Module):
    """Dropout whose mask is decorrelated across shards holding different
    slices of the same logical tensor.

    The SPMD analogue of the reference keeping distinct RNG states per
    model-parallel rank (tensor_parallel/random.py:124-236): inside
    shard_map every rank receives the same flax 'dropout' key, so without
    folding in the shard index, sequence chunks (cp) and head shards (tp)
    would draw byte-identical masks.
    """

    rate: float
    axis_names: tuple = ()

    @nn.compact
    def __call__(self, x, deterministic: bool = False):
        if deterministic or self.rate == 0.0:
            return x
        from apex_tpu.parallel.random import shard_aware_rng_key

        key = shard_aware_rng_key(self.make_rng("dropout"), self.axis_names)
        keep = jax.random.bernoulli(key, 1.0 - self.rate, x.shape)
        return jnp.where(keep, x / (1.0 - self.rate), jnp.zeros_like(x))


def _hidden_dropout_axes(cfg) -> tuple:
    """Axes over which hidden-state dropout masks must differ: tp when the
    sequence is SP-sharded, cp when context-parallel."""
    axes = ()
    if cfg.sequence_parallel:
        axes += (cfg.tensor_axis,)
    if cfg.context_parallel_mode is not None:
        axes += (cfg.context_axis,)
    return axes


class CoreAttention(nn.Module):
    """Unfused attention math for masked/dropout paths.

    Ref: CoreAttention in standalone_transformer_lm.py — baddbmm +
    FusedScaleMaskSoftmax + attention dropout + bmm. Used when flash
    attention can't apply (arbitrary padding masks, attention dropout).
    """

    config: TransformerConfig
    attn_mask_type: AttnMaskType

    @nn.compact
    def __call__(self, q, k, v, attention_mask, deterministic: bool = True):
        # q,k,v: (b, np, s, hn)
        cfg = self.config
        norm = 1.0 / math.sqrt(cfg.kv_channels)
        scale = norm
        softmax_scale = 1.0
        if cfg.apply_query_key_layer_scaling:
            # ref: layer-number scaling folded into softmax scale
            coeff = max(1, cfg.num_layers)
            scale = norm / coeff
            softmax_scale = coeff
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k, preferred_element_type=jnp.float32)
        s = s * scale
        causal = self.attn_mask_type == AttnMaskType.causal
        if causal and attention_mask is not None:
            # fold the padding mask into the causal one so the fused causal
            # path still applies (ref: mask_func composition in CoreAttention)
            from apex_tpu.ops.attention import causal_mask

            future = causal_mask(s.shape[-2], s.shape[-1])
            attention_mask = jnp.logical_or(attention_mask, future)
            causal = False
        probs = fused_scale_mask_softmax(
            s, attention_mask, scale=softmax_scale, causal=causal
        )
        if cfg.attention_dropout > 0.0 and not deterministic:
            # heads are tp-sharded: masks must differ per tp rank (the
            # reference forks the model-parallel RNG around attn dropout)
            probs = ShardAwareDropout(
                rate=cfg.attention_dropout, axis_names=(cfg.tensor_axis,)
            )(probs, deterministic=deterministic)
        ctx = jnp.einsum(
            "bnqk,bnkd->bnqd",
            probs.astype(q.dtype),
            v,
            preferred_element_type=jnp.float32,
        )
        return ctx.astype(q.dtype)


class ParallelAttention(nn.Module):
    """TP multi-head attention with flash-attention core.

    Ref: ParallelAttention in standalone_transformer_lm.py — fused QKV
    ColumnParallelLinear (heads sharded over tp), core attention, Row
    output projection. Cross-attention splits q from kv like the
    reference's AttnType.cross_attn branch.
    """

    config: TransformerConfig
    attn_type: AttnType = AttnType.self_attn
    attn_mask_type: AttnMaskType = AttnMaskType.causal

    @nn.compact
    def __call__(
        self,
        hidden_states,
        attention_mask=None,
        encoder_output=None,
        rotary_pos_emb=None,
        key_padding_mask=None,
        deterministic: bool = True,
        cache_len: Optional[int] = None,
        decode_step: bool = False,
    ):
        cfg = self.config
        if decode_step and cfg.sequence_parallel:
            # one decode token cannot be sequence-sharded over tp: the step
            # runs in plain-TP layout (replicated 1-token input/output; SP
            # only moves activations, so every param is identical) while
            # PREFILL keeps full SP — its column linears gather the
            # sequence anyway, so the cache receives full-length K/V
            cfg = dataclasses.replace(cfg, sequence_parallel=False)
        s, b, _ = hidden_states.shape
        tp = _tp_size(cfg.tensor_axis)
        np_local = cfg.num_attention_heads // tp
        hn = cfg.kv_channels

        cache_active = cache_len is not None or decode_step
        if cache_active:
            # KV-cache decoding (extension: the reference has no inference
            # path). Prefill (cache_len=N): normal causal attention over the
            # prompt + rotated K/V written into "cache" variables. Step
            # (decode_step): one new token attends the cache through the
            # flash key-padding fast path. TP shards the cache with the
            # heads. Under SP, decode steps run plain-TP (see above); under
            # CP, each rank caches the positions it computed (prompt shard
            # + round-robin decode slots) and decode merges per-rank
            # partial softmax stats via cp_decode_attention.
            # CONTRACT: at most N - prompt_len decode steps after a
            # cache_len=N prefill. The index is traced, so overstepping
            # cannot raise here — the dynamic updates would clamp and
            # silently rewrite position N-1. models.generate sizes the
            # cache so this cannot happen; direct callers must too.
            if self.attn_type != AttnType.self_attn:
                raise NotImplementedError("KV cache is self-attention only")
            if attention_mask is not None or key_padding_mask is not None:
                raise NotImplementedError("KV-cache decode computes its own "
                                          "masks")
            if decode_step and not self.has_variable("cache", "cached_key"):
                raise ValueError("decode_step before prefill: call once with "
                                 "cache_len=<total length> first")

        groups = cfg.num_query_groups or cfg.num_attention_heads
        if groups != cfg.num_attention_heads and self.attn_type != AttnType.self_attn:
            raise NotImplementedError("GQA is a self-attention feature")
        if cfg.num_attention_heads % groups != 0 or groups % tp != 0:
            raise ValueError(
                f"num_query_groups ({groups}) must divide "
                f"num_attention_heads ({cfg.num_attention_heads}) and be "
                f"divisible by tp ({tp})"
            )
        g_local = groups // tp

        if self.attn_type == AttnType.self_attn and groups != cfg.num_attention_heads:
            # GQA: separate Q and fused-KV projections (llama convention,
            # consecutive grouping — matches ops.flash_attention's
            # q_head // group kv indexing)
            q = ColumnParallelLinear(
                output_size=cfg.num_attention_heads * hn,
                gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                axis_name=cfg.tensor_axis,
                params_dtype=cfg.params_dtype,
                use_bias=cfg.add_bias_linear,
                name="query",
            )(hidden_states)
            kv = ColumnParallelLinear(
                output_size=2 * groups * hn,
                gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                axis_name=cfg.tensor_axis,
                params_dtype=cfg.params_dtype,
                use_bias=cfg.add_bias_linear,
                name="key_value",
            )(hidden_states)
            q = q.reshape(q.shape[0], b, np_local, hn)
            kv = kv.reshape(kv.shape[0], b, g_local, 2 * hn)
            k, v = jnp.split(kv, 2, axis=-1)  # (s, b, g_local, hn)
        elif self.attn_type == AttnType.self_attn:
            qkv = ColumnParallelLinear(
                output_size=3 * cfg.num_attention_heads * hn,
                gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                axis_name=cfg.tensor_axis,
                params_dtype=cfg.params_dtype,
                use_bias=cfg.add_bias_linear,
                name="query_key_value",
            )(hidden_states)
            sq = qkv.shape[0]
            qkv = qkv.reshape(sq, b, np_local, 3 * hn)
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (s, b, np, hn)
        else:
            q = ColumnParallelLinear(
                output_size=cfg.num_attention_heads * hn,
                gather_output=False,
                sequence_parallel_enabled=cfg.sequence_parallel,
                axis_name=cfg.tensor_axis,
                params_dtype=cfg.params_dtype,
                use_bias=cfg.add_bias_linear,
                name="query",
            )(hidden_states)
            kv = ColumnParallelLinear(
                output_size=2 * cfg.num_attention_heads * hn,
                gather_output=False,
                # SP-sharded encoder output must be gathered for K/V too
                # (ref: standalone_transformer_lm.py:412-419)
                sequence_parallel_enabled=cfg.sequence_parallel,
                axis_name=cfg.tensor_axis,
                params_dtype=cfg.params_dtype,
                use_bias=cfg.add_bias_linear,
                name="key_value",
            )(encoder_output)
            q = q.reshape(q.shape[0], b, np_local, hn)
            kv = kv.reshape(kv.shape[0], b, np_local, 2 * hn)
            k, v = jnp.split(kv, 2, axis=-1)

        cp = (
            _tp_size(cfg.context_axis) if cfg.context_parallel_mode is not None else 1
        )

        cache_index = None
        if decode_step:
            cache_index = self.get_variable("cache", "cache_index")

        if rotary_pos_emb is not None:
            q_pos_emb, k_pos_emb = rotary_pos_emb
            if cp > 1 and not decode_step:
                # sequence is cp-sharded: slice this rank's chunk out of the
                # GLOBAL rotary table so positions stay absolute (a decode
                # token's position is global — cache_index — not per-rank)
                def _local_chunk(emb, s_local):
                    if emb.shape[0] == s_local:
                        return emb
                    r = jax.lax.axis_index(cfg.context_axis)
                    return jax.lax.dynamic_slice_in_dim(
                        emb, r * s_local, s_local, 0
                    )

                q_pos_emb = _local_chunk(q_pos_emb, q.shape[0])
                k_pos_emb = _local_chunk(k_pos_emb, k.shape[0])
            if cache_active and q_pos_emb.shape[0] != q.shape[0]:
                # cache mode passes the FULL-length table; this call covers
                # absolute positions [pos0, pos0 + sq).  sq comes from q,
                # not the layer input: under SP the column linear has
                # already gathered the sequence, so q is s_global long
                pos0 = cache_index if decode_step else 0
                q_pos_emb = jax.lax.dynamic_slice_in_dim(
                    q_pos_emb, pos0, q.shape[0], 0)
                k_pos_emb = jax.lax.dynamic_slice_in_dim(
                    k_pos_emb, pos0, k.shape[0], 0)
            q = apply_rotary_pos_emb(q, q_pos_emb)
            k = apply_rotary_pos_emb(k, k_pos_emb)

        # (s, b, np, hn) -> (b, np, s, hn)
        qb = jnp.transpose(q, (1, 2, 0, 3))
        kb = jnp.transpose(k, (1, 2, 0, 3))
        vb = jnp.transpose(v, (1, 2, 0, 3))

        if cache_active:
            h_kv_local = kb.shape[1]
            # Under CP each rank caches ONLY the positions it computed:
            # its contiguous prompt shard in slots [0, prompt_local), then
            # decode tokens round-robin (token t -> rank t % cp, slot
            # prompt_local + t // cp).  Slot -> global-position mapping is
            # reconstructed from (rank, prompt_local) at decode time, so
            # no cross-rank redistribution ever happens.  cache_index
            # stays GLOBAL (identical on all ranks) — rotary tables and
            # validity masks key off absolute positions.
            if cp > 1:
                if cache_len is not None and cache_len % cp:
                    raise ValueError(
                        f"cache_len ({cache_len}) must divide by cp ({cp})"
                    )
                slots = (cache_len or 0) // cp
            else:
                slots = cache_len or 0
            ck = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, h_kv_local, slots, hn), kb.dtype,
            )
            cv = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, h_kv_local, slots, hn), vb.dtype,
            )
            ci = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            if cp > 1:
                pl = self.variable(
                    "cache", "prompt_len_local",
                    lambda: jnp.zeros((), jnp.int32)
                )
            if decode_step:
                if s != 1:
                    raise NotImplementedError(
                        "decode_step appends one token at a time; use a "
                        "prefill call (cache_len=...) for multi-token blocks"
                    )
                idx = cache_index  # global position of this token
                # One slot/mask implementation for both layouts: with
                # cp == 1 the round-robin map degenerates to slot = idx
                # and gpos = j (p_loc = prompt length, r = owner = 0).
                if cp > 1:
                    r = jax.lax.axis_index(cfg.context_axis)
                    p_loc = pl.value
                    d_cnt = idx - p_loc * cp  # decode tokens written so far
                    slot = p_loc + d_cnt // cp
                    write_here = r == d_cnt % cp
                else:
                    slot = idx
                    write_here = None  # every (i.e. the only) rank writes
                new_k = jax.lax.dynamic_update_slice(
                    ck.value, kb.astype(ck.value.dtype), (0, 0, slot, 0)
                )
                new_v = jax.lax.dynamic_update_slice(
                    cv.value, vb.astype(cv.value.dtype), (0, 0, slot, 0)
                )
                if write_here is None:
                    ck.value, cv.value = new_k, new_v
                else:
                    ck.value = jnp.where(write_here, new_k, ck.value)
                    cv.value = jnp.where(write_here, new_v, cv.value)
                ci.value = idx + 1
                j = jnp.arange(ck.value.shape[2])
                gpos = j if cp == 1 else jnp.where(
                    j < p_loc,
                    r * p_loc + j,
                    p_loc * cp + (j - p_loc) * cp + r,
                )
                # pad out the unwritten future; the sliding window
                # additionally drops keys behind the band (mistral decode)
                padded = gpos > idx
                if cfg.attention_window is not None:
                    padded = jnp.logical_or(
                        padded, gpos <= idx - cfg.attention_window
                    )
                padded = jnp.broadcast_to(padded[None, :], (b, j.size))
                if cp > 1:
                    from apex_tpu.parallel.ring_attention import (
                        cp_decode_attention,
                    )

                    ctx = cp_decode_attention(
                        qb, ck.value, cv.value, padded,
                        axis_name=cfg.context_axis,
                    )
                else:
                    ctx = flash_attention(
                        qb, ck.value, cv.value, causal=False,
                        key_padding_mask=padded, impl=cfg.attention_impl,
                    )
            else:
                # prefill: record the (rotated) prompt K/V, then fall
                # through to the normal attention paths below.  kb, not the
                # layer input, carries the cached length: under SP the
                # column linear has gathered the full sequence; under CP
                # this is the rank's contiguous shard (ring/ulysses run on
                # the default non-zigzag layout — zigzag prefill would
                # scatter positions the slot map above can't reconstruct)
                s_kv = kb.shape[2]
                assert s_kv <= slots, (
                    f"prompt ({s_kv}{' per cp rank' if cp > 1 else ''}) "
                    f"exceeds cache ({slots})"
                )
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, kb.astype(ck.value.dtype), (0, 0, 0, 0)
                )
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, vb.astype(cv.value.dtype), (0, 0, 0, 0)
                )
                ci.value = jnp.asarray(s_kv * cp, jnp.int32)
                if cp > 1:
                    pl.value = jnp.asarray(s_kv, jnp.int32)

        causal = self.attn_mask_type == AttnMaskType.causal
        # apply_query_key_layer_scaling cancels exactly (scores*norm/coeff
        # then softmax_scale=coeff) in the always-fp32 softmax, so the flash
        # path with scale=norm is semantically identical — no fallback needed.
        use_flash = attention_mask is None and (
            cfg.attention_dropout == 0.0 or deterministic
        )
        if key_padding_mask is not None and not use_flash:
            # fold the (b, sk) padding row into the dense mask for the
            # unfused CoreAttention path (True = masked out)
            kp = key_padding_mask[:, None, None, :]
            attention_mask = (
                kp if attention_mask is None
                else jnp.logical_or(attention_mask, kp)
            )
            key_padding_mask = None
        if decode_step:
            pass  # ctx computed against the cache above
        elif cp > 1:
            if not use_flash:
                raise NotImplementedError(
                    "context parallelism requires the flash path: no "
                    "dense attention_mask and no attention dropout (like "
                    "the reference's fused paths); GQA and key-padding "
                    "masks are supported"
                )
            from apex_tpu.parallel.ring_attention import (
                ring_attention,
                ulysses_attention,
            )

            # key_padding_mask here is the LOCAL (b, s_local) shard — the
            # layer runs inside shard_map with sequence-sharded inputs, so
            # the mask arrives sharded exactly like the keys it pads
            win = cfg.attention_window if causal else None
            if cfg.context_parallel_mode == "ring":
                ctx = ring_attention(
                    qb, kb, vb, axis_name=cfg.context_axis, causal=causal,
                    window=win, key_padding_mask=key_padding_mask,
                )
            else:
                ctx = ulysses_attention(
                    qb,
                    kb,
                    vb,
                    axis_name=cfg.context_axis,
                    causal=causal,
                    window=win,
                    attn_fn=functools.partial(
                        flash_attention, impl=cfg.attention_impl
                    ),
                    key_padding_mask=key_padding_mask,
                )
        elif use_flash:
            ctx = flash_attention(
                qb, kb, vb, causal=causal, key_padding_mask=key_padding_mask,
                window=cfg.attention_window if causal else None,
                impl=cfg.attention_impl,
            )
        else:
            if kb.shape[1] != qb.shape[1]:  # GQA through the unfused path
                rep = qb.shape[1] // kb.shape[1]
                kb = jnp.repeat(kb, rep, axis=1)
                vb = jnp.repeat(vb, rep, axis=1)
            if cfg.attention_window is not None and causal:
                # fold the band's lower edge into the dense mask; the causal
                # upper edge stays with CoreAttention's own mask handling
                from apex_tpu.ops.attention import window_mask

                below = window_mask(
                    qb.shape[2], kb.shape[2], cfg.attention_window
                )[None, None]
                attention_mask = (
                    below if attention_mask is None
                    else jnp.logical_or(attention_mask, below)
                )
            ctx = CoreAttention(
                config=cfg, attn_mask_type=self.attn_mask_type, name="core_attention"
            )(qb, kb, vb, attention_mask, deterministic=deterministic)

        # (b, np, s, hn) -> (s, b, np*hn)
        ctx = jnp.transpose(ctx, (2, 0, 1, 3)).reshape(ctx.shape[2], b, np_local * hn)
        out = RowParallelLinear(
            output_size=cfg.hidden_size,
            input_is_parallel=True,
            sequence_parallel_enabled=cfg.sequence_parallel,
            axis_name=cfg.tensor_axis,
            params_dtype=cfg.params_dtype,
            use_bias=cfg.add_bias_linear,
            name="dense",
        )(ctx)
        return out


class ParallelTransformerLayer(nn.Module):
    """Pre-LN transformer block (ref: ParallelTransformerLayer in
    standalone_transformer_lm.py): LN → attn → residual → LN → MLP → residual,
    with optional post-LN residual taps and fp32 residual stream."""

    config: TransformerConfig
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    has_cross_attention: bool = False

    @nn.compact
    def __call__(
        self,
        hidden_states,
        attention_mask=None,
        encoder_output=None,
        enc_dec_attn_mask=None,
        rotary_pos_emb=None,
        key_padding_mask=None,
        deterministic: bool = True,
        cache_len: Optional[int] = None,
        decode_step: bool = False,
    ):
        cfg = self.config
        if decode_step and cfg.sequence_parallel:
            # decode steps run plain-TP (see ParallelAttention): a single
            # token cannot be sequence-sharded, so the MLP's column/row
            # linears must not gather/scatter a sequence axis either
            cfg = dataclasses.replace(cfg, sequence_parallel=False)
        rdtype = jnp.float32 if cfg.fp32_residual_connection else hidden_states.dtype
        cache_active = cache_len is not None or decode_step

        ln_out = Norm(config=cfg, name="input_layernorm")(hidden_states)
        attn_cls = ParallelAttention
        if cfg.recompute_granularity == "selective" and not cache_active:
            # recompute only the attention block in backward (ref: Megatron
            # --recompute-granularity selective; core-attention checkpoint).
            # arg 0 is the module scope; ``deterministic`` (arg 6) is static.
            # (decode has no backward — remat would only re-trace the cache
            # mutation, so it is skipped in cache mode)
            attn_cls = nn.remat(
                ParallelAttention, static_argnums=(6,), prevent_cse=False
            )
        attn_out = attn_cls(
            config=cfg,
            attn_type=AttnType.self_attn,
            attn_mask_type=self.attn_mask_type,
            name="self_attention",
        )(
            ln_out,
            attention_mask,
            None,
            rotary_pos_emb,
            key_padding_mask,
            deterministic,
            **(
                {"cache_len": cache_len, "decode_step": decode_step}
                if cache_active
                else {}
            ),
        )
        residual = (
            ln_out if cfg.apply_residual_connection_post_layernorm else hidden_states
        )
        if cfg.hidden_dropout > 0.0 and not deterministic:
            attn_out = ShardAwareDropout(
                rate=cfg.hidden_dropout, axis_names=_hidden_dropout_axes(cfg)
            )(attn_out, deterministic=deterministic)
        h = (residual.astype(rdtype) + attn_out.astype(rdtype)).astype(
            hidden_states.dtype
        )

        if self.has_cross_attention:
            ln_x = Norm(config=cfg, name="post_inter_attention_layernorm_pre")(h)
            x_out = ParallelAttention(
                config=cfg,
                attn_type=AttnType.cross_attn,
                attn_mask_type=AttnMaskType.padding,
                name="inter_attention",
            )(
                ln_x,
                attention_mask=enc_dec_attn_mask,
                encoder_output=encoder_output,
                deterministic=deterministic,
            )
            residual = ln_x if cfg.apply_residual_connection_post_layernorm else h
            h = (residual.astype(rdtype) + x_out.astype(rdtype)).astype(
                hidden_states.dtype
            )

        ln2 = Norm(config=cfg, name="post_attention_layernorm")(h)
        if cfg.num_moe_experts is not None:
            from apex_tpu.transformer.moe import MoEMLP

            s_, b_, h_ = ln2.shape
            mlp_out, moe_aux = MoEMLP(
                config=cfg,
                num_experts=cfg.num_moe_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                expert_axis=cfg.moe_expert_axis,
                name="mlp",
            )(ln2.reshape(s_ * b_, h_))
            mlp_out = mlp_out.reshape(s_, b_, h_)
            # surface the aux loss: readers pull it via
            # mutable=['intermediates'] and add moe_aux_loss_coeff * mean
            self.sow("intermediates", "moe_aux_loss", moe_aux)
        else:
            mlp_out = ParallelMLP(config=cfg, name="mlp")(ln2)
        residual = ln2 if cfg.apply_residual_connection_post_layernorm else h
        if cfg.hidden_dropout > 0.0 and not deterministic:
            mlp_out = ShardAwareDropout(
                rate=cfg.hidden_dropout, axis_names=_hidden_dropout_axes(cfg)
            )(mlp_out, deterministic=deterministic)
        out = (residual.astype(rdtype) + mlp_out.astype(rdtype)).astype(
            hidden_states.dtype
        )
        if cfg.collect_layer_metrics:
            # per-layer activation-scale tap (registered in monitor/taps.py;
            # read via monitor.taps_from_intermediates): fp32 RMS of the
            # block output, the series that localizes a divergence to a
            # depth before it reaches the loss
            self.sow(
                "intermediates",
                "layer_out_rms",
                jnp.sqrt(jnp.mean(jnp.square(out.astype(jnp.float32)))),
            )
        return out


class ParallelTransformer(nn.Module):
    """Stack of layers + final LN, with activation recompute.

    Ref: ParallelTransformer in standalone_transformer_lm.py; activation
    checkpointing (tensor_parallel/random.py:237 CheckpointFunction) maps to
    ``jax.checkpoint`` (``nn.remat``) around each layer when
    ``recompute_granularity == "full"``. ``num_layers`` here is the LOCAL
    stage depth — pipeline stages instantiate their own slice (ref:
    build_model virtual chunks, schedules/common.py:30).
    """

    config: TransformerConfig
    num_layers: Optional[int] = None
    post_layer_norm: bool = True
    attn_mask_type: AttnMaskType = AttnMaskType.causal

    @nn.compact
    def __call__(
        self,
        hidden_states,
        attention_mask=None,
        rotary_pos_emb=None,
        key_padding_mask=None,
        deterministic: bool = True,
        cache_len: Optional[int] = None,
        decode_step: bool = False,
    ):
        cfg = self.config
        n = self.num_layers if self.num_layers is not None else cfg.num_layers
        cache_active = cache_len is not None or decode_step
        layer_cls = ParallelTransformerLayer
        if cfg.recompute_granularity == "full" and not cache_active:
            # arg 0 is the module scope; ``deterministic`` (arg 7) is static
            # (no backward in decode — see ParallelTransformerLayer)
            layer_cls = nn.remat(
                ParallelTransformerLayer,
                static_argnums=(7,),
                prevent_cse=False,
            )
        for i in range(n):
            hidden_states = layer_cls(
                config=cfg, attn_mask_type=self.attn_mask_type, name=f"layer_{i}"
            )(
                hidden_states,
                attention_mask,
                None,
                None,
                rotary_pos_emb,
                key_padding_mask,
                deterministic,
                **(
                    {"cache_len": cache_len, "decode_step": decode_step}
                    if cache_active
                    else {}
                ),
            )
        if self.post_layer_norm:
            hidden_states = Norm(config=cfg, name="final_layernorm")(hidden_states)
        return hidden_states


def rotary_embedding_for(config: TransformerConfig, seq_len: int, dtype=jnp.float32):
    """Precompute (q_freqs, k_freqs) for ParallelAttention's rotary path."""
    rot_dim = int(config.kv_channels * config.rotary_percent)
    f = rope_frequencies(rot_dim, seq_len, base=config.rotary_base, dtype=dtype)
    return f, f
