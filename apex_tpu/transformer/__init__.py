"""Megatron-style transformer building blocks (ref: apex/transformer)."""

from apex_tpu.transformer.config import TransformerConfig
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType
from apex_tpu.transformer.layer import (
    CoreAttention,
    Norm,
    ParallelAttention,
    ParallelMLP,
    ParallelTransformer,
    ParallelTransformerLayer,
    rotary_embedding_for,
)

__all__ = [
    "AttnMaskType",
    "AttnType",
    "LayerType",
    "ModelType",
    "TransformerConfig",
    "CoreAttention",
    "Norm",
    "ParallelAttention",
    "ParallelMLP",
    "ParallelTransformer",
    "ParallelTransformerLayer",
    "rotary_embedding_for",
]
