"""Megatron-style transformer building blocks (ref: apex/transformer).

The reference's submodule namespace (its __init__.py re-exports amp,
functional, parallel_state, pipeline_parallel, tensor_parallel, utils) is
reproduced so Megatron-style imports migrate by substituting the package
root; implementations live in apex_tpu.parallel / apex_tpu.ops.
"""

from apex_tpu.parallel import parallel_state
from apex_tpu.transformer import amp, functional, pipeline_parallel, tensor_parallel
from apex_tpu.transformer import utils
from apex_tpu.transformer.config import TransformerConfig
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType
from apex_tpu.transformer.layer import (
    CoreAttention,
    Norm,
    ParallelAttention,
    ParallelMLP,
    ParallelTransformer,
    ParallelTransformerLayer,
    rotary_embedding_for,
)
from apex_tpu.transformer.moe import MoEMLP
from apex_tpu.transformer.utils import (
    average_losses_across_data_parallel_group,
    calc_params_l2_norm,
    get_ltor_masks_and_position_ids,
    print_params_min_max_norm,
    report_memory,
)

__all__ = [
    "amp",
    "functional",
    "parallel_state",
    "pipeline_parallel",
    "tensor_parallel",
    "utils",
    "MoEMLP",
    "average_losses_across_data_parallel_group",
    "calc_params_l2_norm",
    "get_ltor_masks_and_position_ids",
    "print_params_min_max_norm",
    "report_memory",
    "AttnMaskType",
    "AttnType",
    "LayerType",
    "ModelType",
    "TransformerConfig",
    "CoreAttention",
    "Norm",
    "ParallelAttention",
    "ParallelMLP",
    "ParallelTransformer",
    "ParallelTransformerLayer",
    "rotary_embedding_for",
]
