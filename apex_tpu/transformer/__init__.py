"""Megatron-style transformer building blocks (ref: apex/transformer)."""

from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType

__all__ = ["AttnMaskType", "AttnType", "LayerType", "ModelType"]
