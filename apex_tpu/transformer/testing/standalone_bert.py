"""Standalone BERT pretraining driven by the Megatron argument system.

Reference parity: apex/transformer/testing/standalone_bert.py (the
runnable BERT its pipeline tests launch). Uses apex_tpu.models.BertModel
(LM head + optional NSP binary head) over a dp x tp mesh with the
no-pipelining gradient-accumulation schedule — the configuration the
reference's bert_model_provider exercises most; pipelined BERT follows the
GPT layout (standalone_gpt.py) if needed.

Run (virtual CPU mesh):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m apex_tpu.transformer.testing.standalone_bert \
        --num-layers 2 --hidden-size 64 --num-attention-heads 4 \
        --seq-length 32 --max-position-embeddings 32 \
        --micro-batch-size 2 --global-batch-size 8 \
        --tensor-model-parallel-size 2 --train-iters 3
"""

import functools

import jax
import jax.numpy as jnp
import optax
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.bert import BertModel
from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.ddp import all_reduce_gradients
from apex_tpu.parallel.pipeline import forward_backward_no_pipelining
from apex_tpu.transformer.testing import global_vars
from apex_tpu.transformer.testing.arguments import parse_args
from apex_tpu.transformer.testing.standalone_gpt import gpt_config_from_args


def run_bert(args=None, log=print):
    if args is None:
        args = global_vars.get_args()
    if args.pipeline_model_parallel_size > 1:
        raise NotImplementedError(
            "standalone_bert covers the dp x tp configuration; pipelined "
            "runs follow standalone_gpt's layout"
        )
    tp = args.tensor_model_parallel_size
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp
    )
    dp = parallel_state.get_data_parallel_world_size()
    cfg = gpt_config_from_args(args)
    model = BertModel(config=cfg, add_binary_head=args.bert_binary_head)

    seq, mb = args.seq_length, args.micro_batch_size
    num_micro = max(1, args.global_batch_size // (mb * dp))
    steps = args.train_iters or 3
    key = jax.random.PRNGKey(args.seed)
    tokens = jax.random.randint(
        key, (steps, num_micro, mb * dp, seq), 0, cfg.vocab_size
    )
    labels = jax.random.randint(
        jax.random.fold_in(key, 1), (steps, num_micro, mb * dp, seq), 0,
        cfg.vocab_size,
    )

    opt = fused_adam(lr=args.lr or 1e-3, betas=(args.adam_beta1, args.adam_beta2),
                     eps=args.adam_eps, weight_decay=args.weight_decay)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, None, "dp"), P(None, None, "dp")),
        out_specs=P(),
        check_vma=False,
    )
    def train(tokens, labels):
        params = model.init(
            jax.random.PRNGKey(args.seed), tokens[0, 0], lm_labels=labels[0, 0]
        )["params"]
        opt_state = opt.init(params)

        def fwd(p, batch):
            toks, labs = batch
            lm_loss, _ = model.apply({"params": p}, toks, lm_labels=labs)
            return jnp.mean(lm_loss)

        def one_step(carry, batch):
            params, opt_state = carry
            loss, _, grads = forward_backward_no_pipelining(
                fwd, params, batch,
                grad_sync_fn=lambda g: all_reduce_gradients(g, axis_name="dp"),
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), xlax.pmean(loss, "dp")

        _, losses = jax.lax.scan(one_step, (params, opt_state), (tokens, labels))
        return losses

    losses = jax.device_get(train(tokens, labels))
    for i, l in enumerate(losses):
        log(f"iteration {i:4d} | lm loss {float(l):.4f}")
    parallel_state.destroy_model_parallel()
    return [float(l) for l in losses]


def main(argv=None):
    args = parse_args(args=argv)
    return run_bert(args)


if __name__ == "__main__":
    main()
