"""Megatron-style testing harness (args + globals + helpers).

Reference parity: apex/transformer/testing — the argument system its
standalone LM scripts and L0 transformer tests build on. The standalone
training-script role is filled by examples/gpt_pretrain.py and
examples/imagenet (see README component map).
"""

from apex_tpu.transformer.testing.arguments import (
    parse_args,
    transformer_config_from_args,
    validate_args,
)
from apex_tpu.transformer.testing.commons import (
    IdentityLayer,
    TEST_SUCCESS_MESSAGE,
    initialize_distributed,
    model_provider_func,
    print_separator,
    set_random_seed,
)
from apex_tpu.transformer.testing.global_vars import (
    destroy_global_variables,
    get_args,
    get_current_global_batch_size,
    get_num_microbatches,
    get_tensorboard_writer,
    get_timers,
    set_global_variables,
    update_num_microbatches,
)

__all__ = [
    "parse_args",
    "validate_args",
    "transformer_config_from_args",
    "set_random_seed",
    "initialize_distributed",
    "print_separator",
    "model_provider_func",
    "IdentityLayer",
    "TEST_SUCCESS_MESSAGE",
    "get_args",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "get_tensorboard_writer",
    "get_timers",
    "set_global_variables",
    "destroy_global_variables",
]
