"""Test-harness helpers.

Reference parity: apex/transformer/testing/commons.py — the shared pieces
its L0 transformer tests import: ``set_random_seed`` (:242),
``initialize_distributed`` (:250, torch.distributed init → here the mesh
init), toy pipeline model providers (:45-230), ``print_separator`` (:291)
and the success banner (distributed_test_base.py's
TEST_SUCCESS_MESSAGE).
"""

import random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.parallel import parallel_state
from apex_tpu.transformer.testing import global_vars

TEST_SUCCESS_MESSAGE = ">> passed the test :-)"


def set_random_seed(seed: int):
    """Seed every host RNG and return the jax PRNG key (ref commons.py:242
    seeds python/numpy/torch/model-parallel-cuda; jax's functional PRNG
    replaces the last two — fold the tp rank in where per-rank streams are
    needed, parallel/random.py)."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def initialize_distributed(backend: str = "xla"):
    """Mesh-based analogue of torch.distributed init (ref commons.py:250).

    Accepts the reference's backend names for call-site compatibility;
    everything maps to one jax device mesh. Parallel sizes come from the
    global args when set (the reference reads RANK/WORLD_SIZE env)."""
    if backend not in ("nccl", "ucc", "gloo", "xla"):
        raise RuntimeError(f"unknown backend {backend}")
    try:
        args = global_vars.get_args()
        tp = args.tensor_model_parallel_size
        pp = args.pipeline_model_parallel_size
        vpp = args.virtual_pipeline_model_parallel_size
    except AssertionError:  # args not initialized: single-axis dp mesh
        tp = pp = 1
        vpp = None
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        virtual_pipeline_model_parallel_size=vpp,
    )


def print_separator(message: str):
    filler_len = (78 - len(message)) // 2
    filler = "-" * filler_len
    print("\n" + filler + f" {message} " + filler, flush=True)


# -- toy pipeline models (ref commons.py:45-230) ---------------------------

def mlp_provider_func(hidden_size: int = 16):
    """Toy per-stage MLP for pipeline tests (ref MyLayer/MyModel :45-82):
    returns (params_init_fn, stage_fn) usable with the compiled schedules."""

    def init_fn(key):
        k1, k2 = jax.random.split(key)
        return {
            "w": jax.random.normal(k1, (hidden_size, hidden_size)) * 0.1,
            "b": jnp.zeros((hidden_size,)),
        }

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    return init_fn, stage_fn


def model_provider_func(hidden_size: int, pre_process: bool,
                        post_process: bool):
    """Ref commons.py:155-163 signature: builds one pipeline chunk with
    pre/post flags — used with schedules.build_model."""
    init_fn, stage_fn = mlp_provider_func(hidden_size)
    return {
        "init_fn": init_fn,
        "stage_fn": stage_fn,
        "pre_process": pre_process,
        "post_process": post_process,
    }


class IdentityLayer:
    """Ref commons.py:234-239: a trainable scaled-identity used by the
    cross-entropy and grad tests."""

    def __init__(self, size, scale: float = 1.0, key: Optional[jax.Array] = None):
        key = key if key is not None else jax.random.PRNGKey(0)
        self.weight = scale * jax.random.normal(key, size)

    def __call__(self):
        return self.weight

    forward = __call__
