"""Megatron-style global variables.

Reference parity: apex/transformer/testing/global_vars.py:26-200 — the
process-global (args, microbatch calculator, tensorboard writer, timers)
registry with initialize-once semantics. The torch.distributed rank checks
become no-ops in SPMD (one process), and the timers are
apex_tpu.utils.Timers (jax.profiler-annotated) instead of CUDA-event
timers.
"""

from apex_tpu.parallel.pipeline.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.testing.arguments import parse_args
from apex_tpu.utils.timers import Timers

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_ADLR_AUTORESUME = None
_GLOBAL_TIMERS = None


def get_args():
    """Return arguments."""
    _ensure_var_is_initialized(_GLOBAL_ARGS, "args")
    return _GLOBAL_ARGS


def get_num_microbatches() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    """Update the number of microbatches from consumed samples (no effect
    unless rampup_batch_size is set; ref global_vars.py:48-60)."""
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(
        consumed_samples, consistency_check
    )


def get_tensorboard_writer():
    """Can be None; no initialization check (ref :69)."""
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    """Can be None; no initialization check (ref :75)."""
    return _GLOBAL_ADLR_AUTORESUME


def get_timers():
    _ensure_var_is_initialized(_GLOBAL_TIMERS, "timers")
    return _GLOBAL_TIMERS


def set_global_variables(extra_args_provider=None, args_defaults={},
                         override_args={}, ignore_unknown_args=False,
                         args=None):
    """Set args, microbatch calculator, tensorboard writer, and timers."""
    parsed = _parse_args(
        extra_args_provider=extra_args_provider,
        defaults=args_defaults,
        override_args=override_args,
        ignore_unknown_args=ignore_unknown_args,
        args=args,
    )
    _build_num_microbatches_calculator(parsed)
    _set_tensorboard_writer(parsed)
    _set_timers()
    return parsed


def destroy_global_variables():
    """Reset every global (tests re-initialize per case; the reference
    leaks these across a process, which its spawn-per-test model hides)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    global _GLOBAL_TENSORBOARD_WRITER, _GLOBAL_ADLR_AUTORESUME, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TENSORBOARD_WRITER = None
    _GLOBAL_ADLR_AUTORESUME = None
    _GLOBAL_TIMERS = None


def _parse_args(extra_args_provider=None, defaults={}, override_args={},
                ignore_unknown_args=False, args=None):
    global _GLOBAL_ARGS
    _ensure_var_is_not_initialized(_GLOBAL_ARGS, "args")
    _GLOBAL_ARGS = parse_args(
        extra_args_provider=extra_args_provider,
        defaults=defaults,
        override_args=override_args,
        ignore_unknown_args=ignore_unknown_args,
        args=args,
    )
    return _GLOBAL_ARGS


def _build_num_microbatches_calculator(args):
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _ensure_var_is_not_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    )
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank=args.rank,
        rampup_batch_size=args.rampup_batch_size,
        global_batch_size=args.global_batch_size,
        micro_batch_size=args.micro_batch_size,
        data_parallel_size=args.data_parallel_size,
    )


def _set_tensorboard_writer(args):
    global _GLOBAL_TENSORBOARD_WRITER
    _ensure_var_is_not_initialized(
        _GLOBAL_TENSORBOARD_WRITER, "tensorboard writer"
    )
    if getattr(args, "tensorboard_dir", None) and args.rank == (
        args.world_size - 1
    ):
        try:
            from torch.utils.tensorboard import SummaryWriter

            _GLOBAL_TENSORBOARD_WRITER = SummaryWriter(
                log_dir=args.tensorboard_dir,
                max_queue=args.tensorboard_queue_size,
            )
        except ModuleNotFoundError:
            pass  # ref prints "no tensorboard, skipping" (:149-156)


def _set_timers():
    global _GLOBAL_TIMERS
    _ensure_var_is_not_initialized(_GLOBAL_TIMERS, "timers")
    _GLOBAL_TIMERS = Timers()


def _ensure_var_is_initialized(var, name):
    assert var is not None, f"{name} is not initialized."


def _ensure_var_is_not_initialized(var, name):
    assert var is None, f"{name} is already initialized."
