"""Standalone GPT pretraining driven by the Megatron argument system.

Reference parity: apex/transformer/testing/standalone_gpt.py (the runnable
GPT its pipeline tests launch) on top of standalone_transformer_lm.py. Here
the model stack is apex_tpu.models (Embedding + ParallelTransformer + head)
and the schedule comes from ``get_forward_backward_func`` exactly like the
reference's test driver: no-pipelining for pp=1, the compiled 1F1B /
interleaved scans otherwise.

Run (virtual CPU mesh):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m apex_tpu.transformer.testing.standalone_gpt \
        --num-layers 4 --hidden-size 64 --num-attention-heads 4 \
        --seq-length 32 --max-position-embeddings 32 \
        --micro-batch-size 2 --global-batch-size 8 \
        --pipeline-model-parallel-size 2 --tensor-model-parallel-size 2 \
        --train-iters 3
"""

import functools

import jax
import jax.numpy as jnp
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt_pipeline import build_gpt_pipeline
from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.ddp import all_reduce_gradients
from apex_tpu.parallel.pipeline import forward_backward_with_pre_post
from apex_tpu.transformer import TransformerConfig
from apex_tpu.transformer.testing import global_vars
from apex_tpu.transformer.testing.arguments import parse_args


def gpt_config_from_args(args) -> TransformerConfig:
    """The reference's gpt_model_provider reads get_args() field by field
    (standalone_gpt.py:33-45); the shared mapping lives in
    arguments.transformer_config_from_args — only the determinism knobs
    differ (the ref tests run dropout-free)."""
    import dataclasses

    from apex_tpu.transformer.testing.arguments import (
        transformer_config_from_args,
    )

    return dataclasses.replace(
        transformer_config_from_args(args),
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )


def _make_router(args):
    """Telemetry sinks from the Megatron argument surface: jsonl via
    ``--metrics-jsonl``, TensorBoard via ``--tensorboard-dir`` (gated on a
    writer being importable), one shared record schema with the other
    producers (apex_tpu.monitor, docs/observability.md). None when no
    sink is requested."""
    from apex_tpu import monitor

    sinks = []
    if getattr(args, "metrics_jsonl", None):
        sinks.append(monitor.JsonlSink(args.metrics_jsonl))
    if getattr(args, "tensorboard_dir", None):
        tb = monitor.try_tensorboard_sink(args.tensorboard_dir)
        if tb is not None:
            sinks.append(tb)
    return monitor.MetricRouter(sinks) if sinks else None


def run_gpt(args=None, log=print):
    """Build mesh + model from args, train ``--train-iters`` steps, return
    the per-step loss list (every loss is the dp/pp-published global mean)."""
    if args is None:
        args = global_vars.get_args()
    tp = args.tensor_model_parallel_size
    pp = args.pipeline_model_parallel_size
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        virtual_pipeline_model_parallel_size=(
            args.virtual_pipeline_model_parallel_size
        ),
    )
    dp = parallel_state.get_data_parallel_world_size()
    cfg = gpt_config_from_args(args)

    seq = args.seq_length
    mb = args.micro_batch_size
    num_micro = args.global_batch_size // (mb * dp)
    if num_micro < 1:
        raise ValueError("global batch too small for micro batch x dp")
    if pp > 1 and num_micro % pp != 0:
        # interleaved/1F1B scans want M % P == 0 for the interleaved case;
        # round up like the reference pads its last batch
        num_micro = -(-num_micro // pp) * pp

    parts = build_gpt_pipeline(cfg, pp)
    key = jax.random.PRNGKey(args.seed)
    steps = args.train_iters or 3
    tokens = jax.random.randint(
        key, (steps, num_micro, mb * dp, seq), 0, cfg.vocab_size
    )
    labels = jnp.roll(tokens, -1, axis=3)

    opt = fused_adam(lr=args.lr or 1e-3, betas=(args.adam_beta1, args.adam_beta2),
                     eps=args.adam_eps, weight_decay=args.weight_decay)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, None, "dp"), P(None, None, "dp")),
        out_specs=P(),
        check_vma=False,
    )
    def train(tokens, labels):
        # muted: this init block runs ONCE PER RUN, not once per step —
        # its collectives (the vocab-parallel embedding's psum, the
        # stage-init forward's RowParallel psums) must not inflate the
        # ledger's per-step comms totals
        with xlax.muted():
            init_key = jax.random.PRNGKey(args.seed)
            pre = parts.embed.init(init_key, tokens[0, 0])["params"]
            h0 = parts.pre_fn(pre, tokens[0, 0])
            r = jax.lax.axis_index("pp")
            stage = parts.chunk.init(
                jax.random.fold_in(jax.random.fold_in(init_key, 7), r), h0
            )["params"]
            params = {
                "pre": pre,
                "stages": stage,
                "post": parts.init_post(jax.random.fold_in(init_key, 9)),
            }
            opt_state = opt.init(params)

        def one_step(carry, batch):
            params, opt_state = carry
            toks, labs = batch
            loss, _, grads = forward_backward_with_pre_post(
                parts.pre_fn, parts.stage_fn, parts.post_loss_fn, params,
                toks, labs, axis_name="pp",
                grad_sync_fn=lambda g: all_reduce_gradients(g, axis_name="dp"),
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            # under SP the post loss is tp-local (pre-divided by tp in
            # post_loss_fn) so psum completes the token mean; without SP
            # the loss is already tp-replicated and a psum would scale by tp
            if cfg.sequence_parallel and tp > 1:
                loss = xlax.psum(loss, "tp")
            loss = xlax.pmean(loss, "dp")
            return (params, opt_state), loss

        _, losses = jax.lax.scan(one_step, (params, opt_state), (tokens, labels))
        return losses

    router = _make_router(args)

    # X-ray startup banner (docs/observability.md): static introspection
    # of the compiled run BEFORE it executes — per-step comms volume from
    # a ledger trace (the whole run is one scan over steps, so the traced
    # step body IS one step's traffic; the once-per-run init block is
    # muted), and XLA's memory breakdown (NOTE: one extra compile — on
    # jax 0.4.x the AOT compile does not share the jit dispatch cache,
    # see xray.memory_report). Records join the same jsonl stream as
    # metrics when a sink is configured.
    if getattr(args, "xray_comms", False):
        from apex_tpu.monitor import xray

        led = xray.predict_comms(train, tokens, labels)
        log(led.summary())
        if router is not None:
            for rec in led.to_records(step=0):
                router.emit(rec)
    if getattr(args, "xray_report", False):
        from apex_tpu.monitor import xray

        report = xray.memory_report(train, tokens, labels)
        log(report.format())
        if router is not None:
            router.event("memory", 0, **report.fields())

    import time

    from apex_tpu.utils.timers import step_annotation

    t0 = time.perf_counter()
    # the whole run is ONE compiled scan, so per-step markers are
    # impossible; the single annotation still makes any profiler window
    # over this run segmentable (as one span covering all steps) by the
    # timeline analyzer (apex_tpu.monitor.xray.timeline) instead of
    # marker-less noise
    with step_annotation(0, name="train_scan"):
        losses = jax.device_get(train(tokens, labels))  # ONE fetch, all steps
    elapsed = max(time.perf_counter() - t0, 1e-9)
    for i, l in enumerate(losses):
        log(f"iteration {i:4d} | lm loss {float(l):.4f}")

    if router is not None:
        from apex_tpu import monitor

        interval = max(1, args.log_interval or 1)
        for i, l in enumerate(losses):
            if i % interval == 0 or i == len(losses) - 1:
                router.metrics(i, loss=float(l))
        # the whole run is ONE jitted scan, so per-step device time is not
        # separable here; the throughput record is honest about covering
        # compile + relay dispatch + all steps (slope-based per-step
        # timing lives in utils/benchmarking.py)
        # num_micro may be rounded UP to a pp multiple above — count the
        # tokens the scan actually processed, not the nominal global batch
        tokens_per_step = num_micro * mb * dp * seq
        sec_per_step = elapsed / max(1, steps)
        router.event(
            "throughput", steps - 1,
            tokens_per_s=monitor.tokens_per_second(
                tokens_per_step * steps, elapsed
            ),
            mfu=monitor.mfu(
                monitor.training_flops_per_step(
                    monitor.gpt_flops_per_token(cfg, seq), tokens_per_step
                ),
                sec_per_step,
                num_devices=len(jax.devices()),
            ),
            wall_s=elapsed,
        )
        router.close()
    parallel_state.destroy_model_parallel()
    return [float(l) for l in losses]


def main(argv=None):
    args = parse_args(args=argv)
    return run_gpt(args)


if __name__ == "__main__":
    main()
