"""Megatron-style argument system.

Reference parity: apex/transformer/testing/arguments.py:23 (parse_args over
14 argument groups, 188 flags, plus the post-parse derivation/validation
block :60-320). Flag names, groups, defaults, and the derivation rules are
kept identical so reference launch commands work verbatim; the handful of
CUDA-only knobs (DDP impl, contiguous buffers, NCCL backend) are accepted
for compatibility and recorded on the namespace, where the TPU runtime
simply has no use for them (XLA owns those decisions).

TPU adaptations in the derivation block:
- ``world_size`` comes from ``jax.device_count()`` when no WORLD_SIZE env
  is present (SPMD: one process sees all chips);
- ``params_dtype`` is a jnp dtype (fp16/bf16 flags map like the reference);
- ``checkpoint_activations``/``recompute_*`` map onto the ``remat`` knobs
  of the compiled schedules (schedules.py) rather than torch checkpointing.
"""

import argparse
import os

import jax.numpy as jnp


def parse_args(extra_args_provider=None, defaults={}, override_args={},
               ignore_unknown_args=False, args=None):
    """Parse all arguments (ref arguments.py:23-120).

    ``args``: optional explicit argv list (the reference reads sys.argv;
    tests pass lists).
    """
    parser = argparse.ArgumentParser(description="Megatron-LM Arguments",
                                     allow_abbrev=False)

    parser = _add_network_size_args(parser)
    parser = _add_regularization_args(parser)
    parser = _add_training_args(parser)
    parser = _add_initialization_args(parser)
    parser = _add_learning_rate_args(parser)
    parser = _add_checkpointing_args(parser)
    parser = _add_mixed_precision_args(parser)
    parser = _add_distributed_args(parser)
    parser = _add_validation_args(parser)
    parser = _add_data_args(parser)
    parser = _add_autoresume_args(parser)
    parser = _add_biencoder_args(parser)
    parser = _add_vision_args(parser)
    parser = _add_logging_args(parser)
    parser.add_argument("--cpu-offload", action="store_true", default=False,
                        help="Turns on CPU offloading")

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    # apply defaults that were not explicitly set on the command line
    for key, value in defaults.items():
        if getattr(parsed, key, None) is None:
            setattr(parsed, key, value)

    return validate_args(parsed, override_args)


def validate_args(args, override_args={}):
    """The reference's post-parse derivation block (arguments.py:60-320)."""
    args.rank = int(os.getenv("RANK", "0"))
    world = os.getenv("WORLD_SIZE")
    if world is not None:
        args.world_size = int(world)
    else:
        try:
            import jax

            args.world_size = jax.device_count()
        except Exception:  # backend not initialized / unavailable
            args.world_size = 1

    for key in override_args:
        setattr(args, key, override_args[key])

    # tensor/pipeline sizes clamp to the world like the reference
    args.tensor_model_parallel_size = min(
        args.tensor_model_parallel_size, args.world_size
    )
    assert args.world_size % args.tensor_model_parallel_size == 0, (
        f"world size ({args.world_size}) is not divisible by tensor model "
        f"parallel size ({args.tensor_model_parallel_size})"
    )
    args.pipeline_model_parallel_size = min(
        args.pipeline_model_parallel_size,
        args.world_size // args.tensor_model_parallel_size,
    )
    args.transformer_pipeline_model_parallel_size = (
        args.pipeline_model_parallel_size - 1
        if args.standalone_embedding_stage
        else args.pipeline_model_parallel_size
    )
    model_parallel_size = (
        args.pipeline_model_parallel_size * args.tensor_model_parallel_size
    )
    assert args.world_size % model_parallel_size == 0, (
        f"world size ({args.world_size}) is not divisible by tensor "
        f"({args.tensor_model_parallel_size}) times pipeline "
        f"({args.pipeline_model_parallel_size}) parallel sizes"
    )
    args.data_parallel_size = args.world_size // model_parallel_size
    if args.pipeline_model_parallel_size > 1:
        if args.pipeline_model_parallel_split_rank is not None:
            assert (
                args.pipeline_model_parallel_split_rank
                < args.pipeline_model_parallel_size
            ), "split rank needs to be less than pipeline model parallel size"

    # deprecated arguments (ref :104-118)
    assert args.batch_size is None, (
        "--batch-size argument is no longer valid, use --micro-batch-size"
    )
    del args.batch_size
    assert args.warmup is None, (
        "--warmup argument is no longer valid, use --lr-warmup-fraction"
    )
    del args.warmup
    assert args.model_parallel_size is None, (
        "--model-parallel-size is no longer valid, "
        "use --tensor-model-parallel-size"
    )
    del args.model_parallel_size

    # recompute knobs (ref :119-127); full/uniform == schedules remat=True
    if args.checkpoint_activations:
        args.recompute_granularity = "full"
        args.recompute_method = "uniform"
    del args.checkpoint_activations
    if args.recompute_activations:
        args.recompute_granularity = "selective"
    del args.recompute_activations

    # batch sizes (ref :143-151)
    assert args.micro_batch_size is not None
    assert args.micro_batch_size > 0
    if args.global_batch_size is None:
        args.global_batch_size = args.micro_batch_size * args.data_parallel_size
    assert args.global_batch_size > 0

    # virtual pipeline (ref :152-162)
    if args.num_layers_per_virtual_pipeline_stage is not None:
        assert args.pipeline_model_parallel_size > 2, (
            "pipeline-model-parallel size should be greater than 2 with "
            "interleaved schedule"
        )
        assert (
            args.num_layers % args.num_layers_per_virtual_pipeline_stage == 0
        ), "number of layers is not divisible by number of layers per virtual pipeline stage"
        args.virtual_pipeline_model_parallel_size = (
            args.num_layers // args.pipeline_model_parallel_size
        ) // args.num_layers_per_virtual_pipeline_stage
    else:
        args.virtual_pipeline_model_parallel_size = None

    # params dtype (ref :165-180); bf16 is the TPU-native half
    args.params_dtype = jnp.float32
    if args.fp16:
        assert not args.bf16
        args.params_dtype = jnp.float16
    if args.bf16:
        assert not args.fp16
        args.params_dtype = jnp.bfloat16
        if not args.accumulate_allreduce_grads_in_fp32:
            args.accumulate_allreduce_grads_in_fp32 = True

    if args.dataloader_type is None:
        args.dataloader_type = "single"
    args.consumed_train_samples = 0
    args.consumed_valid_samples = 0

    # iteration-based vs sample-based training (ref :205-235)
    if args.train_iters:
        assert args.train_samples is None, (
            "expected iteration-based training"
        )
        assert args.lr_decay_samples is None, (
            "expected iteration-based learning rate decay"
        )
        assert args.lr_warmup_samples == 0, (
            "expected iteration-based learning rate warmup"
        )
        assert args.rampup_batch_size is None, (
            "expected no batch-size rampup for iteration-based training"
        )
        if args.lr_warmup_fraction is not None:
            assert args.lr_warmup_iters == 0, (
                "can only specify one of lr-warmup-fraction and lr-warmup-iters"
            )
    if args.train_samples:
        assert args.train_iters is None, "expected sample-based training"
        assert args.lr_decay_iters is None, (
            "expected sample-based learning rate decay"
        )
        assert args.lr_warmup_iters == 0, (
            "expected sample-based learning rate warmup"
        )
        if args.lr_warmup_fraction is not None:
            assert args.lr_warmup_samples == 0, (
                "can only specify one of lr-warmup-fraction and lr-warmup-samples"
            )

    # consistency checks (ref :240-280)
    if args.ffn_hidden_size is None:
        args.ffn_hidden_size = 4 * args.hidden_size
    if args.kv_channels is None:
        assert args.hidden_size % args.num_attention_heads == 0
        args.kv_channels = args.hidden_size // args.num_attention_heads
    if args.seq_length is not None:
        assert args.encoder_seq_length is None
        args.encoder_seq_length = args.seq_length
    else:
        assert args.encoder_seq_length is not None
        args.seq_length = args.encoder_seq_length
    if args.seq_length is not None and args.max_position_embeddings is not None:
        assert args.max_position_embeddings >= args.seq_length
    if args.decoder_seq_length is not None and args.max_position_embeddings is not None:
        assert args.max_position_embeddings >= args.decoder_seq_length
    if args.lr is not None and args.min_lr is not None:
        assert args.min_lr <= args.lr
    if args.save is not None and args.save_interval is not None:
        assert args.save_interval > 0
    if args.fp32_residual_connection:
        assert args.fp16 or args.bf16, (
            "residual connection in fp32 only supported when using fp16 or bf16"
        )
    if args.recompute_granularity == "selective":
        assert args.recompute_method is None, (
            "recompute method is not yet supported for selective recomputing granularity"
        )

    # sequence parallelism needs tensor parallelism (ref :300-310)
    if args.sequence_parallel:
        assert args.tensor_model_parallel_size > 1, (
            "sequence parallelism requires tensor parallelism"
        )

    return args


def transformer_config_from_args(args):
    """Map a parsed namespace onto ``TransformerConfig`` (the reference's
    tests thread args into their transformer layers field by field)."""
    from apex_tpu.transformer import TransformerConfig

    return TransformerConfig(
        num_layers=args.num_layers,
        hidden_size=args.hidden_size,
        num_attention_heads=args.num_attention_heads,
        vocab_size=args.padded_vocab_size
        if getattr(args, "padded_vocab_size", None)
        else args.make_vocab_size_divisible_by,
        max_position_embeddings=args.max_position_embeddings,
        ffn_hidden_size=args.ffn_hidden_size,
        hidden_dropout=args.hidden_dropout,
        attention_dropout=args.attention_dropout,
        layernorm_epsilon=args.layernorm_epsilon,
        sequence_parallel=args.sequence_parallel,
        compute_dtype=args.params_dtype,
    )


def _add_network_size_args(parser):
    group = parser.add_argument_group(title="network size")
    group.add_argument("--num-layers", type=int, default=None)
    group.add_argument("--hidden-size", type=int, default=None)
    group.add_argument("--ffn-hidden-size", type=int, default=None)
    group.add_argument("--num-attention-heads", type=int, default=None)
    group.add_argument("--kv-channels", type=int, default=None)
    group.add_argument("--max-position-embeddings", type=int, default=None)
    group.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    group.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    group.add_argument("--apply-residual-connection-post-layernorm",
                       action="store_true")
    group.add_argument("--openai-gelu", action="store_true")
    group.add_argument("--onnx-safe", type=bool, default=None)
    group.add_argument("--bert-no-binary-head", action="store_false",
                       dest="bert_binary_head")
    group.add_argument("--num-experts", type=int, default=None)
    return parser


def _add_regularization_args(parser):
    group = parser.add_argument_group(title="regularization")
    group.add_argument("--attention-dropout", type=float, default=0.1)
    group.add_argument("--hidden-dropout", type=float, default=0.1)
    group.add_argument("--weight-decay", type=float, default=0.01)
    group.add_argument("--start-weight-decay", type=float)
    group.add_argument("--end-weight-decay", type=float)
    group.add_argument("--weight-decay-incr-style", type=str, default="constant",
                       choices=["constant", "linear", "cosine"])
    group.add_argument("--clip-grad", type=float, default=1.0)
    group.add_argument("--adam-beta1", type=float, default=0.9)
    group.add_argument("--adam-beta2", type=float, default=0.999)
    group.add_argument("--adam-eps", type=float, default=1e-8)
    group.add_argument("--sgd-momentum", type=float, default=0.9)
    return parser


def _add_training_args(parser):
    group = parser.add_argument_group(title="training")
    group.add_argument("--micro-batch-size", type=int, default=None)
    group.add_argument("--batch-size", type=int, default=None,
                       help="Old batch size parameter, do not use. Use --micro-batch-size instead")
    group.add_argument("--global-batch-size", type=int, default=None)
    group.add_argument("--rampup-batch-size", nargs="*", default=None)
    group.add_argument("--recompute-activations", action="store_true")
    group.add_argument("--recompute-granularity", type=str, default=None,
                       choices=["full", "selective"])
    group.add_argument("--distribute-saved-activations", action="store_true")
    group.add_argument("--recompute-method", type=str, default=None,
                       choices=["uniform", "block"])
    group.add_argument("--recompute-num-layers", type=int, default=1)
    group.add_argument("--checkpoint-activations", action="store_true")
    group.add_argument("--train-iters", type=int, default=None)
    group.add_argument("--train-samples", type=int, default=None)
    group.add_argument("--log-interval", type=int, default=100)
    group.add_argument("--exit-interval", type=int, default=None)
    group.add_argument("--exit-duration-in-mins", type=int, default=None)
    group.add_argument("--tensorboard-dir", type=str, default=None)
    group.add_argument("--no-masked-softmax-fusion", action="store_false",
                       dest="masked_softmax_fusion")
    group.add_argument("--no-bias-gelu-fusion", action="store_false",
                       dest="bias_gelu_fusion")
    group.add_argument("--no-bias-dropout-fusion", action="store_false",
                       dest="bias_dropout_fusion")
    group.add_argument("--optimizer", type=str, default="adam",
                       choices=["adam", "sgd"])
    group.add_argument("--dataloader-type", type=str, default=None,
                       choices=["single", "cyclic"])
    group.add_argument("--no-async-tensor-model-parallel-allreduce",
                       action="store_true")
    group.add_argument("--no-persist-layer-norm", action="store_true")
    group.add_argument("--sequence-parallel", action="store_true")
    group.add_argument("--no-gradient-accumulation-fusion",
                       action="store_false",
                       dest="gradient_accumulation_fusion")
    return parser


def _add_initialization_args(parser):
    group = parser.add_argument_group(title="initialization")
    group.add_argument("--seed", type=int, default=1234)
    group.add_argument("--init-method-std", type=float, default=0.02)
    group.add_argument("--init-method-xavier-uniform", action="store_true")
    return parser


def _add_learning_rate_args(parser):
    group = parser.add_argument_group(title="learning rate")
    group.add_argument("--lr", type=float, default=None)
    group.add_argument("--lr-decay-style", type=str, default="linear",
                       choices=["constant", "linear", "cosine"])
    group.add_argument("--lr-decay-iters", type=int, default=None)
    group.add_argument("--lr-decay-samples", type=int, default=None)
    group.add_argument("--lr-warmup-fraction", type=float, default=None)
    group.add_argument("--lr-warmup-iters", type=int, default=0)
    group.add_argument("--lr-warmup-samples", type=int, default=0)
    group.add_argument("--warmup", type=int, default=None,
                       help="Old lr warmup argument, do not use. Use --lr-warmup-fraction instead")
    group.add_argument("--min-lr", type=float, default=0.0)
    group.add_argument("--override-lr-scheduler", action="store_true")
    group.add_argument("--use-checkpoint-lr-scheduler", action="store_true")
    return parser


def _add_checkpointing_args(parser):
    group = parser.add_argument_group(title="checkpointing")
    group.add_argument("--save", type=str, default=None)
    group.add_argument("--save-interval", type=int, default=None)
    group.add_argument("--no-save-optim", action="store_true", default=None)
    group.add_argument("--no-save-rng", action="store_true", default=None)
    group.add_argument("--load", type=str, default=None)
    group.add_argument("--no-load-optim", action="store_true", default=None)
    group.add_argument("--no-load-rng", action="store_true", default=None)
    group.add_argument("--finetune", action="store_true")
    return parser


def _add_mixed_precision_args(parser):
    group = parser.add_argument_group(title="mixed precision")
    group.add_argument("--fp16", action="store_true")
    group.add_argument("--bf16", action="store_true")
    group.add_argument("--loss-scale", type=float, default=None)
    group.add_argument("--initial-loss-scale", type=float, default=2**32)
    group.add_argument("--min-loss-scale", type=float, default=1.0)
    group.add_argument("--loss-scale-window", type=float, default=1000)
    group.add_argument("--hysteresis", type=int, default=2)
    group.add_argument("--fp32-residual-connection", action="store_true")
    group.add_argument("--no-query-key-layer-scaling", action="store_false",
                       dest="apply_query_key_layer_scaling")
    group.add_argument("--attention-softmax-in-fp32", action="store_true")
    group.add_argument("--accumulate-allreduce-grads-in-fp32",
                       action="store_true")
    group.add_argument("--fp16-lm-cross-entropy", action="store_true")
    return parser


def _add_distributed_args(parser):
    group = parser.add_argument_group(title="distributed")
    group.add_argument("--tensor-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-split-rank", type=int,
                       default=None)
    group.add_argument("--model-parallel-size", type=int, default=None,
                       help="Old model parallel argument, do not use. Use --tensor-model-parallel-size instead")
    group.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                       default=None)
    group.add_argument("--distributed-backend", default="xla",
                       choices=["nccl", "gloo", "ucc", "xla"])
    group.add_argument("--DDP-impl", default="local",
                       choices=["local", "torch"])
    group.add_argument("--no-contiguous-buffers-in-local-ddp",
                       action="store_false",
                       dest="use_contiguous_buffers_in_local_ddp")
    group.add_argument("--no-scatter-gather-tensors-in-pipeline",
                       action="store_false",
                       dest="scatter_gather_tensors_in_pipeline")
    group.add_argument("--local_rank", type=int, default=None)
    group.add_argument("--lazy-mpu-init", type=bool, default=None)
    group.add_argument("--use-cpu-initialization", action="store_true",
                       default=None)
    group.add_argument("--empty-unused-memory-level", default=0, type=int,
                       choices=[0, 1, 2])
    group.add_argument("--standalone-embedding-stage", action="store_true",
                       default=False)
    return parser


def _add_validation_args(parser):
    group = parser.add_argument_group(title="validation")
    group.add_argument("--eval-iters", type=int, default=100)
    group.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    group = parser.add_argument_group(title="data and dataloader")
    group.add_argument("--data-path", nargs="*", default=None)
    group.add_argument("--split", type=str, default="969, 30, 1")
    group.add_argument("--vocab-file", type=str, default=None)
    group.add_argument("--merge-file", type=str, default=None)
    group.add_argument("--vocab-extra-ids", type=int, default=0)
    group.add_argument("--seq-length", type=int, default=None)
    group.add_argument("--encoder-seq-length", type=int, default=None)
    group.add_argument("--decoder-seq-length", type=int, default=None)
    group.add_argument("--retriever-seq-length", type=int, default=256)
    group.add_argument("--sample-rate", type=float, default=1.0)
    group.add_argument("--mask-prob", type=float, default=0.15)
    group.add_argument("--short-seq-prob", type=float, default=0.1)
    group.add_argument("--mmap-warmup", action="store_true")
    group.add_argument("--num-workers", type=int, default=2)
    group.add_argument("--tokenizer-type", type=str, default=None,
                       choices=["BertWordPieceLowerCase", "BertWordPieceCase",
                                "GPT2BPETokenizer"])
    group.add_argument("--data-impl", type=str, default="infer",
                       choices=["lazy", "cached", "mmap", "infer"])
    group.add_argument("--reset-position-ids", action="store_true")
    group.add_argument("--reset-attention-mask", action="store_true")
    group.add_argument("--eod-mask-loss", action="store_true")
    return parser


def _add_autoresume_args(parser):
    group = parser.add_argument_group(title="autoresume")
    group.add_argument("--adlr-autoresume", action="store_true")
    group.add_argument("--adlr-autoresume-interval", type=int, default=1000)
    return parser


def _add_biencoder_args(parser):
    group = parser.add_argument_group(title="biencoder")
    group.add_argument("--ict-head-size", type=int, default=None)
    group.add_argument("--biencoder-projection-dim", type=int, default=0)
    group.add_argument("--biencoder-shared-query-context-model",
                       action="store_true")
    group.add_argument("--ict-load", type=str, default=None)
    group.add_argument("--bert-load", type=str, default=None)
    group.add_argument("--titles-data-path", type=str, default=None)
    group.add_argument("--query-in-block-prob", type=float, default=0.1)
    group.add_argument("--use-one-sent-docs", action="store_true")
    group.add_argument("--evidence-data-path", type=str, default=None)
    group.add_argument("--retriever-report-topk-accuracies", nargs="+",
                       type=int, default=[])
    group.add_argument("--retriever-score-scaling", action="store_true")
    group.add_argument("--block-data-path", type=str, default=None)
    group.add_argument("--embedding-path", type=str, default=None)
    group.add_argument("--indexer-batch-size", type=int, default=128)
    group.add_argument("--indexer-log-interval", type=int, default=1000)
    return parser


def _add_vision_args(parser):
    group = parser.add_argument_group(title="vision")
    group.add_argument("--num-classes", type=int, default=1000)
    group.add_argument("--img-h", type=int, default=224)
    group.add_argument("--img-w", type=int, default=224)
    group.add_argument("--num-channels", type=int, default=3)
    group.add_argument("--patch-dim", type=int, default=16)
    group.add_argument("--classes-fraction", type=float, default=1.0)
    group.add_argument("--data-per-class-fraction", type=float, default=1.0)
    group.add_argument("--no-data-sharding", action="store_false",
                       dest="data_sharding")
    group.add_argument("--head-lr-mult", type=float, default=1.0)
    group.add_argument("--vision-pretraining", action="store_true")
    group.add_argument("--vision-pretraining-type", type=str, default="classify",
                       choices=["classify", "inpaint", "dino"])
    group.add_argument("--vision-backbone-type", type=str, default="vit",
                       choices=["vit", "mit", "swin"])
    group.add_argument("--swin-backbone-type", type=str, default="tiny",
                       choices=["tiny", "base", "h3"])
    group.add_argument("--mask-type", type=str, default="random",
                       choices=["random", "row"])
    group.add_argument("--mask-factor", type=float, default=1.0)
    group.add_argument("--iter-per-epoch", type=int, default=1250)
    group.add_argument("--dino-local-img-size", type=int, default=96)
    group.add_argument("--dino-local-crops-number", type=int, default=10)
    group.add_argument("--dino-head-hidden-size", type=int, default=2048)
    group.add_argument("--dino-bottleneck-size", type=int, default=256)
    group.add_argument("--dino-freeze-last-layer", type=float, default=1)
    group.add_argument("--dino-norm-last-layer", action="store_true")
    group.add_argument("--dino-warmup-teacher-temp", type=float, default=0.04)
    group.add_argument("--dino-teacher-temp", type=float, default=0.07)
    group.add_argument("--dino-warmup-teacher-temp-epochs", type=int, default=30)
    return parser


def _add_logging_args(parser):
    group = parser.add_argument_group(title="logging")
    # apex_tpu.monitor extension: per-run metric records (loss, tokens/s,
    # MFU) through the shared MetricRouter sink schema
    group.add_argument("--metrics-jsonl", type=str, default=None,
                       help="write kind='metrics' jsonl records here "
                            "(apex_tpu.monitor schema)")
    # apex_tpu.monitor.xray extension: startup introspection of the
    # compiled step (docs/observability.md, X-ray section)
    group.add_argument("--xray-report", action="store_true",
                       help="print the XLA memory breakdown of the "
                            "compiled step (and emit a kind='memory' "
                            "record) before training")
    group.add_argument("--xray-comms", action="store_true",
                       help="trace the step under the collective ledger "
                            "and print/emit per-axis comms volume + ICI "
                            "roofline (kind='comms' records)")
    group.add_argument("--log-params-norm", action="store_true")
    group.add_argument("--log-num-zeros-in-grad", action="store_true")
    group.add_argument("--tensorboard-log-interval", type=int, default=1)
    group.add_argument("--tensorboard-queue-size", type=int, default=1000)
    group.add_argument("--log-timers-to-tensorboard", action="store_true")
    group.add_argument("--log-batch-size-to-tensorboard", action="store_true")
    group.add_argument("--no-log-learnig-rate-to-tensorboard",
                       action="store_false",
                       dest="log_learning_rate_to_tensorboard")
    group.add_argument("--no-log-loss-scale-to-tensorboard",
                       action="store_false",
                       dest="log_loss_scale_to_tensorboard")
    group.add_argument("--log-validation-ppl-to-tensorboard",
                       action="store_true")
    group.add_argument("--log-memory-to-tensorboard", action="store_true")
    group.add_argument("--log-world-size-to-tensorboard", action="store_true")
    return parser
