"""``apex.transformer.pipeline_parallel`` import-surface alias.

Reference parity: /root/reference/apex/transformer/pipeline_parallel/
__init__.py (``get_forward_backward_func``, ``build_model``) plus the
schedule entry points user code reaches through the package.  The
implementations live in ``apex_tpu.parallel.pipeline`` (compiled-scan
schedules over ppermute edges).
"""

from apex_tpu.parallel.pipeline import (
    build_model,
    build_num_microbatches_calculator,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    forward_backward_with_pre_post,
    get_forward_backward_func,
    get_num_microbatches,
    setup_microbatch_calculator,
    update_num_microbatches,
)

__all__ = [
    "get_forward_backward_func",
    "build_model",
    "build_num_microbatches_calculator",
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "update_num_microbatches",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_with_pre_post",
]
