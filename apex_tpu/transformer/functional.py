"""``apex.transformer.functional`` import-surface alias.

Reference parity: /root/reference/apex/transformer/functional/__init__.py
(``FusedScaleMaskSoftmax``, ``fused_apply_rotary_pos_emb``,
``fused_apply_rotary_pos_emb_cached``).  Implementations in
``apex_tpu.ops`` (softmax dispatcher; RoPE with precomputed-frequency
variant).
"""

from apex_tpu.ops.rope import (
    apply_rotary_pos_emb as fused_apply_rotary_pos_emb,
)
from apex_tpu.ops.rope import (
    apply_rotary_pos_emb_cached as fused_apply_rotary_pos_emb_cached,
)
from apex_tpu.ops.softmax import FusedScaleMaskSoftmax

__all__ = [
    "FusedScaleMaskSoftmax",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
]
