"""``apex.transformer.tensor_parallel`` import-surface alias.

Reference parity: /root/reference/apex/transformer/tensor_parallel/
__init__.py — the names Megatron-style user code imports.  The
implementations live in ``apex_tpu.parallel`` (the TPU design keeps one
parallel package instead of mirroring the reference's split); this module
re-exports them under the reference's path so
``from apex.transformer.tensor_parallel import ColumnParallelLinear``
migrates by substituting the package root.

CUDA-only attribute helpers (set_tensor_model_parallel_attributes etc.)
have no TPU meaning — sharding is carried by the mesh/PartitionSpec, not
per-tensor attributes — and are intentionally absent; ``checkpoint`` and
the RNG helpers map per docs/migration.md (fold_in replaces the CUDA RNG
state tracker).
"""

from apex_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.parallel.random import (
    checkpoint,
    model_parallel_rng_key,
    model_parallel_seed,
)
from apex_tpu.parallel.utils import (
    VocabUtility,
    broadcast_data,
    split_tensor_along_last_dim,
)

__all__ = [
    "vocab_parallel_cross_entropy",
    "broadcast_data",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "checkpoint",
    "model_parallel_rng_key",
    "model_parallel_seed",
    "split_tensor_along_last_dim",
    "VocabUtility",
]
