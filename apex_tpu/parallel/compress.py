"""Quantized gradient collectives with error feedback (EQuARX-style).

The trust layer can predict every collective byte (xray ledger, PR-3),
confirm what XLA emitted (hlo-comms differ, PR-5), and measure achieved
bytes/s per mesh axis (timeline join, PR-6) — this module starts
*shrinking* the bytes. Following EQuARX (arXiv:2506.17615), an all-reduce
over ``n`` ranks decomposes into two quantized phases built entirely from
ledger-routed primitives:

    phase 1 (reduce-scatter):  split the local array into n chunks,
        block-quantize each chunk, ``all_to_all`` the int8 payload and
        the per-block fp32 scales, dequantize and SUM locally — each
        rank now owns the exact-fp32 reduction of its chunk;
    phase 2 (all-gather):      re-quantize the reduced chunk,
        ``all_gather`` payload + scales, dequantize.

Wire traffic is the classic ring cost at int8 width plus the scales
(~1/block_size overhead), i.e. ~4x fewer wire bytes than an fp32 psum —
and because every collective here goes through the
``apex_tpu.monitor.xray.ledger`` wrappers ON the actual wire arrays
(int8 payload, fp32 scales — never the fp32 boundary aval), the ledger
predicts the true compressed bytes and the hlo-comms differ verifies the
int8 pattern was emitted rather than allowlisting it away.

Error feedback (EF): quantization is lossy, so each caller that iterates
(DDP grad sync, the ZeRO optimizers) carries a residual pytree: the
local quantization error is re-added to the NEXT step's gradient before
quantizing (``acc = g + e``; ``e' = acc - dequant(quant(acc))``), which
telescopes — the sum of transmitted updates plus the final residual
equals the sum of true gradients — and restores convergence to the
uncompressed path (pinned by the slow-tier GPT parity tests). Residuals
poisoned by non-finite gradients are RESET to zero (the update is
skipped by found_inf that step anyway; carrying NaN forward would
poison every later step).

Overflow/found_inf exactness: a block containing NaN/Inf produces a
non-finite scale, so every element of that block dequantizes to NaN on
every rank — non-finite gradients PROPAGATE through the compressed
collectives and the grad scaler's ``found_inf`` fires exactly as on the
exact path. The found_inf consensus psum itself is never compressed
(it lives in amp/grad_scaler.py on the exact path).

When NOT to compress: trees of tiny leaves, where per-block scales and
phase padding dominate the payload (``CompressionConfig.min_elements``
routes small leaves to the exact psum), and any reduction whose result
feeds a CONTROL decision (found_inf, clip thresholds) rather than a
parameter update. See docs/parallel.md "Compressed collectives".

This module is the single home of quantize/dequant + collective
compositions — ``lint.compressed-collective`` bans the pattern anywhere
else in apex_tpu/, the same ledger-accounting home rule as
``lint.raw-collective``.
"""

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.monitor.xray import ledger as xlax

__all__ = [
    "CompressionConfig",
    "quantize_blockwise",
    "dequantize_blockwise",
    "quantized_psum",
    "quantized_psum_scatter",
    "quantized_all_gather",
    "ef_init",
    "ef_update",
    "predicted_psum_wire_bytes",
]

#: wire dtypes by config name; fp8 present only on jax builds that ship it
_WIRE_DTYPES = {"int8": (jnp.int8, 127.0)}
_FP8 = getattr(jnp, "float8_e4m3fn", None)
if _FP8 is not None:
    # e4m3 max finite magnitude is 448; scale to half of it so the
    # round-to-nearest of values near amax cannot overflow to inf
    _WIRE_DTYPES["fp8"] = (_FP8, 224.0)


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """How a gradient collective travels the wire.

    - ``dtype``: ``"int8"`` (block-scaled symmetric int8, default) or
      ``"fp8"`` (e4m3 payload, where the jax build ships the dtype).
    - ``block_size``: elements per fp32 scale. Smaller blocks bound the
      per-element error tighter but ship more scales (~4/block_size
      bytes/element overhead).
    - ``error_feedback``: whether callers should carry the residual
      pytree (they decide; the config is the single switch the tests
      and examples toggle).
    - ``min_elements``: leaves smaller than this go through the EXACT
      psum — for tiny leaves the scales + n-divisibility padding can
      exceed the fp32 payload (the "when NOT to compress" rule,
      docs/parallel.md). The default 16 routes scalars and tiny flags —
      the unambiguous losers at any axis size (a 1-element leaf ships
      >10x its exact bytes in scales alone) — to the exact path; the
      break-even grows with the axis size, so tune per mesh.
    """

    dtype: str = "int8"
    block_size: int = 128
    error_feedback: bool = True
    min_elements: int = 16

    def __post_init__(self):
        if self.dtype not in _WIRE_DTYPES:
            have = sorted(_WIRE_DTYPES)
            raise ValueError(
                f"compression dtype {self.dtype!r} not available on this "
                f"jax build; choose from {have}"
            )
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")

    @property
    def wire_dtype(self):
        return _WIRE_DTYPES[self.dtype][0]

    @property
    def qmax(self) -> float:
        return _WIRE_DTYPES[self.dtype][1]


# -- quantization core ------------------------------------------------------


def _num_blocks(n: int, block_size: int) -> int:
    return max(1, -(-n // block_size))


def quantize_blockwise(x, config: CompressionConfig = CompressionConfig()):
    """Block-scale-quantize a 1-D array: ``(payload, scales)``.

    ``payload`` has ``x``'s length in the wire dtype; ``scales`` is
    fp32 of length ``ceil(len/block_size)`` (a ragged final block is
    padded internally with zeros, which quantize exactly). Per-element
    error is bounded by ``scale/2 = amax_block / (2*qmax)``.

    Non-finite handling: a block containing NaN/Inf gets a NON-FINITE
    scale (amax propagates it) and an all-zero payload, so the block
    dequantizes to NaN everywhere — overflow is never silently clipped
    into a finite gradient (the found_inf contract).
    """
    bs = config.block_size
    qmax = config.qmax
    x = jnp.ravel(x).astype(jnp.float32)
    n = x.shape[0]
    nb = _num_blocks(n, bs)
    xp = jnp.pad(x, (0, nb * bs - n)).reshape(nb, bs)
    amax = jnp.max(jnp.abs(xp), axis=1)
    scales = jnp.where(amax > 0, amax / qmax, 1.0)
    # a NaN amax fails the > 0 compare and would silently pick scale 1.0
    # (swallowing the poison); force ANY non-finite block to a NaN scale
    # so it dequantizes to NaN on every rank
    scales = jnp.where(jnp.isfinite(amax), scales, jnp.nan)
    q = xp / scales[:, None]
    if jnp.issubdtype(jnp.dtype(config.wire_dtype), jnp.integer):
        # integer wire: round to the nearest code point. Float wire
        # (fp8) keeps the quotient — the dtype cast below rounds to the
        # nearest representable, preserving fractional precision
        q = jnp.round(q)
    # poison rides the SCALE: zero the payload wherever the quotient is
    # non-finite (x/inf -> 0 is already fine; NaN/inf quotients are not
    # representable on the wire and must not be clipped into fake values)
    q = jnp.where(jnp.isfinite(q), jnp.clip(q, -qmax, qmax), 0.0)
    payload = q.reshape(-1)[:n].astype(config.wire_dtype)
    return payload, scales


def dequantize_blockwise(
    payload, scales, config: CompressionConfig = CompressionConfig()
):
    """Inverse of :func:`quantize_blockwise`: fp32 of ``payload``'s length.

    A non-finite scale spreads NaN over its whole block (``0 * inf`` and
    ``q * nan`` are both NaN) — see the found_inf contract above.
    """
    bs = config.block_size
    n = payload.shape[0]
    nb = scales.shape[0]
    qp = jnp.pad(payload.astype(jnp.float32), (0, nb * bs - n))
    out = (qp.reshape(nb, bs) * scales[:, None].astype(jnp.float32))
    return out.reshape(-1)[:n]


# -- collective decompositions ----------------------------------------------


def _gather_tiled(x, axis_name: str):
    """1-D tiled all_gather, typed INVARIANT under live vma tracking.

    Phase 2's gathered payload is provably identical on every rank; the
    plain gather stays typed axis-varying under checked shard_map, which
    would force callers' out_specs varying where the exact psum's result
    is invariant. The invariant-gather mechanics (private-API import,
    ledger recording, signature-drift guard) live in ONE home —
    ``mappings._all_gather_invariant_dim``."""
    from apex_tpu.parallel.ddp import vma_tracking_live

    if not vma_tracking_live(axis_name):
        return xlax.all_gather(x, axis_name, tiled=True)
    from apex_tpu.parallel.mappings import _all_gather_invariant_dim

    return _all_gather_invariant_dim(x, axis_name, 0)


def _quantized_reduce_chunks(rows, config: CompressionConfig, axis_name: str):
    """Phase 1 on a ``(n, chunk)`` row layout (row j is the payload
    destined for rank j): quantize rows, all_to_all payload + scales,
    dequant + sum. Returns ``(reduced_chunk_f32, transmitted_f32)`` where
    ``transmitted`` is what THIS rank's quantizer actually sent (the EF
    subtraction term), reshaped like ``rows``."""
    n = rows.shape[0]
    payload, scales = jax.vmap(lambda r: quantize_blockwise(r, config))(rows)
    # EF term: the dequantized local contribution, computed before the
    # exchange so no extra bytes move
    transmitted = jax.vmap(
        lambda p, s: dequantize_blockwise(p, s, config)
    )(payload, scales)
    p2 = xlax.all_to_all(payload, axis_name, 0, 0)
    s2 = xlax.all_to_all(scales, axis_name, 0, 0)
    deq = jax.vmap(lambda p, s: dequantize_blockwise(p, s, config))(p2, s2)
    return jnp.sum(deq, axis=0), transmitted


def quantized_psum(
    x,
    axis_name: str,
    config: CompressionConfig = CompressionConfig(),
    return_transmitted: bool = False,
):
    """Block-scaled quantized all-reduce (SUM) of one array.

    The EQuARX decomposition (module docstring): quantized
    reduce-scatter via ``all_to_all`` + local dequant-reduce, then a
    quantized all-gather of the reduced chunks. The result matches
    ``psum`` up to two block-quantization errors (phase 1 on the
    operands, phase 2 on the reduced chunks); inputs that are exact
    integer multiples of their block scale (e.g. integers with a ±qmax
    element in every block) round-trip digit-for-digit.

    ``return_transmitted=True`` additionally returns the fp32 value this
    rank's phase-1 quantizer transmitted (same shape as ``x``) — the
    subtraction term of the error-feedback update (:func:`ef_update`).
    Leaves smaller than ``config.min_elements`` take the exact psum
    (transmitted == x: zero EF error).
    """
    n = xlax.axis_size(axis_name)
    orig_dtype = x.dtype
    orig_shape = x.shape
    size = int(np.prod(orig_shape, dtype=np.int64)) if orig_shape else 1
    if n <= 1 or size < config.min_elements:
        out = xlax.psum(x, axis_name)
        return (out, x.astype(jnp.float32)) if return_transmitted else out
    flat = jnp.ravel(x).astype(jnp.float32)
    pad = (-size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.reshape(n, flat.shape[0] // n)
    red, transmitted = _quantized_reduce_chunks(rows, config, axis_name)
    # phase 2: re-quantize the exact-fp32 reduced chunk and gather. The
    # gathered buffer is dequantized PER CHUNK (each rank's chunk carries
    # its own ragged final block; a flat dequant would misalign scales
    # across chunk boundaries)
    p3, s3 = quantize_blockwise(red, config)
    chunk = red.shape[0]
    pg = _gather_tiled(p3, axis_name).reshape(n, chunk)
    sg = _gather_tiled(s3, axis_name).reshape(n, s3.shape[0])
    gathered = jax.vmap(
        lambda p, s: dequantize_blockwise(p, s, config)
    )(pg, sg).reshape(-1)
    out = gathered[:size].reshape(orig_shape).astype(orig_dtype)
    if return_transmitted:
        sent = transmitted.reshape(-1)[:size].reshape(orig_shape)
        return out, sent
    return out


def quantized_psum_scatter(
    flat,
    axis_name: str,
    config: CompressionConfig = CompressionConfig(),
    return_transmitted: bool = False,
):
    """Quantized reduce-scatter of a 1-D buffer (phase 1 alone).

    ``flat.shape[0]`` must divide by the axis size (the ZeRO flat
    buffers are padded to exactly that). Returns this rank's reduced
    chunk in fp32 — the master-shard update consuming it stays exact;
    only the GRADIENTS traveled int8. With ``return_transmitted=True``
    also returns the fp32 transmitted value (full input length, the EF
    subtraction term).
    """
    n = xlax.axis_size(axis_name)
    if n <= 1:
        out = xlax.psum_scatter(flat, axis_name, tiled=True)
        return (out, flat.astype(jnp.float32)) if return_transmitted else out
    size = flat.shape[0]
    if size % n:
        raise ValueError(
            f"quantized_psum_scatter needs length divisible by the axis "
            f"size, got {size} over n={n} (pad the flat buffer first, as "
            f"the ZeRO optimizers do)"
        )
    rows = jnp.ravel(flat).astype(jnp.float32).reshape(n, size // n)
    red, transmitted = _quantized_reduce_chunks(rows, config, axis_name)
    if return_transmitted:
        return red, transmitted.reshape(size)
    return red


def quantized_all_gather(
    shard,
    axis_name: str,
    config: CompressionConfig = CompressionConfig(),
):
    """Quantized tiled all-gather of a 1-D shard: quantize the local
    shard, gather payload + scales, dequantize. Errors are NOT
    error-fed (a gather has no accumulation to feed back into); the
    ZeRO param all-gather therefore stays EXACT by default — this
    exists for activation/broadcast payloads where one bounded
    quantization error is acceptable."""
    n = xlax.axis_size(axis_name)
    if n <= 1:
        return xlax.all_gather(shard, axis_name, tiled=True)
    orig_dtype = shard.dtype
    flat = jnp.ravel(shard)
    payload, scales = quantize_blockwise(flat, config)
    # dequantize PER SHARD: each rank's shard carries its own ragged
    # final block, so a flat dequant of the concatenation would apply
    # the wrong ranks' scales past the first shard (the same
    # misalignment quantized_psum's phase 2 guards against)
    pg = _gather_tiled(payload, axis_name).reshape(n, flat.shape[0])
    sg = _gather_tiled(scales, axis_name).reshape(n, scales.shape[0])
    out = jax.vmap(
        lambda p, s: dequantize_blockwise(p, s, config)
    )(pg, sg).reshape(-1)
    return out.astype(orig_dtype)


# -- error feedback ---------------------------------------------------------


def ef_init(grads: Any) -> Any:
    """Zero residual pytree (fp32, one leaf per grad leaf)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.zeros(jnp.shape(g), jnp.float32), grads
    )


def ef_update(acc, transmitted):
    """One leaf's residual after transmission: ``acc - transmitted``,
    RESET to zero wherever ``acc`` OR the transmitted value is
    non-finite (a poisoned block transmits NaN for EVERY element it
    covers; the found_inf step skips the update anyway, and a NaN
    residual would poison every later step). ``acc`` is the
    error-compensated gradient (``g + e``) in fp32."""
    acc = acc.astype(jnp.float32)
    sent = transmitted.astype(jnp.float32)
    return jnp.where(
        jnp.isfinite(acc) & jnp.isfinite(sent), acc - sent, 0.0
    )


# -- byte accounting (the hand-count the ledger pin tests mirror) -----------


def predicted_psum_wire_bytes(
    size: int, n: int, config: CompressionConfig = CompressionConfig()
) -> Tuple[int, int]:
    """``(payload_bytes, ici_bytes)`` one :func:`quantized_psum` of a
    ``size``-element leaf books in the ledger — the documented
    hand-count, kept next to the implementation so the pin tests and
    the code cannot drift apart.

    Per the ledger's conventions (monitor/xray/ledger.py): all_to_all
    books the full per-device input and ``(n-1)/n`` of it on the wire;
    a tiled all_gather books the local shard and ``(n-1)`` shards on
    the wire. Phase 1 ships an ``(n, chunk)`` payload + ``(n, nb)``
    scales; phase 2 gathers one chunk + its scales.
    """
    import math

    if n <= 1 or size < config.min_elements:
        nbytes = size * 4
        return nbytes, math.ceil(2 * (n - 1) * nbytes / n) if n > 1 else 0
    item = np.dtype(config.wire_dtype).itemsize
    chunk = -(-size // n)  # ceil: the padded flat length is n*chunk
    nb = _num_blocks(chunk, config.block_size)
    p1_payload = n * chunk * item
    p1_scales = n * nb * 4
    p2_payload = chunk * item
    p2_scales = nb * 4
    payload = p1_payload + p1_scales + p2_payload + p2_scales
    ici = (
        math.ceil((n - 1) * p1_payload / n)
        + math.ceil((n - 1) * p1_scales / n)
        + (n - 1) * p2_payload
        + (n - 1) * p2_scales
    )
    return payload, ici
