"""Tensor-parallel utilities.

Reference parity: apex/transformer/tensor_parallel/utils.py
(split_tensor_along_last_dim :22, VocabUtility :46) and
tensor_parallel/data.py (broadcast_data :80).
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def split_tensor_along_last_dim(x, num_partitions: int) -> Sequence[jax.Array]:
    """(ref: utils.py:22)"""
    return jnp.split(x, num_partitions, axis=-1)


class VocabUtility:
    """Vocab range math (ref: utils.py:46)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        start = rank * per_partition_vocab_size
        return start, start + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        per = global_vocab_size // world_size
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size
        )


def broadcast_data(keys, data, dtype=None):
    """(ref: data.py:80) — broadcast batch data from TP rank 0.

    Under single-controller SPMD every device already sees the same host
    arrays, so this is an identity kept for API parity; multi-controller
    setups get consistency from feeding identical per-process data (the
    jax.distributed contract).
    """
    del dtype
    return {k: data[k] for k in keys}


def pcast_varying(x, axis_names):
    """``jax.lax.pcast(x, axis_names, to='varying')`` with an identity
    fallback on jax versions predating the vma type system (pcast absent
    there, and with no typing the cast is meaningless — exactly the
    unchecked semantics every pre-vma path assumed)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_names, to="varying")


def _widen_leaf(x, want):
    """pcast ``x`` to also vary over the axes in ``want`` it lacks."""
    missing = tuple(sorted(set(want) - set(jax.typeof(x).vma)))
    return pcast_varying(x, missing) if missing else x


def promote_to_vma(tree, like):
    """pcast each leaf of ``tree`` to ALSO vary over ``like``'s varying
    axes — the scan-carry fixed-point helper: accumulators must start
    with the vma their loop bodies will produce (ring attention's block
    scans derive masks from rank positions, so outputs vary even when
    inputs are replicated). No-op when already varying, under
    ``check_vma=False``, or on pre-vma jax."""
    try:
        want = jax.typeof(like).vma
    except AttributeError:
        return tree
    if not want:
        return tree

    return jax.tree_util.tree_map(lambda x: _widen_leaf(x, want), tree)


def pvary_params(tree, axis_name: str = "tp"):
    """Type every leaf of a param pytree VARYING over ``axis_name``
    (leaves already varying pass through; numerics unchanged; no-op under
    ``check_vma=False``).

    Why this exists: under jax's checked shard_map, a tensor-parallel
    param created IN-BODY with a rank-independent initializer (the
    canonical zeros bias of ColumnParallelLinear) is typed replicated
    even though each rank's slice is a distinct coordinate of the global
    parameter — and ``jax.grad`` then auto-psums its gradient over
    ``axis_name``, silently summing what should stay per-rank
    (tests/test_checked_vma.py pins the 7.5% grad error this produced).
    Params that enter the shard_map through tp-sharded ``in_specs``, or
    whose init folds in the tp rank, are already varying and unaffected.
    Call this on stage/layer param trees built inside shard_map before
    differentiating.

    ONLY for sharded params: a genuinely REPLICATED parameter must stay
    invarying — e.g. ``RowParallelLinear``'s bias, which is added once
    AFTER the tp reduction; pvarying it types the layer output spuriously
    varying and shifts every downstream gradient. Apply per-subtree when
    a tree mixes both (tests/test_checked_vma.py shows the pattern).
    """

    def one(x):
        try:
            if axis_name in jax.typeof(x).vma:
                return x
        except AttributeError:
            return x
        return pcast_varying(x, axis_name)

    return jax.tree_util.tree_map(one, tree)


def vma_cond(pred, true_fn, false_fn, *operands):
    """``jax.lax.cond`` whose branch outputs are pcast to their per-leaf
    JOIN vma, so branches varying over different manual-axis sets
    typecheck under jax's checked ``shard_map``.

    Checked mode types every value with its varying-manual-axes (vma)
    set, and ``lax.cond`` requires the two branch output types to match
    EXACTLY — which natural code frequently violates: the canonical
    "skip the optimizer step on overflow" cond returns the (replicated)
    old state from one branch and grad-varying new state from the other.
    A ``jnp.where`` select sidesteps the typecheck (selects auto-pvary)
    but evaluates BOTH branches; this wrapper keeps cond's single-branch
    evaluation by eval_shaping both branches (trace only, no compute),
    taking each output leaf's vma union, and widening each branch's
    outputs to that join INSIDE the branch.

    Falls back to plain ``lax.cond`` when nothing needs widening — in
    particular on pre-vma jax, under ``check_vma=False``, and outside
    ``shard_map``, where it is exactly ``jax.lax.cond``.
    """
    try:
        # muted: these shape probes re-trace branch Python (possibly
        # containing collectives) without becoming part of the program —
        # the xray comms ledger must not double-count them
        from apex_tpu.monitor.xray import ledger as _xlax

        with _xlax.muted():
            t_shape = jax.eval_shape(true_fn, *operands)
            f_shape = jax.eval_shape(false_fn, *operands)
        t_leaves, t_def = jax.tree_util.tree_flatten(t_shape)
        f_leaves, f_def = jax.tree_util.tree_flatten(f_shape)
        if t_def != f_def or len(t_leaves) != len(f_leaves):
            # mismatched structures: let lax.cond produce its own error
            return jax.lax.cond(pred, true_fn, false_fn, *operands)
        wants = []
        any_cast = False
        for a, b in zip(t_leaves, f_leaves):
            va, vb = getattr(a, "vma", None), getattr(b, "vma", None)
            if va is None or vb is None:
                wants.append(None)
                continue
            union = set(va) | set(vb)
            wants.append(tuple(sorted(union)))
            if union != set(va) or union != set(vb):
                any_cast = True
    except Exception:
        # eval_shape failing here says nothing cond itself won't say better
        return jax.lax.cond(pred, true_fn, false_fn, *operands)
    if not any_cast:
        return jax.lax.cond(pred, true_fn, false_fn, *operands)

    def widened(fn):
        def g(*ops):
            out = fn(*ops)
            leaves, treedef = jax.tree_util.tree_flatten(out)
            leaves = [l if w is None else _widen_leaf(l, w)
                      for l, w in zip(leaves, wants)]
            return jax.tree_util.tree_unflatten(treedef, leaves)

        return g

    return jax.lax.cond(pred, widened(true_fn), widened(false_fn), *operands)


def scan_carry_fixed_point(body, carry, x0, max_iters: int = 3):
    """Promote ``carry``'s leaves to the vma fixed point of ``body`` so
    ``jax.lax.scan(body, carry, xs)`` typechecks under checked shard_map.

    A training-loop carry routinely starts with narrower varying axes
    than the body produces (optimizer moments init as replicated zeros
    while their updates inherit the grads' varying axes), and checked
    scan requires carry-in type == carry-out type. This evaluates the
    body's output carry type via ``jax.eval_shape`` (trace only — no
    compute), widens the carry with ``pcast`` where needed, and repeats
    until stable (vma sets only grow toward the mesh's axis set, so this
    terminates; one round suffices in practice).

    ``x0``: one slice of the scan xs (e.g. ``tree_map(lambda a: a[0],
    xs)``); pass ``None`` for a None-xs scan. No-op under
    ``check_vma=False`` / pre-vma jax. Returns the promoted carry.
    """

    def _vma(x):
        try:
            return jax.typeof(x).vma
        except AttributeError:
            return None

    # max_iters + 1 evals: a round whose widening REACHES the fixed point
    # must not raise — convergence means some eval produced no widening,
    # so the last allowed widening gets one extra verification eval
    from apex_tpu.monitor.xray import ledger as _xlax

    for _ in range(max_iters + 1):
        with _xlax.muted():  # shape probe — see vma_cond
            out_carry = jax.eval_shape(lambda c: body(c, x0)[0], carry)
        changed = False

        def widen(c, o):
            nonlocal changed
            have, want = _vma(c), getattr(o, "vma", None)
            if have is None or not want or not (set(want) - set(have)):
                return c
            changed = True
            return _widen_leaf(c, want)

        carry = jax.tree_util.tree_map(widen, carry, out_carry)
        if not changed:
            return carry
    raise ValueError(
        "scan_carry_fixed_point did not converge within "
        f"max_iters={max_iters} widening rounds; raise max_iters "
        "(vma sets only grow toward the mesh axis count)"
    )
