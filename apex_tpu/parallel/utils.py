"""Tensor-parallel utilities.

Reference parity: apex/transformer/tensor_parallel/utils.py
(split_tensor_along_last_dim :22, VocabUtility :46) and
tensor_parallel/data.py (broadcast_data :80).
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def split_tensor_along_last_dim(x, num_partitions: int) -> Sequence[jax.Array]:
    """(ref: utils.py:22)"""
    return jnp.split(x, num_partitions, axis=-1)


class VocabUtility:
    """Vocab range math (ref: utils.py:46)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        start = rank * per_partition_vocab_size
        return start, start + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        per = global_vocab_size // world_size
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size
        )


def broadcast_data(keys, data, dtype=None):
    """(ref: data.py:80) — broadcast batch data from TP rank 0.

    Under single-controller SPMD every device already sees the same host
    arrays, so this is an identity kept for API parity; multi-controller
    setups get consistency from feeding identical per-process data (the
    jax.distributed contract).
    """
    del dtype
    return {k: data[k] for k in keys}
