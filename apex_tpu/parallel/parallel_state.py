"""Model-parallel state: the device mesh and its accessor API.

Reference parity: apex/transformer/parallel_state.py (:155
initialize_model_parallel, :266-407 group construction, :590-755 rank/world
accessors, :761 destroy). The reference builds ~10 families of NCCL process
groups (DP, TP, PP, model, embedding, position-embedding, amax, …); on TPU
*all* of them collapse into named axes of one ``jax.sharding.Mesh``:

    mesh axes = ('dp', 'pp', 'cp', 'tp')     # outermost -> innermost

- 'tp' innermost so tensor-parallel collectives ride the fastest ICI links;
- 'dp' outermost so data-parallel allreduce can cross DCN on multi-slice;
- 'cp' (context/sequence-ring parallelism) sits between — an extension over
  the reference (which has no CP; SURVEY.md §2.5).
- Megatron sequence parallelism reuses the 'tp' axis (as in the reference,
  mappings.py:213-272) and needs no axis of its own.
- The backend-selection dimension (NCCL vs UCC vs IB/Socket hybrid,
  parallel_state.py:108-153) does not exist: XLA compiles collectives onto
  ICI/DCN from the mesh layout.

Rank accessors return Python ints when the corresponding axis is unsharded
and traced values (``lax.axis_index``) inside shard_map otherwise — matching
how the reference's per-process ints generalize to SPMD.
"""

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_MESH: Optional[Mesh] = None
_VIRTUAL_PIPELINE_WORLD_SIZE: Optional[int] = None
_VIRTUAL_PIPELINE_RANK: Optional[int] = None
_PIPELINE_SPLIT_RANK: Optional[int] = None

# canonical axis names
DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"
AXIS_ORDER = (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
):
    """Multi-host/controller bring-up (the reference's
    ``torch.distributed.init_process_group`` role, commons.py:250 +
    parallel_state's NCCL group machinery).

    Wraps ``jax.distributed.initialize`` — with no arguments it reads the
    standard cluster environment (TPU pod metadata / COORDINATOR_ADDRESS /
    SLURM), after which ``jax.devices()`` spans every host and
    ``initialize_model_parallel`` lays the global mesh over them (dp
    outermost → DCN; tp innermost → ICI).

    Idempotent and single-process-safe by explicit checks, not exception
    matching: already-initialized returns immediately, and with no
    arguments AND no cluster environment there is nothing to coordinate,
    so the call is a no-op returning ``(process_count, process_index)``
    (jax's auto-detection would otherwise raise on a dev box).
    """
    # jax.distributed.is_initialized only exists on current jax; older
    # builds expose the same fact through the global client handle
    _is_init = getattr(jax.distributed, "is_initialized", None)
    if _is_init is not None:
        initialized = _is_init()
    else:  # pre-0.5 jax: the global client handle is the same fact
        try:
            from jax._src.distributed import global_state

            initialized = global_state.client is not None
        except Exception:
            initialized = False
    if initialized:
        return jax.process_count(), jax.process_index()
    cluster_env = any(
        v in os.environ
        for v in (
            "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
            "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
        )
    )
    if coordinator_address is None and num_processes is None and not cluster_env:
        return jax.process_count(), jax.process_index()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return jax.process_count(), jax.process_index()


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    context_parallel_size: int = 1,
    devices: Optional[Sequence] = None,
    num_slices: int = 1,
) -> Mesh:
    """Build the global mesh (ref: parallel_state.py:155).

    ``devices`` defaults to ``jax.devices()``; data-parallel size is whatever
    remains after tp*pp*cp, exactly like the reference computes
    data_parallel_size = world_size // (tp*pp) (parallel_state.py:241).

    Topology: with default devices, ``mesh_utils.create_device_mesh``
    arranges the axes along the physical ICI torus (the analogue of the
    reference's IB/Socket-aware NCCL group construction,
    parallel_state.py:108-153). ``num_slices > 1`` builds a HYBRID mesh for
    multi-slice/multi-host pods: the data-parallel axis is split so its
    outer factor crosses DCN while everything else stays on ICI
    (``mesh_utils.create_hybrid_device_mesh``). An explicit ``devices`` list
    (tests, sub-meshes) keeps the plain reshape.
    """
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK
    global _PIPELINE_SPLIT_RANK
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    world = len(devices)
    tp, pp, cp = (
        tensor_model_parallel_size,
        pipeline_model_parallel_size,
        context_parallel_size,
    )
    if world % (tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tp ({tp}) x pp ({pp}) x cp ({cp})"
        )
    dp = world // (tp * pp * cp)
    if num_slices > 1:
        if explicit:
            raise ValueError(
                "num_slices > 1 needs the full device topology; it cannot "
                "be combined with an explicit devices list"
            )
        if dp % num_slices != 0:
            raise RuntimeError(
                f"data-parallel size ({dp}) is not divisible by num_slices "
                f"({num_slices}); only dp crosses DCN"
            )
        from jax.experimental import mesh_utils

        per_slice = (dp // num_slices, pp, cp, tp)
        arr = mesh_utils.create_hybrid_device_mesh(
            per_slice, (num_slices, 1, 1, 1), devices=devices
        )
    elif explicit:
        arr = np.asarray(devices).reshape(dp, pp, cp, tp)
    else:
        from jax.experimental import mesh_utils

        if devices and devices[0].platform == "cpu":
            # CPU backends carry no topology; plain order, no mesh_utils
            arr = np.asarray(devices).reshape(dp, pp, cp, tp)
        else:
            # on real hardware a failure here (unmappable factorization)
            # must surface — silently falling back to enumeration order
            # would put tp collectives on slow links with no diagnostic
            arr = mesh_utils.create_device_mesh((dp, pp, cp, tp),
                                                devices=devices)
    _MESH = Mesh(arr, AXIS_ORDER)
    _VIRTUAL_PIPELINE_WORLD_SIZE = virtual_pipeline_model_parallel_size
    _VIRTUAL_PIPELINE_RANK = 0 if virtual_pipeline_model_parallel_size else None
    _PIPELINE_SPLIT_RANK = pipeline_model_parallel_split_rank
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _MESH


def destroy_model_parallel() -> None:
    """(ref: parallel_state.py:761)"""
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK
    global _PIPELINE_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PIPELINE_WORLD_SIZE = None
    _VIRTUAL_PIPELINE_RANK = None
    _PIPELINE_SPLIT_RANK = None


# -- world sizes ------------------------------------------------------------


def _axis_size(name: str) -> int:
    return int(get_mesh().shape[name])


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(PIPELINE_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DATA_AXIS)


def get_model_parallel_world_size() -> int:
    return get_tensor_model_parallel_world_size() * get_pipeline_model_parallel_world_size()


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_WORLD_SIZE


def get_amax_reduction_axes() -> tuple:
    """Axes of the FP8 amax-reduction group (ref parallel_state.py:280-292:
    tp x dp ranks sharing a pipeline stage — every rank that sees a shard
    of the same activations; 'cp' joins for the same reason dp does).
    Use inside shard_map: ``amax = amax_reduction(local_amax)``."""
    return (DATA_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


def amax_reduction(local_amax):
    """pmax of a local |activation|-max over the amax group (the delayed-
    scaling statistic FP8 recipes synchronize; ref use_fp8 groups)."""
    out = local_amax
    for ax in get_amax_reduction_axes():
        if _MESH is not None and int(get_mesh().shape[ax]) > 1:
            try:
                from apex_tpu.monitor.xray import ledger as xlax

                out = xlax.pmax(out, ax)
            except NameError as e:
                # outside shard_map the statistic would be silently
                # UNREDUCED over a >1 axis — surface the misuse instead
                raise RuntimeError(
                    f"amax_reduction over {ax!r} requested outside shard_map "
                    f"while the mesh has {int(get_mesh().shape[ax])} shards; "
                    f"the amax would miss the other shards' values. Call "
                    f"inside shard_map."
                ) from e
    return out


# -- ranks ------------------------------------------------------------------


def _axis_rank(name: str):
    """Python 0 when the axis is trivial; traced ``lax.axis_index`` inside
    shard_map over that axis.  Outside shard_map with a >1 axis there IS no
    well-defined rank (the single-controller host sees all shards), so that
    misuse raises instead of silently acting as rank 0 (VERDICT r3 weak #4);
    non-axis errors (bad axis name, tracing bugs) always propagate."""
    if _MESH is None or int(get_mesh().shape[name]) == 1:
        return 0
    try:
        return jax.lax.axis_index(name)
    except NameError as e:
        raise RuntimeError(
            f"{name!r} rank requested outside shard_map while the mesh has "
            f"{int(get_mesh().shape[name])} {name!r} shards — the host view "
            f"has no single rank. Call inside shard_map over {name!r}."
        ) from e


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS)


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    global _VIRTUAL_PIPELINE_RANK
    _VIRTUAL_PIPELINE_RANK = rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PIPELINE_SPLIT_RANK


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """(ref: parallel_state.py:649) — traced bool inside shard_map over pp."""
    if not ignore_virtual and _VIRTUAL_PIPELINE_WORLD_SIZE is not None:
        if _VIRTUAL_PIPELINE_RANK != 0:
            return False
    r = get_pipeline_model_parallel_rank()
    return r == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    """(ref: parallel_state.py:660)"""
    if not ignore_virtual and _VIRTUAL_PIPELINE_WORLD_SIZE is not None:
        if _VIRTUAL_PIPELINE_RANK != (_VIRTUAL_PIPELINE_WORLD_SIZE - 1):
            return False
    r = get_pipeline_model_parallel_rank()
    return r == get_pipeline_model_parallel_world_size() - 1


# -- sharding helpers -------------------------------------------------------


def named_sharding(*spec):
    """NamedSharding over the global mesh for a PartitionSpec."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(get_mesh(), PartitionSpec(*spec))
