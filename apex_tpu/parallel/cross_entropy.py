"""Vocab-parallel cross entropy.

Reference parity: apex/transformer/tensor_parallel/cross_entropy.py
(_VocabParallelCrossEntropy, :23-131): logits are sharded along vocab over
TP; the softmax-CE is computed with three TP collectives — max (pmax),
sum-exp (psum), and the target-logit partial (psum) — plus label smoothing.

TPU design: straight jnp over ``lax`` collectives; autodiff produces the
same (softmax - onehot) backward the reference hand-writes, with the psum
transposes handled by JAX.
"""

import jax
import jax.numpy as jnp

from apex_tpu.parallel import parallel_state


def vocab_parallel_cross_entropy(
    logits_local, target, label_smoothing: float = 0.0, axis_name: str = "tp"
):
    """Per-token CE loss from vocab-sharded logits.

    ``logits_local``: (..., vocab/tp) this rank's shard; ``target``: (...)
    global token ids. Returns fp32 losses shaped like ``target``.
    """
    tp = 1
    if parallel_state.model_parallel_is_initialized():
        tp = parallel_state.get_tensor_model_parallel_world_size()
    lf = logits_local.astype(jnp.float32)
    vocab_local = lf.shape[-1]

    if tp == 1:
        lse = jax.scipy.special.logsumexp(lf, axis=-1)
        tlogit = jnp.take_along_axis(lf, target[..., None], axis=-1)[..., 0]
        mean_logit = jnp.mean(lf, axis=-1)
    else:
        rank = jax.lax.axis_index(axis_name)
        start = rank * vocab_local
        # global max for stability (ref: allreduce MAX, cross_entropy.py:38);
        # the shift cancels analytically, so keep it out of the grad graph
        # (pmax has no differentiation rule).
        gmax = jax.lax.pmax(
            jax.lax.stop_gradient(jnp.max(lf, axis=-1)), axis_name
        )
        shifted = lf - gmax[..., None]
        sum_exp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
        lse = jnp.log(sum_exp) + gmax
        # target logit: only the owning rank contributes (ref: masked gather
        # + allreduce, cross_entropy.py:55-77)
        in_range = (target >= start) & (target < start + vocab_local)
        local_ids = jnp.clip(target - start, 0, vocab_local - 1)
        partial = jnp.take_along_axis(lf, local_ids[..., None], axis=-1)[..., 0]
        tlogit = jax.lax.psum(jnp.where(in_range, partial, 0.0), axis_name)
        mean_logit = jax.lax.psum(jnp.sum(lf, axis=-1), axis_name) / (
            vocab_local * tp
        )

    loss = lse - tlogit
    if label_smoothing > 0.0:
        # (ref: cross_entropy.py:86-103 label smoothing term)
        smooth_loss = lse - mean_logit
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth_loss
    return loss
