"""Vocab-parallel cross entropy.

Reference parity: apex/transformer/tensor_parallel/cross_entropy.py
(_VocabParallelCrossEntropy, :23-131): logits are sharded along vocab over
TP; the softmax-CE is computed with three TP collectives — max (pmax),
sum-exp (psum), and the target-logit partial (psum) — and the BACKWARD is
hand-written (softmax - onehot, :105-130) in the same spirit as the
reference's autograd Function.

INTENTIONAL label-smoothing deviation: the reference rescales the
smoothing coefficient by K/(K-1) and computes the smooth term over the
LOCAL vocab partition (cross_entropy.py:86-103); this implementation
uses ``label_smoothing`` directly with a uniform prior over the GLOBAL
vocab — the textbook formulation, self-consistent between fwd
(``(1-ls)*ce + ls*(lse - mean_logit)``) and bwd (``- ls/V_global``),
and invariant to the TP degree (the reference's local-partition term
changes with tp). Exact-parity porting of the K/(K-1) variant was
rejected, not overlooked.

The backward is a ``custom_vjp``, not autodiff: differentiating through
the forward's psums under ``check_vma=False`` double-counts (the psum
transposes to another psum, so each rank's redundant loss copy
contributes — measured tp x the dense gradient on an 8-way mesh;
tests/test_checked_vma.py::test_vocab_parallel_ce_grads_match_dense
pins the fix against dense grads in BOTH shard_map modes). The hand-written rule is shard-local — no collective
in the backward at all — and its cotangent is typed correctly under
checked vma for free (invarying ct x varying softmax = varying).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.parallel import parallel_state


def _tp_size() -> int:
    if parallel_state.model_parallel_is_initialized():
        return parallel_state.get_tensor_model_parallel_world_size()
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(
    logits_local, target, label_smoothing: float = 0.0, axis_name: str = "tp"
):
    """Per-token CE loss from vocab-sharded logits.

    ``logits_local``: (..., vocab/tp) this rank's shard; ``target``: (...)
    global token ids. Returns fp32 losses shaped like ``target``.
    Reverse-mode only (custom_vjp — same contract as the reference's
    autograd Function); forward-mode transforms (jvp/jacfwd) are not
    supported through this loss.
    """
    loss, _ = _vp_ce_fwd(logits_local, target, label_smoothing, axis_name)
    return loss


def _vp_ce_fwd(logits_local, target, label_smoothing, axis_name):
    tp = _tp_size()
    lf = logits_local.astype(jnp.float32)
    vocab_local = lf.shape[-1]

    if tp == 1:
        lse = jax.scipy.special.logsumexp(lf, axis=-1)
        tlogit = jnp.take_along_axis(lf, target[..., None], axis=-1)[..., 0]
        mean_logit = jnp.mean(lf, axis=-1)
        in_range = jnp.ones(target.shape, bool)
        local_ids = target
    else:
        rank = jax.lax.axis_index(axis_name)
        start = rank * vocab_local
        # global max for stability (ref: allreduce MAX, cross_entropy.py:38)
        gmax = xlax.pmax(jnp.max(lf, axis=-1), axis_name)
        shifted = lf - gmax[..., None]
        sum_exp = xlax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
        lse = jnp.log(sum_exp) + gmax
        # target logit: only the owning rank contributes (ref: masked gather
        # + allreduce, cross_entropy.py:55-77)
        in_range = (target >= start) & (target < start + vocab_local)
        local_ids = jnp.clip(target - start, 0, vocab_local - 1)
        partial = jnp.take_along_axis(lf, local_ids[..., None], axis=-1)[..., 0]
        tlogit = xlax.psum(jnp.where(in_range, partial, 0.0), axis_name)
        mean_logit = xlax.psum(jnp.sum(lf, axis=-1), axis_name) / (
            vocab_local * tp
        )

    loss = lse - tlogit
    if label_smoothing > 0.0:
        # (ref: cross_entropy.py:86-103 label smoothing term)
        smooth_loss = lse - mean_logit
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth_loss
    # softmax of THIS rank's shard (ref: exp_logits saved for backward)
    softmax_local = jnp.exp(lf - lse[..., None])
    # zero-size slice: carries the primal's dtype AND vma type into bwd
    res = (softmax_local, in_range, local_ids, logits_local[..., :0])
    return loss, res


def _vp_ce_bwd(label_smoothing, axis_name, res, ct):
    """d loss / d logit_j = softmax_j - (1-ls) * onehot_j - ls / V
    (ref: cross_entropy.py:105-130) — shard-local, no collectives."""
    softmax_local, in_range, local_ids, probe = res
    vocab_local = softmax_local.shape[-1]
    vocab_global = vocab_local * _tp_size()
    onehot = (
        jax.nn.one_hot(local_ids, vocab_local, dtype=jnp.float32)
        * in_range[..., None]
    )
    g = softmax_local - (1.0 - label_smoothing) * onehot
    if label_smoothing > 0.0:
        g = g - label_smoothing / vocab_global
    g = (g * ct[..., None].astype(jnp.float32)).astype(probe.dtype)
    # integer target takes a float0 cotangent
    return g, np.zeros(local_ids.shape, dtype=jax.dtypes.float0)


vocab_parallel_cross_entropy.defvjp(_vp_ce_fwd, _vp_ce_bwd)
