"""Tensor-parallel collective mappings (autograd-paired collectives).

Reference parity: apex/transformer/tensor_parallel/mappings.py — the six
autograd Functions that define Megatron TP/SP:

| reference (mappings.py)                   | forward            | backward          |
|-------------------------------------------|--------------------|-------------------|
| _CopyToModelParallelRegion (:141)         | identity           | all-reduce        |
| _ReduceFromModelParallelRegion (:159)     | all-reduce         | identity          |
| _ScatterToModelParallelRegion (:177)      | split last dim     | all-gather        |
| _GatherFromModelParallelRegion (:195)     | all-gather last    | split             |
| _ScatterToSequenceParallelRegion (:213)   | split first dim    | all-gather        |
| _GatherFromSequenceParallelRegion (:231)  | all-gather first   | reduce-scatter    |
| _ReduceScatterToSequenceParallelRegion (:253) | reduce-scatter | all-gather        |

TPU design: each is a ``jax.custom_vjp`` over ``lax`` collectives with a mesh
axis name (default 'tp'), usable inside ``shard_map``. Callers (the TP
layers) skip these entirely when the axis has size 1 — same fast path as the
reference's world_size==1 shortcuts; over a size-1 shard_map axis the
collectives themselves are also no-ops.
"""

import functools

import jax

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.parallel.utils import pcast_varying

# -- raw collectives (axis-name-parameterized) ------------------------------
# All collectives go through the xray ledger wrappers (monitor/xray/
# ledger.py) — same primitives, plus trace-time comms accounting. Because
# every op here is a custom_vjp fwd OR bwd rule, a ledger trace of
# jax.grad captures the full TP fwd+bwd collective traffic.


def _split_along_axis(x, axis_name: str, dim: int):
    """Keep this rank's slice of dim (ref: utils.py split_tensor_along_last_dim)."""
    n = xlax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * size, size, axis=dim)


def _all_gather_dim(x, axis_name: str, dim: int):
    return xlax.all_gather(x, axis_name, axis=dim, tiled=True)


def _all_gather_invariant_dim(x, axis_name: str, dim: int):
    """all_gather typed INVARIANT over ``axis_name``: every rank provably
    receives the same gathered array. Under checked shard_map the scatter
    ops' bwd rules owe a cotangent with the PRIMAL input's vma — a
    replicated activation — and the plain ``all_gather`` stays typed
    axis-varying, failing the custom_vjp typecheck (caught by the GPT
    pp x tp x sp integration under default shard_map). Same collective,
    different type; identical under ``check_vma=False``."""
    try:
        # private import: jax exposes no public invariant gather yet —
        # switch to the public API the release it appears
        from jax._src.lax.parallel import all_gather_invariant
    except ImportError:  # older jax: unchecked semantics, plain gather
        return _all_gather_dim(x, axis_name, dim)
    try:
        # no wrapper for the private invariant gather: record it under
        # the same op kind (identical bytes on the wire)
        xlax.record("all_gather", x, axis_name)
        return all_gather_invariant(x, axis_name, axis=dim, tiled=True)
    except TypeError as e:  # signature drift in a future jax release
        raise TypeError(
            "jax._src.lax.parallel.all_gather_invariant's signature "
            "changed; update _all_gather_invariant_dim in "
            "apex_tpu/parallel/mappings.py (falling back to the plain "
            "gather would silently lose the invariant typing checked "
            f"shard_map requires): {e}"
        ) from e


def _reduce_scatter_dim(x, axis_name: str, dim: int):
    return xlax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


def _typed_gather(g, primal_probe, axis_name: str, dim: int):
    """all_gather for a scatter op's bwd, typed to match the PRIMAL:
    the usual replicated primal needs the invariant gather (checked
    shard_map owes an invarying cotangent), but a genuinely axis-varying
    primal — recorded as a zero-size residual slice carrying its vma —
    needs the plain varying gather. Pre-vma jax / check_vma=False reads
    everything unvarying AND accepts either, so plain gather is used."""
    try:
        varying = axis_name in jax.typeof(primal_probe).vma
    except AttributeError:
        varying = True
    if varying:
        return _all_gather_dim(g, axis_name, dim)
    from apex_tpu.parallel.ddp import vma_tracking_live

    if not vma_tracking_live(axis_name):
        return _all_gather_dim(g, axis_name, dim)
    return _all_gather_invariant_dim(g, axis_name, dim)


# -- custom_vjp pairs -------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name="tp"):
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (xlax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name="tp"):
    return xlax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return xlax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    # the primal input was axis-VARYING (per-rank partial sums); the
    # cotangent of the psum'd output arrives invarying, so re-type it
    # (identity under check_vma=False / pre-vma jax, and on numerics)
    return (pcast_varying(g, axis_name),)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name="tp"):
    return _split_along_axis(x, axis_name, -1)


def _scatter_fwd(x, axis_name):
    # zero-size slice: carries the primal's vma TYPE into bwd for free
    return _split_along_axis(x, axis_name, -1), x[..., :0]


def _scatter_bwd(axis_name, res, g):
    return (_typed_gather(g, res, axis_name, g.ndim - 1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name="tp"):
    return _all_gather_dim(x, axis_name, x.ndim - 1)


def _gather_fwd(x, axis_name):
    return _all_gather_dim(x, axis_name, x.ndim - 1), None


def _gather_bwd(axis_name, _, g):
    return (_split_along_axis(g, axis_name, g.ndim - 1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis_name="tp"):
    return _split_along_axis(x, axis_name, 0)


def _scatter_seq_fwd(x, axis_name):
    return _split_along_axis(x, axis_name, 0), x[:0]


def _scatter_seq_bwd(axis_name, res, g):
    return (_typed_gather(g, res, axis_name, 0),)


scatter_to_sequence_parallel_region.defvjp(_scatter_seq_fwd, _scatter_seq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(
    x, axis_name="tp", to_model_parallel=True, defer_sync=False
):
    """SP activation gather (fwd all-gather over the sequence dim).

    ``defer_sync=True`` is the EXPERIMENTAL arXiv:2506.19645 relaxation
    (Tensor-Parallelism with Partially Synchronized Activations), off by
    default: the backward pass SKIPS the cross-rank reduce-scatter and
    keeps only the local shard of the cotangent — the gradient
    synchronization this gather owes is deferred to the surrounding dp
    sync instead of paid per-layer on the tp axis. Gradients become
    approximate (cross-rank activation-grad terms are dropped), so this
    is only sound for syncs the paper's analysis shows are relaxable;
    convergence must be re-pinned per model. The skipped collective is
    neither executed nor ledger-predicted, so the hlo-comms differ stays
    clean either way.
    """
    return _all_gather_dim(x, axis_name, 0)


def _gather_seq_fwd(x, axis_name, to_model_parallel, defer_sync):
    return _all_gather_dim(x, axis_name, 0), None


def _gather_seq_bwd(axis_name, to_model_parallel, defer_sync, _, g):
    if to_model_parallel and not defer_sync:
        return (_reduce_scatter_dim(g, axis_name, 0),)
    # defer_sync relaxation (or plain data movement): local shard only,
    # no cross-rank reduction — zero tp-axis bytes in the backward
    return (_split_along_axis(g, axis_name, 0),)


gather_from_sequence_parallel_region.defvjp(_gather_seq_fwd, _gather_seq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis_name="tp"):
    return _reduce_scatter_dim(x, axis_name, 0)


def _rs_fwd(x, axis_name):
    return _reduce_scatter_dim(x, axis_name, 0), None


def _rs_bwd(axis_name, _, g):
    return (_all_gather_dim(g, axis_name, 0),)


reduce_scatter_to_sequence_parallel_region.defvjp(_rs_fwd, _rs_bwd)
