"""Tensor-parallel collective mappings (autograd-paired collectives).

Reference parity: apex/transformer/tensor_parallel/mappings.py — the six
autograd Functions that define Megatron TP/SP:

| reference (mappings.py)                   | forward            | backward          |
|-------------------------------------------|--------------------|-------------------|
| _CopyToModelParallelRegion (:141)         | identity           | all-reduce        |
| _ReduceFromModelParallelRegion (:159)     | all-reduce         | identity          |
| _ScatterToModelParallelRegion (:177)      | split last dim     | all-gather        |
| _GatherFromModelParallelRegion (:195)     | all-gather last    | split             |
| _ScatterToSequenceParallelRegion (:213)   | split first dim    | all-gather        |
| _GatherFromSequenceParallelRegion (:231)  | all-gather first   | reduce-scatter    |
| _ReduceScatterToSequenceParallelRegion (:253) | reduce-scatter | all-gather        |

TPU design: each is a ``jax.custom_vjp`` over ``lax`` collectives with a mesh
axis name (default 'tp'), usable inside ``shard_map``. Callers (the TP
layers) skip these entirely when the axis has size 1 — same fast path as the
reference's world_size==1 shortcuts; over a size-1 shard_map axis the
collectives themselves are also no-ops.
"""

import functools

import jax

# -- raw collectives (axis-name-parameterized) ------------------------------


def _split_along_axis(x, axis_name: str, dim: int):
    """Keep this rank's slice of dim (ref: utils.py split_tensor_along_last_dim)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * size, size, axis=dim)


def _all_gather_dim(x, axis_name: str, dim: int):
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _reduce_scatter_dim(x, axis_name: str, dim: int):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


# -- custom_vjp pairs -------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name="tp"):
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name="tp"):
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name="tp"):
    return _split_along_axis(x, axis_name, -1)


def _scatter_fwd(x, axis_name):
    return _split_along_axis(x, axis_name, -1), None


def _scatter_bwd(axis_name, _, g):
    return (_all_gather_dim(g, axis_name, g.ndim - 1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name="tp"):
    return _all_gather_dim(x, axis_name, x.ndim - 1)


def _gather_fwd(x, axis_name):
    return _all_gather_dim(x, axis_name, x.ndim - 1), None


def _gather_bwd(axis_name, _, g):
    return (_split_along_axis(g, axis_name, g.ndim - 1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis_name="tp"):
    return _split_along_axis(x, axis_name, 0)


def _scatter_seq_fwd(x, axis_name):
    return _split_along_axis(x, axis_name, 0), None


def _scatter_seq_bwd(axis_name, _, g):
    return (_all_gather_dim(g, axis_name, 0),)


scatter_to_sequence_parallel_region.defvjp(_scatter_seq_fwd, _scatter_seq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, axis_name="tp", to_model_parallel=True):
    return _all_gather_dim(x, axis_name, 0)


def _gather_seq_fwd(x, axis_name, to_model_parallel):
    return _all_gather_dim(x, axis_name, 0), None


def _gather_seq_bwd(axis_name, to_model_parallel, _, g):
    if to_model_parallel:
        return (_reduce_scatter_dim(g, axis_name, 0),)
    return (_split_along_axis(g, axis_name, 0),)


gather_from_sequence_parallel_region.defvjp(_gather_seq_fwd, _gather_seq_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis_name="tp"):
    return _reduce_scatter_dim(x, axis_name, 0)


def _rs_fwd(x, axis_name):
    return _reduce_scatter_dim(x, axis_name, 0), None


def _rs_bwd(axis_name, _, g):
    return (_all_gather_dim(g, axis_name, 0),)


reduce_scatter_to_sequence_parallel_region.defvjp(_rs_fwd, _rs_bwd)
