"""Number-of-microbatches calculators (constant + batch-size rampup).

Reference parity: apex/transformer/microbatches.py —
``ConstantNumMicroBatches`` (:93) and ``RampupBatchsizeNumMicroBatches``
(:112), plus the module-level calculator registry from
pipeline_parallel/utils.py:58 (``setup_microbatch_calculator``,
``get_num_microbatches``, ``get_current_global_batch_size``,
``update_num_microbatches``).

These are pure host-side Python (they gate how many microbatches the
compiled schedule scans over), so the logic carries over almost verbatim in
*semantics*: global_batch_size must divide by micro_batch_size x dp, rampup
grows the global batch linearly in ``batch_size_increment`` steps every
``rampup_samples / steps`` consumed samples.
"""

from typing import List, Optional, Union


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        raise NotImplementedError


class ConstantNumMicroBatchesCalculator(NumMicroBatchesCalculator):
    """Fixed global batch (ref: microbatches.py:93)."""

    def __init__(self, global_batch_size: int, micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel size "
                f"({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        if self.num_micro_batches < 1:
            raise ValueError("number of microbatches must be at least 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        pass


class RampupBatchsizeNumMicroBatchesCalculator(NumMicroBatchesCalculator):
    """Linear batch-size rampup (ref: microbatches.py:112).

    Global batch grows from ``start_batch_size`` to ``global_batch_size`` in
    increments of ``batch_size_increment``, evenly spread over
    ``ramup_samples`` consumed samples.
    """

    def __init__(
        self,
        start_batch_size: int,
        batch_size_increment: int,
        ramup_samples: int,
        global_batch_size: int,
        micro_batch_size: int,
        data_parallel_size: int,
    ):
        super().__init__()
        if global_batch_size <= 0 or start_batch_size <= 0 or batch_size_increment <= 0:
            raise ValueError("batch sizes and increment must be positive")
        if ramup_samples < 0:
            raise ValueError("ramup_samples must be non-negative")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = micro_batch_size * data_parallel_size

        diff_batch_size = global_batch_size - start_batch_size
        if diff_batch_size < 0:
            raise ValueError("global batch size must be >= start batch size")
        if diff_batch_size % batch_size_increment != 0:
            raise ValueError(
                f"expected global batch size interval ({diff_batch_size}) to be divisible "
                f"by the batch size increment ({batch_size_increment})"
            )
        num_increments = diff_batch_size // batch_size_increment
        self.rampup_samples_per_increment = (
            ramup_samples / num_increments if num_increments > 0 else 0
        )
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples > self.ramup_samples or self.rampup_samples_per_increment == 0:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size
            )
        if consistency_check:
            if (
                self.current_global_batch_size % self.micro_batch_times_data_parallel_size
                != 0
            ):
                raise ValueError(
                    f"current global batch size ({self.current_global_batch_size}) is not "
                    f"divisible by micro-batch-size ({self.micro_batch_size}) times "
                    f"data parallel size ({self.data_parallel_size})"
                )
        self.num_micro_batches = max(
            1, self.current_global_batch_size // self.micro_batch_times_data_parallel_size
        )


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> NumMicroBatchesCalculator:
    """(ref: microbatches.py:24 build_num_microbatches_calculator)"""
    if rampup_batch_size is None:
        return ConstantNumMicroBatchesCalculator(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "expected the following format: --rampup-batch-size <start batch size> "
            "<batch size increment> <ramp-up samples>"
        )
    start, incr, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatchesCalculator(
        start, incr, samples, global_batch_size, micro_batch_size, data_parallel_size
    )


# -- module-level registry (ref: pipeline_parallel/utils.py:40-121) ---------

_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """(ref: pipeline_parallel/utils.py:58)"""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size, data_parallel_size
    )


def _calculator() -> NumMicroBatchesCalculator:
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError("num microbatches calculator is not initialized")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_num_microbatches() -> int:
    return _calculator().get()


def get_current_global_batch_size() -> int:
    return _calculator().get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, consistency_check: bool = True) -> None:
    _calculator().update(consumed_samples, consistency_check)


def destroy_num_microbatches_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
