"""Stage-edge communication for pipeline parallelism.

Reference parity: apex/transformer/pipeline_parallel/p2p_communication.py —
``_communicate`` (:168) and the 9 public ops built on it (:385-690):
recv_forward, send_forward, recv_backward, send_backward,
send_forward_recv_backward, send_backward_recv_forward, … The reference
drives dynamic NCCL/UCC isend/irecv pairs (``_run_p2pops``, :48-160) with
shape/dtype negotiation between adjacent stages.

TPU design: every stage edge is a ``jax.lax.ppermute`` over the 'pp' mesh
axis inside ``shard_map``. This eliminates the entire reference machinery:

- shape/dtype negotiation (:200-260): shapes are static under jit;
- FutureTensor async handles (:34): XLA's latency-hiding scheduler overlaps
  the permute with compute automatically;
- batched vs individual isend/irecv (:48-160): one collective either way;
- the "scatter-gather over TP ranks" optimization (:270-330): subsumed by
  sequence-parallel shardings on the tensors themselves.

Conventions: "forward" moves activations to the *next* stage (rank r → r+1,
non-ring: the last stage sends to nobody, the first stage receives zeros);
"backward" moves gradients to the *previous* stage. Autodiff of a ppermute
is the transposed ppermute, so the backward schedule needs no hand-written
edges at all — these backward ops exist for API parity and custom schedules.

All functions are pytree-polymorphic and must be called inside
``shard_map``/``pmap`` over ``axis_name``.
"""

from typing import Any, List, Tuple

import jax

from apex_tpu.monitor.xray import ledger as xlax


# -- the edge grammar --------------------------------------------------------
# Every pipeline edge this module ships is built by one of these four
# constructors. The static collective-safety validator
# (apex_tpu.analysis.collectives) checks traced ppermute edge sets against
# exactly this grammar: linear chains with an interior gap are flagged as
# mismatched send/recv pairs (a stage's input edge fires but the stream
# never reaches it), and anything that is not a partial permutation is
# rejected outright. Build edges through these helpers and the validator
# can never drift from the schedules.


def forward_edges(n: int) -> List[Tuple[int, int]]:
    """Linear +1 chain: rank r sends to r+1; the last rank sends nowhere."""
    return [(i, i + 1) for i in range(n - 1)]


def backward_edges(n: int) -> List[Tuple[int, int]]:
    """Linear -1 chain: rank r sends to r-1; rank 0 sends nowhere."""
    return [(i + 1, i) for i in range(n - 1)]


def ring_edges(n: int) -> List[Tuple[int, int]]:
    """Full ring: every rank sends to (r+1) mod n."""
    return [(i, (i + 1) % n) for i in range(n)]


def last_to_first_edges(n: int) -> List[Tuple[int, int]]:
    """The single wrap edge closing the ring: rank n-1 to rank 0."""
    return [(n - 1, 0)]


def _permute(x: Any, axis_name: str, perm) -> Any:
    # the xray wrapper records each edge's bytes when a comms ledger is
    # tracing (same primitive either way)
    return jax.tree_util.tree_map(
        lambda leaf: xlax.ppermute(leaf, axis_name, perm), x
    )


def _pp_size(axis_name: str):
    return xlax.axis_size(axis_name)


def send_forward_recv_forward(x: Any, axis_name: str = "pp") -> Any:
    """Ship activations one stage downstream (ref ops :385,:421 fused).

    Rank r receives rank r-1's ``x``; rank 0 receives zeros (it will
    overwrite them with fresh microbatch input). The send and recv sides of
    the reference's paired isend/irecv collapse into one ppermute.
    """
    n = _pp_size(axis_name)
    return _permute(x, axis_name, forward_edges(n))


def send_backward_recv_backward(g: Any, axis_name: str = "pp") -> Any:
    """Ship gradients one stage upstream (ref :450): rank r receives rank
    r+1's ``g``; the last stage receives zeros."""
    n = _pp_size(axis_name)
    return _permute(g, axis_name, backward_edges(n))


def ring_forward(x: Any, axis_name: str = "pp") -> Any:
    """Full ring shift: rank r receives rank r-1's ``x``, rank 0 receives
    rank P-1's. The interleaved schedule uses this single collective for
    both edge kinds each tick — same-chunk hops (r → r+1) and the
    chunk-advance wrap (P-1 → 0), which carries a microbatch from chunk v
    on the last rank to chunk v+1 on rank 0."""
    n = _pp_size(axis_name)
    return _permute(x, axis_name, ring_edges(n))


def ring_send_last_to_first(x: Any, axis_name: str = "pp") -> Any:
    """Close the pipeline ring: the last stage's ``x`` arrives at stage 0,
    everyone else receives zeros. Used by the circular (virtual-PP) schedule
    and by embedding-weight sharing between first/last stages (ref:
    parallel_state embedding groups, :319-407)."""
    n = _pp_size(axis_name)
    return _permute(x, axis_name, last_to_first_edges(n))


# -- thin API-parity aliases (ref p2p_communication.py:385-690) -------------
# In an SPMD collective there is no separate send/recv pair: both sides are
# the same ppermute. The split names are kept so schedules read like the
# reference.


def recv_forward(x_sent_upstream: Any, axis_name: str = "pp") -> Any:
    return send_forward_recv_forward(x_sent_upstream, axis_name)


def send_forward(x: Any, axis_name: str = "pp") -> Any:
    return send_forward_recv_forward(x, axis_name)


def recv_backward(g_sent_downstream: Any, axis_name: str = "pp") -> Any:
    return send_backward_recv_backward(g_sent_downstream, axis_name)


def send_backward(g: Any, axis_name: str = "pp") -> Any:
    return send_backward_recv_backward(g, axis_name)
