"""Schedule algebra: predicted tick counts and bubble fractions.

Every schedule in ``schedules.py`` is a compiled scan over clock ticks,
so its cost model is exact combinatorics, not profiling: given P stages,
M microbatches, and V model chunks, the tick counts below are the
lengths of the scans the schedule actually builds, and the bubble
fraction is the share of per-stage wall ticks spent on masked garbage.
This module computes those numbers for every registered schedule so an
overlap claim is checkable BEFORE a device is touched — the predicted
half of the proof loop whose measured half is the timeline analyzer's
per-step idle/bubble (``monitor/xray/timeline``, joined via
``analyze(..., predicted_bubble_fraction=...)``).

Unit convention (the zero-bubble literature's F/B/W decomposition,
arXiv:2401.10241 applied to the compiled-scan formulation): one
microbatch-stage of forward work F, activation-grad work B, and
weight-grad work W each cost ONE unit; a fused backward tick (jax.grad
through the forward scan computes B and W together) costs TWO. Per
stage, one full step is ``M*(F + B + W) = 3M`` useful units.

- ``no_pipelining`` — grad accumulation, no stages: 3M units, no bubble.
- ``1f1b`` — the compiled 1F1B-equivalent: a forward scan of M + P - 1
  ticks (1 unit each) and its differentiated reverse (2 units each);
  span 3(M + P - 1), bubble fraction (P-1)/(M+P-1) — the reference
  pipeline bubble, paid in full.
- ``interleaved`` — virtual PP: both scans stretch to V*M + P - 1 ticks
  of one-chunk work; bubble fraction (P-1)/(V*M+P-1), the 1F1B bubble
  shrunk by 1/V.
- ``zero_bubble`` — the B/W split (``forward_backward_zero_bubble``):
  only F and B sit on the p2p critical path (two M + P - 1 tick scans);
  the M units of W per stage are deferred filler with no edge
  dependence, schedulable into the 2(P-1) bubble slots each stage holds
  across the two scans. Leftover W (max(0, M - 2(P-1)) units) extends
  the span; bubble fraction max(0, 2(P-1) - M) / span — ZERO whenever
  M >= 2(P-1), and strictly below 1F1B's for every M >= 1, P >= 2.

Honesty caveat: these are dependence-graph lower bounds. The compiled
zero-bubble schedule expresses the W-off-the-critical-path dataflow
(dx feeds the edge chain, dp feeds only an accumulator), and XLA's
latency-hiding scheduler decides how much of the predicted filling is
realized on hardware — which is exactly what the timeline analyzer
measures per step. Predicted < measured is a scheduler shortfall;
measured < predicted is impossible (the algebra is the bound).
"""

import dataclasses
from typing import Callable, Dict, List

__all__ = [
    "ScheduleCost",
    "SCHEDULES",
    "schedule_cost",
    "compare",
    "bubble_fraction_1f1b",
]


@dataclasses.dataclass(frozen=True)
class ScheduleCost:
    """Predicted cost of one schedule at (P, M, V), in work units.

    ``forward_ticks``/``backward_ticks`` are the actual scan lengths the
    schedule compiles; ``span_units`` is the per-stage wall span in F/B/W
    units (a fused-backward tick counts 2); ``useful_units`` is always
    3·M·V per rank. The identity ``span_units == useful_units +
    bubble_units`` holds by construction and is test-pinned.
    """

    name: str
    num_stages: int  # P
    num_microbatches: int  # M
    num_model_chunks: int  # V
    forward_ticks: int
    backward_ticks: int
    filler_ticks: int  # trailing deferred-W ticks the bubbles couldn't hold
    span_units: int
    useful_units: int

    @property
    def bubble_units(self) -> int:
        return self.span_units - self.useful_units

    @property
    def bubble_fraction(self) -> float:
        return self.bubble_units / self.span_units if self.span_units else 0.0


def _validate(P: int, M: int, V: int) -> None:
    if P < 1 or M < 1 or V < 1:
        raise ValueError(
            f"schedule algebra needs P, M, V >= 1; got P={P} M={M} V={V}"
        )


def no_pipelining_cost(
    num_stages: int, num_microbatches: int, num_model_chunks: int = 1
) -> ScheduleCost:
    """Grad accumulation: M forward + M fused-backward iterations, no
    stages, no bubble (``forward_backward_no_pipelining``)."""
    P, M, V = num_stages, num_microbatches, num_model_chunks
    _validate(P, M, V)
    return ScheduleCost(
        name="no_pipelining", num_stages=1, num_microbatches=M,
        num_model_chunks=1, forward_ticks=M, backward_ticks=M,
        filler_ticks=0, span_units=3 * M, useful_units=3 * M,
    )


def one_f_one_b_cost(
    num_stages: int, num_microbatches: int, num_model_chunks: int = 1
) -> ScheduleCost:
    """The compiled 1F1B-equivalent
    (``forward_backward_pipelining_without_interleaving``): forward scan
    of M + P - 1 ticks at 1 unit, reversed scan at 2 units (B and W
    fused by the grad transpose). Bubble fraction (P-1)/(M+P-1)."""
    P, M, V = num_stages, num_microbatches, num_model_chunks
    _validate(P, M, V)
    T = M + P - 1
    return ScheduleCost(
        name="1f1b", num_stages=P, num_microbatches=M, num_model_chunks=1,
        forward_ticks=T, backward_ticks=T, filler_ticks=0,
        span_units=3 * T, useful_units=3 * M,
    )


def interleaved_cost(
    num_stages: int, num_microbatches: int, num_model_chunks: int = 2
) -> ScheduleCost:
    """Virtual PP (``forward_backward_pipelining_with_interleaving``):
    one scan of V*M + P - 1 one-chunk ticks per direction; P - 1 of them
    are bubble, so the fraction shrinks by 1/V. Requires M % P == 0, as
    the schedule itself asserts."""
    P, M, V = num_stages, num_microbatches, num_model_chunks
    _validate(P, M, V)
    if V < 2:
        raise ValueError(
            f"interleaved schedule needs num_model_chunks >= 2 (got {V}): "
            f"V=1 is just 1F1B, and silently computing its bubble here "
            f"would mislabel the prediction"
        )
    if M % P != 0:
        raise ValueError(
            f"interleaved schedule requires num_microbatches ({M}) % "
            f"pipeline size ({P}) == 0"
        )
    T = V * M + P - 1
    return ScheduleCost(
        name="interleaved", num_stages=P, num_microbatches=M,
        num_model_chunks=V, forward_ticks=T, backward_ticks=T,
        filler_ticks=0, span_units=3 * T, useful_units=3 * M * V,
    )


def zero_bubble_cost(
    num_stages: int, num_microbatches: int, num_model_chunks: int = 1
) -> ScheduleCost:
    """The B/W split (``forward_backward_zero_bubble``): F and B each
    run an M + P - 1 tick scan on the p2p critical path; every stage
    holds P - 1 bubble slots in each, and the M deferred-W units fill
    them. W the 2(P-1) slots can't hold runs as trailing filler ticks.

    span = 2(M+P-1) + max(0, M - 2(P-1)); bubble = max(0, 2(P-1) - M).
    Zero bubble at M >= 2(P-1); always < 1F1B's (P-1)/(M+P-1).
    """
    P, M, V = num_stages, num_microbatches, num_model_chunks
    _validate(P, M, V)
    T = M + P - 1
    slots = 2 * (P - 1)  # per-stage bubble slots across the F and B scans
    filler = max(0, M - slots)
    return ScheduleCost(
        name="zero_bubble", num_stages=P, num_microbatches=M,
        num_model_chunks=1, forward_ticks=T, backward_ticks=T,
        filler_ticks=filler, span_units=2 * T + filler,
        useful_units=3 * M,
    )


#: registered schedule cost models — keys are the names the bench
#: section and the timeline join use
SCHEDULES: Dict[str, Callable[..., ScheduleCost]] = {
    "no_pipelining": no_pipelining_cost,
    "1f1b": one_f_one_b_cost,
    "interleaved": interleaved_cost,
    "zero_bubble": zero_bubble_cost,
}


def schedule_cost(
    name: str,
    num_stages: int,
    num_microbatches: int,
    num_model_chunks: int = 1,
) -> ScheduleCost:
    """Cost of one registered schedule at (P, M, V)."""
    if name not in SCHEDULES:
        raise KeyError(
            f"unknown schedule {name!r}; registered: {sorted(SCHEDULES)}"
        )
    return SCHEDULES[name](num_stages, num_microbatches, num_model_chunks)


def compare(
    num_stages: int, num_microbatches: int, num_model_chunks: int = 2
) -> List[ScheduleCost]:
    """Every registered schedule's cost at one (P, M, V), bubble-sorted
    (best first) — the table the bench section prints and the docs
    quote. The interleaved row is skipped when M % P != 0 (the schedule
    itself would refuse that shape)."""
    out = []
    for name in SCHEDULES:
        try:
            out.append(schedule_cost(
                name, num_stages, num_microbatches, num_model_chunks
            ))
        except ValueError:
            continue
    return sorted(out, key=lambda c: (c.bubble_fraction, c.name))


def bubble_fraction_1f1b(num_stages: int, num_microbatches: int) -> float:
    """The classic (P-1)/(M+P-1) — the number every zero-bubble claim is
    measured against."""
    _validate(num_stages, num_microbatches, 1)
    return (num_stages - 1) / (num_microbatches + num_stages - 1)
