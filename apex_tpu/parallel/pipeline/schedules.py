"""Pipeline schedules as compiled collective programs.

Reference parity: apex/transformer/pipeline_parallel/schedules/ —
- forward_backward_no_pipelining (fwd_bwd_no_pipelining.py:23),
- 1F1B without interleaving (fwd_bwd_pipelining_without_interleaving.py:241),
- interleaved 1F1B over virtual-PP model chunks
  (fwd_bwd_pipelining_with_interleaving.py:27),
- get_forward_backward_func dispatcher (schedules/__init__.py:22),
- build_model with pre/post_process flags (schedules/common.py:30).

TPU design. The reference's schedules are host Python loops issuing dynamic
NCCL p2p ops per microbatch (warmup / steady-1F1B / cooldown phases with
wait handles). Under XLA everything inside jit is traced once and compiled,
so the schedule becomes a ``lax.scan`` over T = M + P - 1 clock ticks inside
``shard_map`` over the 'pp' mesh axis:

- at tick t, stage s computes microbatch t - s (bubble ticks compute masked
  garbage — the SPMD cost of the (P-1)/(M+P-1) pipeline bubble, identical
  to the reference's bubble fraction);
- stage edges are a single ``ppermute`` (p2p.py);
- the BACKWARD schedule is not written at all: ``jax.grad`` through the
  scan reverses it tick-for-tick (ppermute transposes into the opposite
  edge), yielding the same reversed-pipeline order the reference hand-codes
  in its cooldown/steady phases;
- 1F1B's purpose is bounding stashed activations to P microbatches; here
  per-tick ``jax.checkpoint`` on the stage body keeps live memory to the
  scan carry (one microbatch) plus per-tick boundary activations, the same
  asymptotics;
- the interleaved schedule maps virtual-PP chunk v on rank r to global
  stage v*P + r exactly like the reference's chunk-id mapping
  (fwd_bwd_pipelining_with_interleaving.py:221-259), executed as ONE scan
  over V*M + P - 1 ticks of one-chunk work each — bubble fraction
  (P-1)/(V*M + P - 1), the non-interleaved bubble shrunk by 1/V
  (see pipeline_forward_interleaved).

All schedule functions must run inside ``shard_map`` over ``axis_name``.
``stage_fn(params, x) -> y`` must be shape-uniform (y like x); embedding /
loss heads live outside the scan (pre_process/post_process in build_model).

Static validation: every edge these schedules ship is built from the
p2p edge grammar (p2p.forward_edges/backward_edges/ring_edges/
last_to_first_edges), and the trace-time collective-safety validator
(``apex_tpu.analysis.collectives``) checks traced schedules against it —
non-permutation edge sets and gapped chains (a stage whose input edge
fires while its feeder edge is missing: the static deadlock) are
findings. One honest caveat, as with the comms ledger: the BACKWARD
schedule's reversed edges are synthesized by jax's transpose rules and
never appear in a forward trace, so the validator sees them only when
the traced function includes ``jax.grad`` of the scan (the fwd+bwd
program), which all ``forward_backward_*`` entry points here do.

ZERO-BUBBLE (B/W split). ``forward_backward_zero_bubble`` (and its
pre/post twin) hand-write the backward pipeline instead of deriving it
from ``jax.grad``: the backward pass splits into B (activation-grad:
``dx``, the only value the reversed p2p chain carries) and W
(weight-grad: ``dp``, which feeds nothing but a local accumulator).
Expressing that split in the program's dataflow is what lets XLA's
latency-hiding scheduler fill each backward tick's edge-transfer wait
with W compute instead of idling — the compiled-scan realization of
zero-bubble scheduling (arXiv:2401.10241), whose predicted tick counts
and bubble fractions live in ``algebra.py`` and whose realized bubble
the timeline analyzer measures. Two structural consequences:

- the reversed edges are REAL ``p2p.send_backward_recv_backward`` calls,
  so the comms ledger predicts the backward pp traffic exactly (the
  transpose blind spot above closes for this schedule) and the HLO
  differ can match every emitted permute to a prediction;
- memory: the forward scan stashes its per-tick stage inputs AND outputs
  (2 boundary activations x T ticks — the deferred-W stash, vs the
  remat'd 1F1B's 1 x T carry residuals), and each backward tick
  recomputes the stage forward once inside its vjp, exactly the remat
  trade the fused path already pays.
"""

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.parallel.pipeline import p2p


def _leading_dim(tree: Any) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        raise ValueError("empty microbatch pytree")
    return leaves[0].shape[0]


def _index(tree: Any, i) -> Any:
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree
    )


def _varying_zeros(out_shape, axis_name: str):
    """Zero boundary-activation carry whose varying-manual-axes type is a
    FIXED POINT of the tick body: the stage output's vma (carried by the
    ``jax.eval_shape`` avals under checked shard_map — dp-varying data,
    tp-varying params, ...) plus ``axis_name`` (the in-scan ppermute makes
    the received edge pp-varying even when nothing else is). An unvarying
    zeros carry fails the scan typecheck the first time the body returns
    a varying value. ``pcast`` is a no-op under ``check_vma=False``."""

    from apex_tpu.parallel.utils import pcast_varying

    def one(s):
        z = jnp.zeros(s.shape, s.dtype)
        axes = set(getattr(s, "vma", ()) or ()) | {axis_name}
        return pcast_varying(z, tuple(sorted(axes)))

    return jax.tree_util.tree_map(one, out_shape)


def _scan_ticks(tick, state0, num_ticks: int, tick_block_remat: int):
    """Scan ``tick`` over ``num_ticks`` ticks, optionally rematerializing in
    blocks: with ``tick_block_remat = B > 0`` the scan nests — an outer scan
    over ceil(T/B) blocks whose body (an inner B-tick scan) is
    ``jax.checkpoint``ed, so differentiation stashes one carry per BLOCK
    instead of per tick: live boundary-activation memory drops from O(T) to
    O(T/B + B) at the cost of one forward recompute of each block — the
    knob that restores the reference 1F1B's O(P) in-flight bound
    (fwd_bwd_pipelining_without_interleaving.py:345-348) for large M.

    Returns (final_state, ys) like ``lax.scan``; padding ticks (to fill the
    last block) run the pipeline beyond its useful range, and callers index
    only real ticks out of ``ys``.
    """
    if tick_block_remat and 0 < tick_block_remat < num_ticks:
        # B >= T degenerates to one checkpointed block: every padding tick
        # runs a real ppermute + stage computation for zero residual
        # savings, so fall through to the plain scan instead
        B = tick_block_remat
        nblocks = -(-num_ticks // B)

        @jax.checkpoint
        def block(carry, tblock):
            return jax.lax.scan(tick, carry, tblock)

        ticks = jnp.arange(nblocks * B).reshape(nblocks, B)
        # the tick body traces ONCE but runs nblocks*B times (padding
        # ticks included — they ship real edges); the xray comms ledger
        # weighs its collectives accordingly
        with xlax.scaled(nblocks * B):
            state, ys = jax.lax.scan(block, state0, ticks)
        # un-block the stacked outputs: (nblocks, B, ...) -> (nblocks*B, ...)
        ys = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), ys
        )
        return state, ys
    with xlax.scaled(num_ticks):
        return jax.lax.scan(tick, state0, jnp.arange(num_ticks))


def pipeline_forward(
    stage_fn: Callable[[Any, Any], Any],
    params: Any,
    microbatches: Any,
    *,
    axis_name: str = "pp",
    remat: bool = True,
    tick_block_remat: int = 0,
) -> Any:
    """Run M microbatches through the P-stage compiled pipeline.

    ``microbatches``: pytree with leading dim M (stage-0 input; only the
    first stage reads it, so it may be garbage elsewhere). Returns a pytree
    with leading dim M of last-stage outputs — *valid on the last stage
    only* (other stages hold bubble garbage), mirroring how the reference's
    forward_step returns losses only on the final stage (common.py:296-309).

    Memory: the scan carry is ONE boundary activation; per-tick outputs are
    scan ys (microbatch m exits at the statically-known tick m + P - 1, so
    collecting them is a static slice, not a carried M-slot buffer — keeping
    the buffer in the carry would make every tick's residual O(M)).
    ``tick_block_remat`` bounds the per-tick residuals for large M
    (_scan_ticks).
    """
    num_stages = xlax.axis_size(axis_name)  # static inside shard_map
    rank = jax.lax.axis_index(axis_name)
    num_micro = _leading_dim(microbatches)
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    mb0 = _index(microbatches, 0)
    with xlax.muted():  # shape probe, not part of the compiled program
        out_shape = jax.eval_shape(stage_fn, params, mb0)
    state0 = _varying_zeros(out_shape, axis_name)

    def tick(state, t):
        # named scopes are the per-phase timing taps: they label the HLO
        # ops, so profiler captures (monitor.ProfilerTrigger, utils.trace)
        # attribute each tick's time to edge-transfer vs stage compute
        with jax.named_scope("pp_p2p"):
            recv = p2p.send_forward_recv_forward(state, axis_name)
        mb = _index(microbatches, jnp.clip(t, 0, num_micro - 1))
        is_first = rank == 0
        x = jax.tree_util.tree_map(
            lambda a, b: jnp.where(is_first, a, b), mb, recv
        )
        with jax.named_scope("pp_stage"):
            y = body(params, x)
        return y, y

    num_ticks = num_micro + num_stages - 1
    _, ys = _scan_ticks(tick, state0, num_ticks, tick_block_remat)
    # microbatch m's last-stage output was produced at tick m + (P-1)
    return jax.tree_util.tree_map(
        lambda a: jax.lax.slice_in_dim(a, num_stages - 1, num_ticks, axis=0),
        ys,
    )


def pipeline_forward_interleaved(
    stage_fn: Callable[[Any, Any], Any],
    params_chunks: Any,
    microbatches: Any,
    *,
    num_model_chunks: int,
    axis_name: str = "pp",
    remat: bool = True,
    tick_block_remat: int = 0,
) -> Any:
    """Genuinely interleaved virtual-PP forward: ONE scan over
    T = V*M + P - 1 ticks, one chunk-computation per rank per tick.

    Chunk v on rank r implements global stage v*P + r (the reference's
    chunk-id map, fwd_bwd_pipelining_with_interleaving.py:221-259), and the
    per-rank work order is the reference's group-of-P depth-first pattern:
    microbatch group k = (kP..kP+P-1) runs chunks 0..V-1 before group k+1
    starts. Rank r processes, at tick t with u = t - r:
        k = u // (V*P), v = (u % (V*P)) // P, m = k*P + u % P.
    Each produced activation is consumed exactly one tick later by the next
    global stage — same-chunk hop (rank r+1) or the ring wrap (rank 0,
    chunk v+1) — so every tick ships ONE ring ppermute.

    Per-tick work is one chunk = 1/V of a rank's layers, and only P - 1 of
    the V*M + P - 1 ticks are bubble — bubble fraction (P-1)/(V*M + P - 1),
    i.e. the reference's ≈(P-1)/M shrunk by 1/V, unlike V sequential passes
    (V*(M + P - 1) ticks, bubble unchanged). Requires M % P == 0, as the
    reference asserts (:118).

    Returns last-stage outputs (leading dim M), valid on rank P-1 only.

    Memory: like ``pipeline_forward``, the carry is one boundary activation
    and outputs are scan ys gathered post-scan — on the last rank,
    microbatch m (group k = m // P, slot i = m % P) clears the final global
    stage at the statically-known tick k*V*P + (V-1)*P + i + (P-1), so the
    gather indices are a host-side constant.
    """
    num_stages = xlax.axis_size(axis_name)  # static inside shard_map
    rank = jax.lax.axis_index(axis_name)
    num_micro = _leading_dim(microbatches)
    V = num_model_chunks
    if num_micro % num_stages != 0:
        raise ValueError(
            f"interleaved schedule requires num_microbatches ({num_micro}) "
            f"% pipeline size ({num_stages}) == 0"
        )
    def chunk_fn(chunks, v, x):
        # the chunk gather lives INSIDE the rematerialized body: saved as a
        # residual it would cost one full chunk's params PER TICK — measured
        # 133 MiB vs 2 MiB at M=128 on the toy config (BENCH.md, pipeline
        # memory table); rematerialized it costs nothing extra
        pv = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
            chunks,
        )
        return stage_fn(pv, x)

    body = jax.checkpoint(chunk_fn) if remat else chunk_fn

    mb0 = _index(microbatches, 0)
    with xlax.muted():  # shape probe, not part of the compiled program
        out_shape = jax.eval_shape(body, params_chunks, 0, mb0)
    state0 = _varying_zeros(out_shape, axis_name)

    def tick(state, t):
        # per-phase profiler taps, as in pipeline_forward
        with jax.named_scope("pp_p2p"):
            recv = p2p.ring_forward(state, axis_name)
        u = t - rank
        uc = jnp.clip(u, 0, V * num_micro - 1)
        v = (uc % (V * num_stages)) // num_stages
        m = (uc // (V * num_stages)) * num_stages + uc % num_stages
        # fresh input only where the stream enters the model: rank 0, chunk 0
        takes_input = (rank == 0) & (v == 0)
        mb = _index(microbatches, m)
        x = jax.tree_util.tree_map(
            lambda a, b: jnp.where(takes_input, a, b), mb, recv
        )
        with jax.named_scope("pp_stage"):
            y = body(params_chunks, v, x)
        return y, y

    num_ticks = V * num_micro + num_stages - 1
    _, ys = _scan_ticks(tick, state0, num_ticks, tick_block_remat)
    # exit tick of microbatch m on the last rank (u = t - (P-1)):
    #   u_out = (m // P)*V*P + (V-1)*P + (m % P)
    ms = jnp.arange(num_micro)
    exit_ticks = (
        (ms // num_stages) * V * num_stages
        + (V - 1) * num_stages
        + ms % num_stages
        + num_stages
        - 1
    )
    return jax.tree_util.tree_map(lambda a: a[exit_ticks], ys)


def _stages_forward(
    stage_fn, stages_params, h, *, axis_name: str, remat: bool,
    num_model_chunks: int, tick_block_remat: int = 0,
):
    """Forward through this rank's chunk(s): the plain pipeline for V=1,
    the single-scan interleaved schedule for V>1."""
    if num_model_chunks == 1:
        return pipeline_forward(
            stage_fn, stages_params, h, axis_name=axis_name, remat=remat,
            tick_block_remat=tick_block_remat,
        )
    return pipeline_forward_interleaved(
        stage_fn, stages_params, h, num_model_chunks=num_model_chunks,
        axis_name=axis_name, remat=remat, tick_block_remat=tick_block_remat,
    )


def _publish_losses(per_microbatch_losses, axis_name: str):
    """Mask bubble garbage off non-final stages, publish the mean loss and
    the per-microbatch losses from the last stage to every stage."""
    num_stages = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    losses = jnp.where(rank == num_stages - 1, per_microbatch_losses, 0.0)
    loss = _last_stage_mean_loss(losses, axis_name)
    return loss, xlax.psum(losses, axis_name)


def _last_stage_mean_loss(per_microbatch_losses, axis_name: str):
    """Average per-microbatch losses and publish from the last stage to all
    stages (ref: losses divided by num_microbatches on the last stage,
    common.py:305-309; other stages return nothing)."""
    num_stages = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    mean = jnp.mean(per_microbatch_losses)
    local = jnp.where(rank == num_stages - 1, mean, 0.0)
    # Publish the value via psum but keep only the LOCAL term on the grad
    # path: psum's transpose would re-sum the replicated cotangent and
    # scale grads by P. With the local term, the loss cotangent enters the
    # graph once (on the last stage) and the ppermute transposes carry it
    # back through every stage exactly as the reference's backward phases.
    return local + jax.lax.stop_gradient(
        xlax.psum(local, axis_name) - local
    )


# -- zero-bubble (B/W split) -------------------------------------------------


def _zb_forward_scan(
    stage_fn, params, microbatches, *, axis_name: str, remat: bool,
    tick_block_remat: int,
):
    """The zero-bubble forward pass: ``pipeline_forward``'s tick loop,
    additionally stashing every tick's stage INPUT (the value the
    backward scan's per-tick vjp replays — the deferred-W stash).

    Returns ``(xs, outs)``: ``xs`` with leading dim T = M + P - 1 (this
    stage's input at each tick, bubble ticks included), ``outs`` with
    leading dim M (last-stage outputs, valid on the last stage only).
    """
    num_stages = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    num_micro = _leading_dim(microbatches)
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    mb0 = _index(microbatches, 0)
    with xlax.muted():  # shape probe, not part of the compiled program
        out_shape = jax.eval_shape(stage_fn, params, mb0)
    state0 = _varying_zeros(out_shape, axis_name)

    def tick(state, t):
        with jax.named_scope("pp_p2p"):
            recv = p2p.send_forward_recv_forward(state, axis_name)
        mb = _index(microbatches, jnp.clip(t, 0, num_micro - 1))
        is_first = rank == 0
        x = jax.tree_util.tree_map(
            lambda a, b: jnp.where(is_first, a, b), mb, recv
        )
        with jax.named_scope("pp_stage"):
            y = body(params, x)
        return y, (x, y)

    num_ticks = num_micro + num_stages - 1
    _, (xs, ys) = _scan_ticks(tick, state0, num_ticks, tick_block_remat)
    outs = jax.tree_util.tree_map(
        lambda a: jax.lax.slice_in_dim(a, num_stages - 1, num_ticks, axis=0),
        ys,
    )
    return xs, outs


def _zb_backward_scan(stage_fn, params, xs, seed, *, axis_name: str,
                      num_micro: int):
    """The hand-written backward pipeline: a reverse-clock scan of
    T = M + P - 1 ticks whose tick body splits B from W.

    At reverse tick q every stage replays its forward tick t = T - 1 - q
    (the backward schedule is the forward's exact mirror: stage s handled
    microbatch m = t - s there, so the reversal needs no per-stage index
    algebra — only the shared clock flips). The tick:

    - receives the downstream cotangent over a REAL backward edge
      (``send_backward_recv_backward`` — ledger-recorded, unlike the
      transpose-synthesized edges of the ``jax.grad`` path);
    - the last stage swaps in its own loss seed for the microbatch that
      exited at t;
    - one ``jax.vjp`` replay of the stage yields both halves, but only
      ``dx`` (B) enters the carried edge chain — ``dp`` (W) feeds the
      grad accumulator, a dataflow XLA's latency-hiding scheduler is
      free to move into the edge-transfer wait (the zero-bubble filling;
      ``algebra.zero_bubble_cost`` is its tick-count model);
    - bubble ticks (this stage outside its valid window) contribute
      exact zeros to both halves.

    Returns ``(stage_grads, dxs)`` where ``dxs`` (leading dim T) holds
    each tick's masked ``dx`` — stage 0's entries are the cotangents of
    its microbatch inputs, which the pre/post variant feeds to the
    embedding vjp.
    """
    num_stages = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    num_ticks = num_micro + num_stages - 1

    x0 = _index(xs, 0)
    with xlax.muted():  # shape probes only
        out_shape = jax.eval_shape(stage_fn, params, x0)
        p_shape = jax.eval_shape(lambda p: p, params)
    d0 = _varying_zeros(out_shape, axis_name)
    g0 = _varying_zeros(p_shape, axis_name)

    def btick(carry, q):
        dprev, gacc = carry
        with jax.named_scope("pp_p2p_bwd"):
            recv = p2p.send_backward_recv_backward(dprev, axis_name)
        t = num_ticks - 1 - q
        x = _index(xs, t)
        # the microbatch exiting the LAST stage at forward tick t seeds
        # its loss cotangent here; everyone else consumes the edge
        m = t - (num_stages - 1)
        seed_m = _index(seed, jnp.clip(m, 0, num_micro - 1))
        is_seed = (rank == num_stages - 1) & (m >= 0) & (m < num_micro)
        dy = jax.tree_util.tree_map(
            lambda s, r: jnp.where(is_seed, s, r), seed_m, recv
        )
        # this stage's valid window mirrors the forward's: u = t - rank
        u = t - rank
        valid = (u >= 0) & (u < num_micro)
        with jax.named_scope("pp_stage_bwd"):
            _, pull = jax.vjp(stage_fn, params, x)
            dp, dx = pull(dy)
        dx = jax.tree_util.tree_map(
            lambda a: jnp.where(valid, a, jnp.zeros_like(a)), dx
        )
        dp = jax.tree_util.tree_map(
            lambda a: jnp.where(valid, a, jnp.zeros_like(a)), dp
        )
        gacc = jax.tree_util.tree_map(jnp.add, gacc, dp)
        return (dx, gacc), dx

    with xlax.scaled(num_ticks):
        (_, grads), dxs = jax.lax.scan(
            btick, (d0, g0), jnp.arange(num_ticks)
        )
    return grads, dxs


def _loss_seed_cotangent(num_micro: int, axis_name: str):
    """d(published mean loss)/d(per-microbatch losses): 1/M on the last
    stage (only its losses reach the mean — ``_last_stage_mean_loss``
    keeps just the local term on the grad path), zero elsewhere."""
    num_stages = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    return jnp.where(
        rank == num_stages - 1,
        jnp.full((num_micro,), 1.0 / num_micro),
        jnp.zeros((num_micro,)),
    )


def forward_backward_zero_bubble(
    stage_fn: Callable[[Any, Any], Any],
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    params: Any,
    microbatches: Any,
    targets: Any,
    *,
    axis_name: str = "pp",
    remat: bool = True,
    tick_block_remat: int = 0,
    grad_sync_fn: Optional[Callable[[Any], Any]] = None,
):
    """Zero-bubble-style schedule: same signature and same gradients as
    ``forward_backward_pipelining_without_interleaving``, backward
    hand-written with the B/W split (module docstring). Tick counts and
    the predicted bubble fraction: ``algebra.zero_bubble_cost(P, M)``.
    """
    num_micro = _leading_dim(microbatches)
    xs, outs = _zb_forward_scan(
        stage_fn, params, microbatches, axis_name=axis_name, remat=remat,
        tick_block_remat=tick_block_remat,
    )
    losses, loss_pull = jax.vjp(
        lambda o: jax.vmap(loss_fn)(o, targets), outs
    )
    loss, losses_pub = _publish_losses(losses, axis_name)
    (douts,) = loss_pull(_loss_seed_cotangent(num_micro, axis_name))
    grads, _ = _zb_backward_scan(
        stage_fn, params, xs, douts, axis_name=axis_name,
        num_micro=num_micro,
    )
    if grad_sync_fn is not None:
        grads = grad_sync_fn(grads)
    return loss, losses_pub, grads


def forward_backward_zero_bubble_with_pre_post(
    pre_fn: Callable[[Any, Any], Any],
    stage_fn: Callable[[Any, Any], Any],
    post_loss_fn: Callable[[Any, Any, Any], jnp.ndarray],
    params: Any,
    inputs: Any,
    targets: Any,
    *,
    axis_name: str = "pp",
    remat: bool = True,
    tick_block_remat: int = 0,
    grad_sync_fn: Optional[Callable[[Any], Any]] = None,
):
    """``forward_backward_with_pre_post`` with the zero-bubble backward:
    embedding + stages + head in one B/W-split program, gradients equal
    to the fused path's.

    The pre/post halves ride the stage machinery: the head's loss vjp
    provides the last-stage seeds, and stage 0's per-tick ``dx`` stash
    IS the embedding-output cotangent (microbatch m's entry lands at
    reverse tick (M-1-m) + (P-1), a host-side constant), so the
    embedding vjp needs no extra pipeline pass. Replicated pre/post
    grads are combined over pp exactly as in the fused variant.
    """
    num_stages = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    num_micro = _leading_dim(inputs)

    def pre_all(pre):
        with jax.named_scope("pp_pre"):
            return jax.vmap(lambda mb: pre_fn(pre, mb))(inputs)

    h, pre_pull = jax.vjp(pre_all, params["pre"])
    xs, outs = _zb_forward_scan(
        stage_fn, params["stages"], h, axis_name=axis_name, remat=remat,
        tick_block_remat=tick_block_remat,
    )

    def post_all(post, o):
        with jax.named_scope("pp_post"):
            return jax.vmap(
                lambda y, t: post_loss_fn(post, y, t)
            )(o, targets)

    losses, post_pull = jax.vjp(post_all, params["post"], outs)
    loss, losses_pub = _publish_losses(losses, axis_name)
    dpost, douts = post_pull(_loss_seed_cotangent(num_micro, axis_name))
    stage_grads, dxs = _zb_backward_scan(
        stage_fn, params["stages"], xs, douts, axis_name=axis_name,
        num_micro=num_micro,
    )
    # microbatch m entered stage 0 at forward tick m, i.e. reverse tick
    # (T-1) - m = (M-1-m) + (P-1) — static gather indices for dL/dh
    qs = (num_micro - 1 - jnp.arange(num_micro)) + (num_stages - 1)
    dh = jax.tree_util.tree_map(lambda a: a[qs], dxs)
    # only stage 0 consumed h; its dx rows are the real cotangents
    dh = jax.tree_util.tree_map(
        lambda a: jnp.where(rank == 0, a, jnp.zeros_like(a)), dh
    )
    (dpre,) = pre_pull(dh)

    grads = {
        "pre": _combine_replicated_grads(dpre, axis_name),
        "stages": stage_grads,
        "post": _combine_replicated_grads(dpost, axis_name),
    }
    if grad_sync_fn is not None:
        grads = grad_sync_fn(grads)
    return loss, losses_pub, grads


def _combine_replicated_grads(tree, axis_name: str):
    """Combine pp-replicated params' grads (nonzero on one rank only)
    onto every rank — the tied-embedding allreduce semantics, with the
    checked-shard_map dispatch of ``forward_backward_with_pre_post``:
    under live vma tracking the transpose already psummed replicated
    leaves, and a second psum would multiply by P."""
    from apex_tpu.parallel.ddp import grads_already_reduced, vma_tracking_live

    tracking = vma_tracking_live(axis_name)

    def one(g):
        if grads_already_reduced(g, axis_name, tracking):
            return g
        return xlax.psum(g, axis_name)

    return jax.tree_util.tree_map(one, tree)


def forward_backward_no_pipelining(
    forward_step_fn: Callable[[Any, Any], jnp.ndarray],
    params: Any,
    microbatches: Any,
    *,
    grad_sync_fn: Optional[Callable[[Any], Any]] = None,
):
    """Gradient accumulation over microbatches, no pipeline (ref:
    fwd_bwd_no_pipelining.py:23).

    ``forward_step_fn(params, microbatch) -> scalar loss``. Gradients are
    accumulated across all microbatches and synchronized ONCE at the end via
    ``grad_sync_fn`` (e.g. a dp psum) — the reference's "no_sync on all but
    the last microbatch" semantics (:37-48). Returns
    ``(mean_loss, per_microbatch_losses, grads)``.
    """
    num_micro = _leading_dim(microbatches)
    grad_fn = jax.value_and_grad(forward_step_fn)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)

    def body(acc, mb):
        loss, g = grad_fn(params, mb)
        acc = jax.tree_util.tree_map(jnp.add, acc, g)
        return acc, loss

    grads, losses = jax.lax.scan(body, zeros, microbatches)
    grads = jax.tree_util.tree_map(lambda g: g / num_micro, grads)
    if grad_sync_fn is not None:
        grads = grad_sync_fn(grads)
    return jnp.mean(losses), losses, grads


def forward_backward_pipelining_without_interleaving(
    stage_fn: Callable[[Any, Any], Any],
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    params: Any,
    microbatches: Any,
    targets: Any,
    *,
    axis_name: str = "pp",
    remat: bool = True,
    tick_block_remat: int = 0,
    grad_sync_fn: Optional[Callable[[Any], Any]] = None,
):
    """Compiled 1F1B-equivalent schedule (ref:
    fwd_bwd_pipelining_without_interleaving.py:241).

    ``loss_fn(last_stage_output, target) -> scalar`` is applied per
    microbatch on the last stage; the mean loss is psum-published so every
    stage returns the same scalar. Returns
    ``(loss, per_microbatch_losses, grads)`` where ``grads`` matches this
    stage's ``params`` — the backward pipeline (warmup/steady/cooldown of
    the reference) emerges from differentiating the forward scan.
    """
    def total_loss(p):
        outs = pipeline_forward(
            stage_fn, p, microbatches, axis_name=axis_name, remat=remat,
            tick_block_remat=tick_block_remat,
        )
        return _publish_losses(jax.vmap(loss_fn)(outs, targets), axis_name)

    (loss, losses), grads = jax.value_and_grad(total_loss, has_aux=True)(params)
    if grad_sync_fn is not None:
        grads = grad_sync_fn(grads)
    return loss, losses, grads


def forward_backward_pipelining_with_interleaving(
    stage_fn: Callable[[Any, Any], Any],
    loss_fn: Callable[[Any, Any], jnp.ndarray],
    params_chunks: Any,
    microbatches: Any,
    targets: Any,
    *,
    num_model_chunks: int,
    axis_name: str = "pp",
    remat: bool = True,
    tick_block_remat: int = 0,
    grad_sync_fn: Optional[Callable[[Any], Any]] = None,
):
    """Virtual-pipeline (interleaved) schedule (ref:
    fwd_bwd_pipelining_with_interleaving.py:27).

    ``params_chunks`` carries a leading dim V = num_model_chunks on every
    leaf: this stage's V model chunks, where chunk v on rank r implements
    global stage v*P + r — the reference's chunk-id mapping (:221-259). The
    microbatch stream makes V circular passes over the P ranks, chained by
    a last→first ring edge, so the layer order is exactly the reference's
    interleaved assignment.
    """
    def total_loss(chunks):
        outs = _stages_forward(
            stage_fn, chunks, microbatches, axis_name=axis_name,
            remat=remat, num_model_chunks=num_model_chunks,
            tick_block_remat=tick_block_remat,
        )
        return _publish_losses(jax.vmap(loss_fn)(outs, targets), axis_name)

    (loss, losses), grads = jax.value_and_grad(total_loss, has_aux=True)(
        params_chunks
    )
    if grad_sync_fn is not None:
        grads = grad_sync_fn(grads)
    return loss, losses, grads


def forward_backward_with_pre_post(
    pre_fn: Callable[[Any, Any], Any],
    stage_fn: Callable[[Any, Any], Any],
    post_loss_fn: Callable[[Any, Any, Any], jnp.ndarray],
    params: Any,
    inputs: Any,
    targets: Any,
    *,
    axis_name: str = "pp",
    remat: bool = True,
    num_model_chunks: int = 1,
    tick_block_remat: int = 0,
    grad_sync_fn: Optional[Callable[[Any], Any]] = None,
):
    """Full-model pipeline step: embedding + stages + head in one backward.

    ``params`` is a dict ``{"pre": …, "stages": …, "post": …}``:
    - ``pre`` (e.g. the embedding) and ``post`` (final norm + head/loss)
      are REPLICATED across pp ranks; only stage 0 / the last stage's
      compute reaches the loss, so their raw grads are nonzero on one rank
      only — they are psum-synced over pp afterwards, which is exactly the
      reference's first/last-stage embedding-group grad allreduce for tied
      embeddings (parallel_state.py:319-407 embedding groups);
    - ``stages`` holds this rank's chunk params (leading dim V when
      ``num_model_chunks`` > 1, chunk v = global stage v*P + rank).

    ``pre_fn(pre_params, input_mb) -> h``; ``stage_fn(chunk_params, h) ->
    h``; ``post_loss_fn(post_params, h, target_mb) -> scalar``. Returns
    ``(loss, per_microbatch_losses, grads)`` with grads matching
    ``params``.
    """
    def total_loss(p):
        # pre/stages/post named scopes: the per-phase breakdown a profiler
        # capture shows for the full pipelined step
        with jax.named_scope("pp_pre"):
            h = jax.vmap(lambda mb: pre_fn(p["pre"], mb))(inputs)
        outs = _stages_forward(
            stage_fn, p["stages"], h, axis_name=axis_name, remat=remat,
            num_model_chunks=num_model_chunks,
            tick_block_remat=tick_block_remat,
        )
        with jax.named_scope("pp_post"):
            losses = jax.vmap(
                lambda y, t: post_loss_fn(p["post"], y, t)
            )(outs, targets)
        return _publish_losses(losses, axis_name)

    (loss, losses), grads = jax.value_and_grad(total_loss, has_aux=True)(params)
    # replicated pre/post params: combine the single contributing rank's
    # grads onto every rank (tied-embedding allreduce semantics) — the
    # shared vma-dispatched helper the zero-bubble variant also uses
    grads = dict(grads)
    grads["pre"] = _combine_replicated_grads(grads["pre"], axis_name)
    grads["post"] = _combine_replicated_grads(grads["post"], axis_name)
    if grad_sync_fn is not None:
        grads = grad_sync_fn(grads)
    return loss, losses, grads


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int],
    pipeline_model_parallel_size: int,
    zero_bubble: bool = False,
) -> Callable:
    """Schedule dispatcher (ref: schedules/__init__.py:22): interleaved iff
    virtual PP is set, 1F1B iff PP > 1, else plain grad accumulation.
    ``zero_bubble=True`` swaps the 1F1B schedule for the B/W-split
    ``forward_backward_zero_bubble`` (same signature, same gradients;
    predicted bubble per ``algebra.zero_bubble_cost``). Virtual PP has
    no zero-bubble variant yet — the combination raises."""
    if virtual_pipeline_model_parallel_size is not None:
        if pipeline_model_parallel_size <= 1:
            raise ValueError(
                "virtual pipeline parallelism requires pipeline_model_parallel_size > 1"
            )
        if zero_bubble:
            raise ValueError(
                "zero_bubble has no interleaved variant: pick virtual PP "
                "(bubble/V) or the B/W split, not both"
            )
        return functools.partial(
            forward_backward_pipelining_with_interleaving,
            num_model_chunks=virtual_pipeline_model_parallel_size,
        )
    if pipeline_model_parallel_size > 1:
        if zero_bubble:
            return forward_backward_zero_bubble
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def build_model(
    model_provider_func: Callable[..., Any],
    pipeline_rank: int,
    pipeline_world_size: int,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    **kwargs,
) -> List[Any]:
    """Construct this pipeline stage's model chunk(s) with pre/post flags
    (ref: schedules/common.py:30-108).

    ``model_provider_func(pre_process=..., post_process=..., **kwargs)``
    builds one chunk; ``pre_process`` is True only for global stage 0
    (owns the embedding), ``post_process`` only for the final global stage
    (owns the head/loss) — the reference's flags at common.py:83-108. With
    virtual PP, chunk v on rank r is global stage v*P + r, so rank 0 chunk 0
    gets pre_process and rank P-1 chunk V-1 gets post_process.

    Host-side helper: in SPMD there is no per-process rank, so the caller
    names the stage being built (e.g. when stacking per-stage params for a
    'pp'-sharded leading axis).
    """
    v = virtual_pipeline_model_parallel_size or 1
    chunks = []
    for chunk_id in range(v):
        global_stage = chunk_id * pipeline_world_size + pipeline_rank
        pre = global_stage == 0
        post = global_stage == v * pipeline_world_size - 1
        chunks.append(
            model_provider_func(pre_process=pre, post_process=post, **kwargs)
        )
    return chunks
