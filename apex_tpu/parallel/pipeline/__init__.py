"""Pipeline parallelism over the 'pp' mesh axis.

Reference parity: apex/transformer/pipeline_parallel — p2p_communication.py
(stage edges), schedules/ (no-pipelining, 1F1B, interleaved), microbatches.py
(constant + batch-size-rampup calculators), utils.py (microbatch calculator
registry).

TPU design (see schedules.py docstring): schedules are *compiled* collective
programs — a ``lax.scan`` over clock ticks with ``ppermute`` stage edges
inside ``shard_map`` — instead of the reference's host-driven loops over
dynamic NCCL p2p ops. The backward schedule is not hand-written at all: it is
``jax.grad`` differentiating through the scan, which reverses every
``ppermute`` edge automatically.
"""

from apex_tpu.parallel.pipeline.microbatches import (
    ConstantNumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatchesCalculator,
    build_num_microbatches_calculator,
    setup_microbatch_calculator,
    get_num_microbatches,
    get_current_global_batch_size,
    update_num_microbatches,
    destroy_num_microbatches_calculator,
)
from apex_tpu.parallel.pipeline.p2p import (
    send_forward,
    recv_forward,
    send_backward,
    recv_backward,
    send_forward_recv_forward,
    send_backward_recv_backward,
    ring_forward,
    ring_send_last_to_first,
)
from apex_tpu.parallel.pipeline.schedules import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    forward_backward_with_pre_post,
    forward_backward_zero_bubble,
    forward_backward_zero_bubble_with_pre_post,
    get_forward_backward_func,
    pipeline_forward,
    pipeline_forward_interleaved,
    build_model,
)
from apex_tpu.parallel.pipeline.algebra import (
    ScheduleCost,
    SCHEDULES,
    schedule_cost,
    compare as compare_schedules,
    bubble_fraction_1f1b,
)

__all__ = [
    "ConstantNumMicroBatchesCalculator",
    "RampupBatchsizeNumMicroBatchesCalculator",
    "build_num_microbatches_calculator",
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "destroy_num_microbatches_calculator",
    "send_forward",
    "recv_forward",
    "send_backward",
    "recv_backward",
    "send_forward_recv_forward",
    "send_backward_recv_backward",
    "ring_forward",
    "ring_send_last_to_first",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_with_pre_post",
    "forward_backward_zero_bubble",
    "forward_backward_zero_bubble_with_pre_post",
    "get_forward_backward_func",
    "pipeline_forward",
    "pipeline_forward_interleaved",
    "build_model",
    "ScheduleCost",
    "SCHEDULES",
    "schedule_cost",
    "compare_schedules",
    "bubble_fraction_1f1b",
]
