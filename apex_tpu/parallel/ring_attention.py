"""Context parallelism: ring attention + Ulysses all-to-all attention.

The reference has NO context/ring parallelism (SURVEY.md §2.5: its
long-context story tops out at Megatron sequence parallelism plus a
seq<=512 fused MHA kernel, contrib/fmha). This module is the long-context
subsystem the build brief makes first-class: sequence-sharded exact
attention over the 'cp' mesh axis, scaling max context length linearly in
the number of chips.

Two strategies, both exact:

- **Ring attention** (`ring_attention`): every rank keeps its query chunk;
  K/V chunks rotate around the cp ring via ``ppermute`` while an online
  (flash-style) softmax accumulates in fp32. Each ring step processes the
  visiting K/V chunk in ``block_size`` slices through an inner ``lax.scan``
  with the same online-softmax update, so local memory is
  O(s_local x block_size) — never the full (s_local, s_local) score matrix.
  The backward is NOT autodiff through the forward scan (which would stash
  every rotated K/V — O(cp) memory): a ``custom_vjp`` runs a second ring
  pass that recomputes probabilities blockwise from the saved logsumexp and
  rotates dK/dV accumulators *with* their chunks. The first ring step uses
  the resident chunk, so each pass issues exactly P-1 forward rotations
  (plus one homing rotation in backward), and XLA's latency-hiding
  scheduler overlaps each step's ppermute with the next step's matmuls.
- **Ulysses** (`ulysses_attention`): two ``all_to_all``s repartition
  sequence-sharded activations to head-sharded, run the full-sequence
  Pallas flash kernel locally, and repartition back. Cheaper collectives
  for moderate contexts; requires heads % cp == 0 (and kv_heads % cp == 0
  under GQA).

Both strategies take GQA/MQA-grouped K/V (heads % kv_heads == 0; the ring
rotates the grouped heads — heads/kv_heads x less ICI traffic than
repeating before the ring) and a sequence-sharded ``key_padding_mask``
whose local shard rotates/gathers with its keys; an all-padded visiting
chunk is skipped like an out-of-band one.

Causal handling in the ring: masks and chunk skipping are driven by GLOBAL
position vectors (``_positions``/``_band_keep``), so chunk layout is a
parameter. Contiguous layout keeps the classic behavior — chunk j vs local
queries of rank i: (j < i) full, (j == i) causal, (j > i) skipped entirely
(``_chunk_contributes`` + ``lax.cond``; sliding windows additionally skip
chunks behind the band) — but late ranks do more work per lockstep
rotation. ``zigzag=True`` (with ``zigzag_shard``-prepared inputs) gives
every rank one early and one late sequence piece, equalizing per-rotation
causal work across ranks.
"""

import functools
import math

import jax
import jax.numpy as jnp

from apex_tpu.monitor.xray import ledger as xlax

_NEG_INF = -1e30


def _rotate(tree, axis_name: str):
    """Move every leaf one rank down the ring (rank r -> r+1 mod P)."""
    n = xlax.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda x: xlax.ppermute(x, axis_name, perm), tree
    )


def _positions(src, num_ranks, s_local: int, zigzag: bool):
    """(s_local,) GLOBAL sequence positions of rank ``src``'s chunk
    (``src`` may be traced).

    - contiguous (zigzag=False): rank r holds rows [r*s, (r+1)*s).
    - zigzag: the sequence is cut into 2P pieces and rank r holds pieces
      (r, 2P-1-r) concatenated — the causal-ring load balance: every rank
      owns one early and one late piece, so per-rotation work is equal
      instead of growing with rank index."""
    if not zigzag:
        return src * s_local + jnp.arange(s_local, dtype=jnp.int32)
    half = s_local // 2
    base = jnp.arange(half, dtype=jnp.int32)
    return jnp.concatenate([
        src * half + base,
        (2 * num_ranks - 1 - src) * half + base,
    ])


def _band_keep(rows, cols, causal: bool, window=None):
    """Keep-mask (len(rows), len(cols)) from GLOBAL positions, or None when
    nothing is masked. One band definition for both chunk layouts."""
    if not causal and window is None:
        return None
    r = rows[:, None]
    c = cols[None, :]
    keep = jnp.bool_(True)
    if causal:
        keep = jnp.logical_and(keep, c <= r)
    if window is not None:
        keep = jnp.logical_and(keep, c > r - window)
    return keep


def _chunk_contributes(rows, cols, causal: bool, window, pieces: int = 1):
    """Whether the visiting chunk's band intersects the local queries —
    the chunk is SKIPPED entirely (lax.cond) otherwise, making a windowed
    ring cost O(window + sq) keys per rank instead of O(seq).

    ``pieces`` is the number of CONTIGUOUS position runs per chunk (1
    contiguous, 2 zigzag). Bounds are evaluated per piece pair — a single
    min/max over a split zigzag chunk would span nearly the whole
    sequence and never skip anything, losing the windowed ring's
    O(window) scaling. Within a piece positions ascend, so min/max are
    its end elements."""
    if window is None and not causal:
        return jnp.bool_(True)
    r = rows.reshape(pieces, -1)
    c = cols.reshape(pieces, -1)
    rmin, rmax = r[:, 0], r[:, -1]
    cmin, cmax = c[:, 0], c[:, -1]
    pair_ok = jnp.ones((pieces, pieces), bool)
    if causal:
        pair_ok = jnp.logical_and(pair_ok, cmin[None, :] <= rmax[:, None])
    if window is not None:
        pair_ok = jnp.logical_and(
            pair_ok, cmax[None, :] > rmin[:, None] - window
        )
    return jnp.any(pair_ok)


def _chunk_block_size(s_local: int, block_size: int) -> int:
    bk = min(block_size, s_local)
    while s_local % bk != 0:  # s_local is a power-of-two-ish shard; cheap
        bk -= 1
    return bk


def _allow_mask(rows, cols_b, causal, window, keep_b):
    """Combined (sq, bk) band mask x (b, bk) key-validity mask, broadcast
    to the grouped score shape (b, G, g, sq, bk); None when unmasked."""
    band = _band_keep(rows, cols_b, causal, window)
    allow = None
    if band is not None:
        allow = band[None, None, None]
    if keep_b is not None:
        kb = keep_b[:, None, None, None, :]
        allow = kb if allow is None else jnp.logical_and(allow, kb)
    return allow


def _online_chunk_update(state, q, kc, vc, scale, rows, cols, causal,
                         block_size, window=None, keep=None):
    """Stream one visiting K/V chunk through the online softmax in
    ``block_size`` slices. state = (acc, m, l) accumulated so far;
    ``rows``/``cols`` are the global positions of the local queries and
    the visiting keys (any layout).

    ``q`` is GQA-grouped (b, h_kv, g, sq, d) against kc/vc (b, h_kv, s, d)
    — grouped K/V means the ring rotates h_kv heads, not h (g x less ICI
    traffic than repeating K/V before the ring).  ``keep`` is the visiting
    chunk's (b, s_kv) key-validity mask (False = padded-out key).

    Dot operands KEEP the input dtype (bf16 stays bf16) with fp32
    accumulation — upcasting before the einsum forces the MXU's slow fp32
    path (same policy as ops/attention.py); softmax math stays fp32."""
    s_kv = kc.shape[-2]
    bk = _chunk_block_size(s_kv, block_size)
    num_blocks = s_kv // bk
    from apex_tpu.parallel.utils import promote_to_vma

    state = promote_to_vma(state, rows)

    def block_step(carry, j):
        acc, m, l = carry
        lo = j * bk
        kb = jax.lax.dynamic_slice_in_dim(kc, lo, bk, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vc, lo, bk, axis=2)
        s = (
            jnp.einsum("bGgqd,bGkd->bGgqk", q, kb,
                       preferred_element_type=jnp.float32)
            * scale
        )
        allow = _allow_mask(
            rows, jax.lax.dynamic_slice_in_dim(cols, lo, bk, axis=0),
            causal, window,
            None if keep is None
            else jax.lax.dynamic_slice_in_dim(keep, lo, bk, axis=1),
        )
        if allow is not None:
            s = jnp.where(allow, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if allow is not None:
            p = jnp.where(allow, p, 0.0)  # exp(-inf - (-inf)) guard
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bGgqk,bGkd->bGgqd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
        )
        return (acc_new, m_new, l_new), None

    if num_blocks == 1:
        state, _ = block_step(state, jnp.int32(0))
        return state
    state, _ = jax.lax.scan(block_step, state, jnp.arange(num_blocks))
    return state


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _ring(q, k, v, kbias, axis_name, causal, scale, block_size, window, zigzag):
    o, _ = _ring_fwd_res(
        q, k, v, kbias, axis_name, causal, scale, block_size, window, zigzag
    )
    return o


def _bias_placeholder(b: int, axis_name: str):
    """Rotatable stand-in for a None key-padding bias in the ring scan
    carry — typed varying so it survives the in-scan ppermute's vma under
    checked shard_map (identity under check_vma=False / pre-vma jax)."""
    from apex_tpu.parallel.utils import pcast_varying

    return pcast_varying(jnp.zeros((b, 0)), axis_name)


def _keep_from_bias(kbias):
    """(b, s) float bias (0 valid / _NEG_INF padded) -> bool validity mask.
    The bias is float (not bool) only so it can ride the custom_vjp as a
    differentiable primal with a zero cotangent."""
    return None if kbias is None else kbias > 0.5 * _NEG_INF


def _ring_fwd_res(q, k, v, kbias, axis_name, causal, scale, block_size,
                  window, zigzag):
    num_ranks = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    q5 = q.reshape(b, h_kv, g, sq, d)
    rows = _positions(rank, num_ranks, sq, zigzag)
    keep0 = _keep_from_bias(kbias)

    init_state = (
        jnp.zeros((b, h_kv, g, sq, d), jnp.float32),
        jnp.full((b, h_kv, g, sq), _NEG_INF, jnp.float32),
        jnp.zeros((b, h_kv, g, sq), jnp.float32),
    )
    # step 0 on the resident chunk — no rotation needed
    state = _online_chunk_update(
        init_state, q5, k, v, scale, rows, rows, causal, block_size, window,
        keep0,
    )

    def step(carry, t):
        (kc, vc, biasc), state = carry
        kc, vc, biasc = _rotate((kc, vc, biasc), axis_name)
        src = jax.lax.rem(rank - t + num_ranks, num_ranks)
        cols = _positions(src, num_ranks, sq, zigzag)
        # trace-time None check: with no kpm the carry holds a (b, 0)
        # placeholder, which must NOT become an all-False keep mask
        keep_c = _keep_from_bias(biasc) if kbias is not None else None
        contributes = _chunk_contributes(rows, cols, causal, window,
                                         2 if zigzag else 1)
        if keep_c is not None:
            # an all-padded visiting chunk is skipped like an out-of-band one
            contributes = jnp.logical_and(contributes, jnp.any(keep_c))
        state = jax.lax.cond(
            contributes,
            lambda st: _online_chunk_update(
                st, q5, kc, vc, scale, rows, cols, causal, block_size,
                window, keep_c,
            ),
            lambda st: st,
            state,
        )
        return ((kc, vc, biasc), state), None

    if num_ranks > 1:
        bias_carry = (kbias if kbias is not None
                      else _bias_placeholder(b, axis_name))
        # in-scan ppermutes make every carried leaf axis-varying; promote
        # the initial carry so its type is already the fixed point even
        # when the caller's q/k/v arrive axis-replicated (per-leaf no-op
        # when already varying / under check_vma=False)
        from apex_tpu.parallel.utils import pvary_params

        carry0 = pvary_params(((k, v, bias_carry), state), axis_name)
        # the rotation traces once but runs P-1 times (comms accounting)
        with xlax.scaled(num_ranks - 1):
            ((_, _, _), state), _ = jax.lax.scan(
                step, carry0, jnp.arange(1, num_ranks)
            )
    acc, m, l = state
    l = jnp.maximum(l, 1e-30)
    o = (acc / l[..., None]).reshape(b, h, sq, d).astype(q.dtype)
    lse = m + jnp.log(l)  # (b, h_kv, g, sq)
    return o, (q, k, v, kbias, o, lse)


def _chunk_bwd_update(q, do, delta, lse, kc, vc, dkc, dvc, dq, scale, rows,
                      cols, causal, block_size, window=None, keep=None):
    """Blockwise gradient contributions of one visiting K/V chunk.
    GQA-grouped like _online_chunk_update (q/do/delta/lse carry the
    (b, h_kv, g, ...) layout; kc/vc/dkc/dvc the (b, h_kv, ...) one).
    Operand-dtype policy as in _online_chunk_update; dkc/dvc/dq accumulate
    in fp32."""
    s_kv = kc.shape[-2]
    bk = _chunk_block_size(s_kv, block_size)
    num_blocks = s_kv // bk
    from apex_tpu.parallel.utils import promote_to_vma

    dkc, dvc, dq = promote_to_vma((dkc, dvc, dq), rows)

    def block_step(carry, j):
        dkc, dvc, dq = carry
        lo = j * bk
        kb = jax.lax.dynamic_slice_in_dim(kc, lo, bk, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vc, lo, bk, axis=2)
        s = (
            jnp.einsum("bGgqd,bGkd->bGgqk", q, kb,
                       preferred_element_type=jnp.float32)
            * scale
        )
        allow = _allow_mask(
            rows, jax.lax.dynamic_slice_in_dim(cols, lo, bk, axis=0),
            causal, window,
            None if keep is None
            else jax.lax.dynamic_slice_in_dim(keep, lo, bk, axis=1),
        )
        if allow is not None:
            s = jnp.where(allow, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])
        if allow is not None:
            p = jnp.where(allow, p, 0.0)
        dv_b = jnp.einsum(
            "bGgqk,bGgqd->bGkd", p.astype(do.dtype), do,
            preferred_element_type=jnp.float32,
        )
        dp = jnp.einsum(
            "bGgqd,bGkd->bGgqk", do, vb, preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[..., None]) * scale
        ds_lo = ds.astype(kb.dtype)
        dq = dq + jnp.einsum(
            "bGgqk,bGkd->bGgqd", ds_lo, kb, preferred_element_type=jnp.float32
        )
        dk_b = jnp.einsum(
            "bGgqk,bGgqd->bGkd", ds_lo, q, preferred_element_type=jnp.float32
        )
        dkc = jax.lax.dynamic_update_slice_in_dim(
            dkc, jax.lax.dynamic_slice_in_dim(dkc, lo, bk, 2) + dk_b, lo, 2
        )
        dvc = jax.lax.dynamic_update_slice_in_dim(
            dvc, jax.lax.dynamic_slice_in_dim(dvc, lo, bk, 2) + dv_b, lo, 2
        )
        return (dkc, dvc, dq), None

    if num_blocks == 1:
        (dkc, dvc, dq), _ = block_step((dkc, dvc, dq), jnp.int32(0))
    else:
        (dkc, dvc, dq), _ = jax.lax.scan(
            block_step, (dkc, dvc, dq), jnp.arange(num_blocks)
        )
    return dkc, dvc, dq


def _ring_bwd(axis_name, causal, scale, block_size, window, zigzag, res, do):
    q, k, v, kbias, o, lse = res
    num_ranks = xlax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    q5 = q.reshape(b, h_kv, g, sq, d)
    do5 = do.reshape(b, h_kv, g, sq, d)
    o5 = o.reshape(b, h_kv, g, sq, d)
    rows = _positions(rank, num_ranks, sq, zigzag)
    keep0 = _keep_from_bias(kbias)
    delta = jnp.sum(
        do5.astype(jnp.float32) * o5.astype(jnp.float32), axis=-1
    )  # (b, h_kv, g, sq)

    zeros_k = jnp.zeros(k.shape, jnp.float32)
    zeros_v = jnp.zeros(v.shape, jnp.float32)
    dq0 = jnp.zeros(q5.shape, jnp.float32)
    # step 0 on the resident chunk
    dk0, dv0, dq = _chunk_bwd_update(
        q5, do5, delta, lse, k, v, zeros_k, zeros_v, dq0, scale, rows, rows,
        causal, block_size, window, keep0,
    )

    def step(carry, t):
        (kc, vc, biasc, dkc, dvc), dq = carry
        # dK/dV ride the ring with their chunks
        kc, vc, biasc, dkc, dvc = _rotate(
            (kc, vc, biasc, dkc, dvc), axis_name
        )
        src = jax.lax.rem(rank - t + num_ranks, num_ranks)
        cols = _positions(src, num_ranks, sq, zigzag)
        keep_c = _keep_from_bias(biasc) if kbias is not None else None
        contributes = _chunk_contributes(rows, cols, causal, window,
                                         2 if zigzag else 1)
        if keep_c is not None:
            contributes = jnp.logical_and(contributes, jnp.any(keep_c))
        dkc, dvc, dq = jax.lax.cond(
            contributes,
            lambda ops: _chunk_bwd_update(
                q5, do5, delta, lse, kc, vc, ops[0], ops[1], ops[2], scale,
                rows, cols, causal, block_size, window, keep_c,
            ),
            lambda ops: ops,
            (dkc, dvc, dq),
        )
        return ((kc, vc, biasc, dkc, dvc), dq), None

    bias_carry = (kbias if kbias is not None
                  else _bias_placeholder(b, axis_name))
    carry = ((k, v, bias_carry, dk0, dv0), dq)
    if num_ranks > 1:
        from apex_tpu.parallel.utils import pvary_params

        carry = pvary_params(carry, axis_name)  # see fwd: carry fixed point
        with xlax.scaled(num_ranks - 1):  # see fwd: P-1 rotations
            carry, _ = jax.lax.scan(step, carry, jnp.arange(1, num_ranks))
    (kc, vc, _, dk, dv), dq = carry
    # one homing rotation: after P-1 rotations the accumulators sit one rank
    # short of their owners
    if num_ranks > 1:
        dk, dv = _rotate((dk, dv), axis_name)
    dkbias = None if kbias is None else jnp.zeros_like(kbias)
    return (dq.reshape(b, h, sq, d).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype), dkbias)


_ring.defvjp(_ring_fwd_res, _ring_bwd)


def ring_attention(
    q,
    k,
    v,
    axis_name: str = "cp",
    causal: bool = False,
    scale: float = None,
    block_size: int = 512,
    window: int = None,
    zigzag: bool = False,
    key_padding_mask=None,
):
    """Exact sequence-sharded attention over the ``axis_name`` ring.

    q: (batch, heads, seq_local, head_dim); k, v: (batch, kv_heads,
    seq_local, head_dim) with heads % kv_heads == 0 (GQA/MQA: the ring
    rotates the GROUPED K/V, heads/kv_heads x less ICI traffic than
    repeating keys before the ring) — the local chunk of a sequence
    sharded over the cp axis. Call inside ``shard_map``.
    ``block_size`` bounds the K/V slice processed at once (local memory
    O(seq_local x block_size)). Returns the local output chunk; grads flow
    through a second ring pass (see module docstring).

    ``window`` (sliding-window, causal only) bands attention in GLOBAL
    positions across the ring's chunks — long-context mistral-style
    attention sharded over cp.

    ``key_padding_mask``: (batch, seq_local) bool, True = padded-out key —
    the LOCAL shard of the global padding mask, sharded exactly like k/v
    (zigzag-reordered with ``zigzag_shard`` when zigzag=True). It rotates
    around the ring with its K/V chunk, and an all-padded visiting chunk
    is skipped entirely like an out-of-band one.

    ``zigzag`` (causal load balance): shards carry pieces (r, 2P-1-r) of
    the sequence instead of contiguous chunks — prepare them with
    ``zigzag_shard`` and restore outputs with ``zigzag_unshard``. Under
    contiguous causal sharding, rank r touches r+1 chunks per pass while
    the masks kill the rest, so late ranks dominate the lockstep ring;
    zigzag gives every rank one early and one late piece, equalizing
    per-rotation work (~2x less wasted compute at large P).
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True (mistral semantics)")
    if zigzag and q.shape[-2] % 2:
        raise ValueError("zigzag needs an even per-rank sequence length")
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"heads ({q.shape[1]}) not divisible by kv_heads ({k.shape[1]})"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    kbias = None
    if key_padding_mask is not None:
        if key_padding_mask.shape != (q.shape[0], k.shape[2]):
            raise ValueError(
                f"key_padding_mask {key_padding_mask.shape} != "
                f"(batch, seq_local) = {(q.shape[0], k.shape[2])}"
            )
        # float carrier (0 valid / -inf padded) so the mask can be a
        # differentiable custom_vjp primal with a zero cotangent
        kbias = jnp.where(key_padding_mask, _NEG_INF, 0.0).astype(jnp.float32)
    return _ring(q, k, v, kbias, axis_name, causal, scale, block_size,
                 window, zigzag)


def _zigzag_index(s: int, num_ranks: int):
    """Permutation placing pieces (r, 2P-1-r) consecutively for each r —
    the single source of the zigzag order for shard AND unshard."""
    if s % (2 * num_ranks):
        raise ValueError(
            f"sequence ({s}) not divisible by 2*cp ({2 * num_ranks})"
        )
    half = s // (2 * num_ranks)
    return jnp.concatenate([
        jnp.concatenate([
            r * half + jnp.arange(half),
            (2 * num_ranks - 1 - r) * half + jnp.arange(half),
        ])
        for r in range(num_ranks)
    ])


def zigzag_shard(x, num_ranks: int, axis: int = -2):
    """Reorder a GLOBAL sequence axis so a contiguous cp shard hands rank r
    the zigzag pieces (r, 2P-1-r). Apply before sharding inputs (and to
    targets/position ids that must stay aligned); invert with
    ``zigzag_unshard``."""
    return jnp.take(x, _zigzag_index(x.shape[axis], num_ranks), axis=axis)


def zigzag_unshard(x, num_ranks: int, axis: int = -2):
    """Inverse of ``zigzag_shard`` on the same global axis."""
    inv = jnp.argsort(_zigzag_index(x.shape[axis], num_ranks))
    return jnp.take(x, inv, axis=axis)


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str = "cp",
    causal: bool = False,
    scale: float = None,
    window: int = None,
    attn_fn=None,
    key_padding_mask=None,
):
    """DeepSpeed-Ulysses-style attention: all-to-all from sequence-sharded
    to head-sharded, full-sequence local attention, all-to-all back.

    q: (batch, heads, seq_local, head_dim); k, v may carry fewer (GQA)
    heads — both counts must be divisible by the cp size (each rank keeps
    whole query groups, so the local attention stays a plain GQA call).
    ``attn_fn(q, k, v, causal=..., scale=...)`` defaults to the Pallas
    flash kernel. The two all_to_alls transpose to their own inverses
    under autodiff, so no custom backward is needed.

    ``key_padding_mask``: (batch, seq_local) bool local shard (True =
    padded) — all-gathered over cp (cheap: bytes per key, vs the d-dim
    K/V that ride the all_to_alls) so each head-sharded rank masks the
    full sequence it now sees.
    """
    if attn_fn is None:
        from apex_tpu.ops.attention import flash_attention

        attn_fn = flash_attention
    num_ranks = xlax.axis_size(axis_name)  # static inside shard_map
    assert q.shape[1] % num_ranks == 0, (
        f"heads ({q.shape[1]}) not divisible by cp size ({num_ranks}); "
        "use ring_attention for head counts below the cp degree"
    )
    assert k.shape[1] % num_ranks == 0, (
        f"kv_heads ({k.shape[1]}) not divisible by cp size ({num_ranks}); "
        "use ring_attention for grouped-KV head counts below the cp degree"
    )

    # With cp=1 this degrades to plain attention.
    def to_heads(x):
        # (b, h, s_loc, d) -> (b, h/P, s_glob, d)
        return xlax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def to_seq(x):
        return xlax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    # heads are sharded but each rank sees the FULL sequence, so the local
    # attention supports windows natively
    kw = {} if window is None else {"window": window}
    if key_padding_mask is not None:
        kw["key_padding_mask"] = xlax.all_gather(
            key_padding_mask, axis_name, axis=1, tiled=True
        )
    oh = attn_fn(qh, kh, vh, causal=causal, scale=scale, **kw)
    return to_seq(oh)


def cp_decode_attention(q, k, v, padded, axis_name: str, scale=None):
    """Single-token decode attention over a context-parallel KV cache.

    The decode-time counterpart of :func:`ring_attention` (extension — the
    reference has no inference path): each rank holds a shard of the KV
    cache, the one new query token is replicated over ``axis_name``, and
    the per-rank partial softmax stats merge with the flash/ring
    log-sum-exp identity via one ``pmax`` + two ``psum``s.  Per decode
    step that is O(1) collective latency instead of re-gathering the
    cache, and each rank's compute is O(L_local) — long-context decode
    scales across the mesh exactly like the ring trains it.

    Args:
      q: (b, h, 1, d), replicated over ``axis_name``.
      k, v: (b, h_kv, L_local, d) — this rank's cache shard (GQA: h must
        be a multiple of h_kv; consecutive grouping, q_head // g).
      padded: (b, L_local) bool, True = slot holds no valid key (unwritten
        tail, out-of-window, or another rank's turn in a round-robin
        layout).
      scale: softmax scale, default 1/sqrt(d) (flash_attention's default).

    Returns (b, h, 1, d), replicated over ``axis_name``.
    """
    b, h, sq, d = q.shape
    if sq != 1:
        raise ValueError(f"cp_decode_attention is single-token (sq={sq})")
    h_kv = k.shape[1]
    if h % h_kv:
        raise ValueError(f"GQA heads {h} not a multiple of kv heads {h_kv}")
    g = h // h_kv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32).reshape(b, h_kv, g, d)
    s = jnp.einsum("bhgd,bhld->bhgl", qf, k.astype(jnp.float32)) * scale
    pad = padded[:, None, None, :]
    s = jnp.where(pad, _NEG_INF, s)
    m = jnp.max(s, axis=-1, keepdims=True)  # (b, h_kv, g, 1)
    p = jnp.where(pad, 0.0, jnp.exp(s - m))  # all-padded shard: p == 0
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhgl,bhld->bhgd", p, v.astype(jnp.float32))
    m_g = xlax.pmax(m, axis_name)
    alpha = jnp.exp(m - m_g)  # -> 0 for shards far below the global max
    l_g = xlax.psum(l * alpha, axis_name)
    o_g = xlax.psum(o * alpha, axis_name) / l_g
    return o_g.reshape(b, h, 1, d).astype(q.dtype)
