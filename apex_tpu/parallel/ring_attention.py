"""Context parallelism: ring attention + Ulysses all-to-all attention.

The reference has NO context/ring parallelism (SURVEY.md §2.5: its
long-context story tops out at Megatron sequence parallelism plus a
seq<=512 fused MHA kernel, contrib/fmha). This module is the long-context
subsystem the build brief makes first-class: sequence-sharded exact
attention over the 'cp' mesh axis, scaling max context length linearly in
the number of chips.

Two strategies, both exact:

- **Ring attention** (`ring_attention`): every rank keeps its query chunk;
  K/V chunks rotate around the cp ring via ``ppermute`` while an online
  (flash-style) softmax accumulates in fp32. The backward is NOT autodiff
  through the forward scan (which would stash every rotated K/V — O(cp)
  memory): a ``custom_vjp`` runs a second ring pass that recomputes
  attention probabilities from the saved logsumexp and rotates dK/dV
  accumulators *with* their chunks, so memory stays O(local) and the
  compiler overlaps each step's ppermute with the next step's matmuls
  (the TPU analogue of ring-attention's comm/compute overlap).
- **Ulysses** (`ulysses_attention`): two ``all_to_all``s repartition
  sequence-sharded activations to head-sharded, run the full-sequence
  Pallas flash kernel locally, and repartition back. Cheaper collectives
  for moderate contexts; requires heads % cp == 0.

Causal handling in the ring: the chunk from rank j attends against local
queries of rank i with (j < i) → full block, (j == i) → causal block,
(j > i) → fully masked (contributes nothing). Ranks with higher indices do
more work — the standard ring-attention causal imbalance; zigzag
load-balanced chunk ordering is a planned optimization.
"""

import functools
import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _rotate(tree, axis_name: str):
    """Move every leaf one rank down the ring (rank r -> r+1 mod P)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda x: jax.lax.ppermute(x, axis_name, perm), tree
    )


def _block_scores(q, k, scale, src, rank, causal):
    """Masked fp32 scores for one ring step; returns (s, allow).

    q: (b, h, sq, d) local queries, k: (b, h, sk, d) visiting chunk from
    rank ``src`` (traced). allow is the keep-mask implementing the global
    causal structure across chunks.
    """
    s = (
        jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if not causal:
        return s, None
    sq, sk = s.shape[-2], s.shape[-1]
    tri = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]  # lower incl diag
    allow = jnp.where(
        src < rank, True, jnp.where(src == rank, tri, False)
    )  # (sq, sk) traced
    s = jnp.where(allow, s, _NEG_INF)
    return s, allow


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, axis_name, causal, scale):
    o, _ = _ring_fwd_res(q, k, v, axis_name, causal, scale)
    return o


def _ring_fwd_res(q, k, v, axis_name, causal, scale):
    num_ranks = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    qf = q.astype(jnp.float32)

    def step(carry, t):
        (kc, vc), acc, m, l = carry
        src = jax.lax.rem(rank - t + num_ranks, num_ranks)
        s, allow = _block_scores(qf, kc.astype(jnp.float32), scale, src, rank, causal)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if allow is not None:
            p = jnp.where(allow, p, 0.0)  # exp(-inf - (-inf)) guard
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32)
        )
        return (_rotate((kc, vc), axis_name), acc_new, m_new, l_new), None

    init = (
        (k, v),
        jnp.zeros((b, h, sq, d), jnp.float32),
        jnp.full((b, h, sq), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
    )
    (_, acc, m, l), _ = jax.lax.scan(step, init, jnp.arange(num_ranks))
    l = jnp.maximum(l, 1e-30)
    o = (acc / l[..., None]).astype(q.dtype)
    lse = m + jnp.log(l)
    return o, (q, k, v, o, lse)


def _ring_bwd(axis_name, causal, scale, res, do):
    q, k, v, o, lse = res
    num_ranks = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (b, h, sq)

    def step(carry, t):
        (kc, vc, dkc, dvc), dq = carry
        src = jax.lax.rem(rank - t + num_ranks, num_ranks)
        kcf = kc.astype(jnp.float32)
        vcf = vc.astype(jnp.float32)
        s, allow = _block_scores(qf, kcf, scale, src, rank, causal)
        p = jnp.exp(s - lse[..., None])
        if allow is not None:
            p = jnp.where(allow, p, 0.0)
        dvc = dvc + jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vcf)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kcf)
        dkc = dkc + jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        # dK/dV ride the ring with their chunks; after P rotations they are
        # home with the full sum of every rank's contribution
        return (_rotate((kc, vc, dkc, dvc), axis_name), dq), None

    init = (
        (k, v, jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)),
        jnp.zeros(q.shape, jnp.float32),
    )
    ((_, _, dk, dv), dq), _ = jax.lax.scan(step, init, jnp.arange(num_ranks))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd_res, _ring_bwd)


def ring_attention(
    q,
    k,
    v,
    axis_name: str = "cp",
    causal: bool = False,
    scale: float = None,
):
    """Exact sequence-sharded attention over the ``axis_name`` ring.

    q, k, v: (batch, heads, seq_local, head_dim) — the local chunk of a
    sequence sharded in rank order over the cp axis. Call inside
    ``shard_map``. Returns the local output chunk; grads flow through a
    second ring pass (see module docstring).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _ring(q, k, v, axis_name, causal, scale)


def ulysses_attention(
    q,
    k,
    v,
    axis_name: str = "cp",
    causal: bool = False,
    scale: float = None,
    attn_fn=None,
):
    """DeepSpeed-Ulysses-style attention: all-to-all from sequence-sharded
    to head-sharded, full-sequence local attention, all-to-all back.

    q, k, v: (batch, heads, seq_local, head_dim) with heads divisible by
    the cp size. ``attn_fn(q, k, v, causal=..., scale=...)`` defaults to
    the Pallas flash kernel. The two all_to_alls transpose to their own
    inverses under autodiff, so no custom backward is needed.
    """
    if attn_fn is None:
        from apex_tpu.ops.attention import flash_attention

        attn_fn = flash_attention
    num_ranks = jax.lax.psum(1, axis_name)  # static inside shard_map
    assert q.shape[1] % num_ranks == 0, (
        f"heads ({q.shape[1]}) not divisible by cp size ({num_ranks}); "
        "use ring_attention for head counts below the cp degree"
    )

    # With cp=1 this degrades to plain attention.
    def to_heads(x):
        # (b, h, s_loc, d) -> (b, h/P, s_glob, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    oh = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    return to_seq(oh)
