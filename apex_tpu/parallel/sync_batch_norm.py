"""Synchronized BatchNorm over the data-parallel axis.

Reference parity: apex.parallel.SyncBatchNorm — both the Python fallback
(parallel/sync_batchnorm.py:9) and the optimized CUDA path
(optimized_sync_batchnorm_kernel.py:10: ``syncbn.welford_mean_var`` per
rank, all_gather of per-rank stats, ``welford_parallel`` combine :43) — and
``convert_syncbn_model`` (parallel/__init__.py:21).

TPU design: per-shard moments + a count-weighted psum combine (numerically
the welford_parallel merge, expressed as two fused reductions):

    N      = psum(n_i)
    mean   = psum(n_i * m_i) / N
    var    = psum(n_i * (v_i + m_i^2)) / N - mean^2

which is exact for unequal per-shard counts (the reference's
two_gpu_test_different_batch_size case — SURVEY.md hard part #6).
Channel-last-ness is not a thing on TPU (XLA picks layouts).
"""

from typing import Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.monitor.xray import ledger as xlax


class SyncBatchNorm(nn.Module):
    """flax BatchNorm drop-in that reduces statistics over mesh axes.

    ``axis_names``: mesh axes to sync over (default ('dp',)); pass () to
    recover a local BatchNorm. Running stats live in the 'batch_stats'
    collection like flax.linen.BatchNorm. ``momentum`` follows the torch
    convention: new_running = (1 - momentum) * running + momentum * batch.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.1
    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True
    axis_names: Sequence[str] = ("dp",)
    dtype: Optional[jnp.dtype] = None
    scale_init: Callable = nn.initializers.ones_init()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            n_local = jnp.asarray(
                jnp.prod(jnp.asarray([x.shape[a] for a in reduce_axes])), jnp.float32
            )
            m_local = jnp.mean(xf, axis=reduce_axes)
            v_local = jnp.mean(jnp.square(xf), axis=reduce_axes) - jnp.square(m_local)
            n, m_sum, s_sum = n_local, m_local * n_local, (
                v_local + jnp.square(m_local)
            ) * n_local
            for ax in self.axis_names:
                try:
                    n = xlax.psum(n, ax)
                    m_sum = xlax.psum(m_sum, ax)
                    s_sum = xlax.psum(s_sum, ax)
                except NameError:  # axis not in scope -> local BN
                    pass
            mean = m_sum / n
            var = s_sum / n - jnp.square(mean)

            if not self.is_initializing():
                ra_mean.value = (
                    1.0 - self.momentum
                ) * ra_mean.value + self.momentum * mean
                # unbiased running var (torch SyncBN semantics)
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_var.value = (
                    1.0 - self.momentum
                ) * ra_var.value + self.momentum * unbiased

        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            scale = self.param("scale", self.scale_init, (features,), jnp.float32)
            y = y * scale
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (features,), jnp.float32)
            y = y + bias
        return y.astype(self.dtype or x.dtype)


def convert_syncbn_model(module, axis_names: Sequence[str] = ("dp",)):
    """Module surgery BatchNorm -> SyncBatchNorm (ref:
    apex.parallel.convert_syncbn_model, parallel/__init__.py:21-44, which
    walks the torch module tree replacing BatchNorm instances).

    flax modules are frozen dataclasses, so "surgery" is a recursive
    ``clone`` with replaced fields:

    - ``flax.linen.BatchNorm`` field values become ``SyncBatchNorm`` with
      the same hyperparameters (momentum converted between flax's
      ``new = m*old + (1-m)*batch`` and the torch convention used here);
    - already-sync norms and modules exposing a ``bn_axes`` field (e.g.
      apex_tpu.models.ResNet, contrib bottlenecks) are re-pointed at
      ``axis_names``;
    - nested module fields (including lists/tuples/dicts of modules)
      recurse.

    Parameter/batch-stats pytrees are structurally unchanged, so existing
    variables keep working — same as the reference, which moves the torch
    state dict across. Limitation (documented, inherent): submodules
    constructed inline inside an ``@nn.compact`` body are invisible to any
    post-hoc walk; modules like that should take a norm factory or
    ``bn_axes`` argument instead (apex_tpu.models.resnet does).
    """
    def convert_value(v):
        if isinstance(v, SyncBatchNorm):
            return v.clone(axis_names=tuple(axis_names))
        if isinstance(v, nn.BatchNorm):
            if v.axis != -1:
                # SyncBatchNorm normalizes the LAST axis; converting a
                # channels-not-last BatchNorm would silently normalize the
                # wrong axis AND change param shapes under the caller's
                # existing variables
                raise NotImplementedError(
                    f"convert_syncbn_model: BatchNorm(axis={v.axis}) is not "
                    "channels-last; transpose the model or construct "
                    "SyncBatchNorm directly"
                )
            if v.axis_index_groups is not None:
                raise NotImplementedError(
                    "convert_syncbn_model: axis_index_groups (subgroup "
                    "sync) has no SyncBatchNorm equivalent; construct the "
                    "sync norm directly"
                )
            extra = (v.axis_name,) if v.axis_name else ()
            return SyncBatchNorm(
                use_running_average=v.use_running_average,
                momentum=1.0 - v.momentum,  # flax -> torch convention
                epsilon=v.epsilon,
                use_scale=v.use_scale,
                use_bias=v.use_bias,
                axis_names=tuple(axis_names) + extra,
                dtype=v.dtype,
                scale_init=v.scale_init,
                bias_init=v.bias_init,
            )
        if isinstance(v, nn.Module):
            return convert_syncbn_model(v, axis_names=axis_names)
        if isinstance(v, (list, tuple)):
            return type(v)(convert_value(x) for x in v)
        if isinstance(v, dict):
            return {k: convert_value(x) for k, x in v.items()}
        return v

    updates = {}
    for name in getattr(module, "__dataclass_fields__", {}):
        if name in ("parent", "name"):
            continue
        old = getattr(module, name)
        new = convert_value(old)
        if name == "bn_axes":
            new = tuple(axis_names)
        if new is not old:
            updates[name] = new
    return module.clone(**updates) if updates else module
