"""Parallel RNG and activation checkpointing.

Reference parity: apex/transformer/tensor_parallel/random.py —
``CudaRNGStatesTracker`` (:124) forks CUDA RNG state per region,
``model_parallel_cuda_manual_seed`` (:204) gives TP rank i the seed
``seed + 2718 + tp_rank`` for model-parallel regions and the plain seed for
data-parallel regions, and ``CheckpointFunction`` (:237) re-runs forward in
backward with the RNG state restored.

TPU design: JAX PRNG keys are pure values — the entire stateful tracker
collapses into ``jax.random.fold_in``:

- model-parallel region key  = fold_in(fold_in(key, 2718), tp_rank)
- data-parallel region key   = key (same on all TP ranks)

and activation checkpointing is ``jax.checkpoint`` (recompute with identical
keys by construction — no fork/restore machinery needed; this is hard part
"RNG exactness" solved by design).
"""

import functools
from typing import Callable

import jax

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.parallel import parallel_state

_MODEL_PARALLEL_OFFSET = 2718  # matches the reference's seed offset constant


def model_parallel_rng_key(key, axis_name: str = "tp"):
    """Key for model-parallel regions: distinct per TP rank.

    (ref: random.py:204-236 — tensor-model-parallel seed = seed + 2718 + rank)
    """
    key = jax.random.fold_in(key, _MODEL_PARALLEL_OFFSET)
    if parallel_state.model_parallel_is_initialized():
        if parallel_state.get_tensor_model_parallel_world_size() > 1:
            rank = jax.lax.axis_index(axis_name)
            key = jax.random.fold_in(key, rank)
    return key


def shard_aware_rng_key(key, axis_names):
    """Fold the rank along each *active* named axis into ``key``.

    Used to decorrelate dropout masks across shards that each hold a
    different slice of the same logical tensor (sequence-parallel over tp,
    context-parallel over cp) — the SPMD equivalent of the reference's
    CudaRNGStatesTracker keeping distinct generator states per
    model-parallel rank (ref: random.py:124-236). Axes that are not bound
    (module traced outside shard_map, e.g. during ``init``) or have size 1
    are skipped.
    """
    for name in axis_names:
        try:
            key = jax.random.fold_in(key, jax.lax.axis_index(name))
        except NameError:
            pass
    return key


def data_parallel_rng_key(key):
    """Key for data-parallel regions: identical on all TP ranks (ref:
    random.py — default generator keeps the data-parallel seed)."""
    return key


def model_parallel_seed(seed: int, tp_rank: int) -> int:
    """Host-side helper mirroring the reference's integer seed math, for
    tests that compare against closed-form rank seeds."""
    return seed + _MODEL_PARALLEL_OFFSET + tp_rank


def checkpoint(fn: Callable = None, *, policy=None, prevent_cse: bool = True):
    """Activation checkpointing (ref: tensor_parallel.random.checkpoint,
    random.py:237 CheckpointFunction).

    A thin alias of ``jax.checkpoint``: forward runs without saving
    intermediates; backward recomputes. ``policy`` maps to
    ``jax.checkpoint_policies`` (e.g. ``dots_saveable``) — the TPU analogue
    of the reference's ``distribute_saved_activations`` memory knobs.
    """
    if fn is None:
        return functools.partial(checkpoint, policy=policy, prevent_cse=prevent_cse)
    return jax.checkpoint(fn, policy=policy, prevent_cse=prevent_cse)


# saved-activation distribution (random.py:246-266 partitions saved tensors
# across TP ranks): on TPU, save activations sequence-sharded instead
def distribute_saved_activations_policy():
    """Checkpoint policy that offloads nothing but marks only cheap
    recomputes: save matmul outputs, recompute elementwise. The
    sequence-sharded variant comes from running the checkpointed fn under
    shard_map with SP enabled — saved residuals are then already 1/tp-sized,
    which is what the reference's distribute_saved_activations achieves."""
    return jax.checkpoint_policies.dots_saveable


def checkpoint_distributed(fn: Callable, axis_name: str = "tp"):
    """Checkpoint with the saved boundary activation PARTITIONED over the
    tensor-parallel ranks (ref random.py:246-266: CheckpointFunction with
    ``distribute_saved_activations`` splits the saved input across the TP
    group and all-gathers it before recompute).

    The wrapped function's first argument (sequence-major, replicated over
    ``axis_name`` — the SP-off case the reference targets) is scattered
    along dim 0 OUTSIDE the checkpoint boundary and gathered back inside:
    autodiff then stashes only the 1/tp shard. The memory saving costs
    three all-gathers per step (forward primal, backward recompute, and
    the scatter's cotangent transpose) — the price of (tp-1)/tp of every
    boundary. Must run inside shard_map with ``axis_name`` bound, and dim 0
    must divide by the axis size (asserted — a silent floor-split would
    drop rows).

    Measured (BENCH.md): wins when MANY segments stash boundaries (the
    per-layer remat pattern — 3.7x less live memory at 16 segments,
    tp=8); for a SINGLE segment the transient all-gather buffer outweighs
    the one saved boundary (0.84x), so don't wrap a whole network in one
    call.
    """
    from apex_tpu.parallel.mappings import (
        gather_from_sequence_parallel_region,
        scatter_to_sequence_parallel_region,
    )

    inner = jax.checkpoint(
        lambda shard, *args: fn(
            gather_from_sequence_parallel_region(
                shard, axis_name, to_model_parallel=False
            ),
            *args,
        )
    )

    @functools.wraps(fn)
    def wrapped(x, *args):
        n = xlax.axis_size(axis_name)
        if x.shape[0] % n != 0:
            raise ValueError(
                f"checkpoint_distributed: leading dim ({x.shape[0]}) not "
                f"divisible by {axis_name} size ({n}); the split would "
                "silently drop rows"
            )
        return inner(scatter_to_sequence_parallel_region(x, axis_name), *args)

    return wrapped
