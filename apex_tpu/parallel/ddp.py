"""Data-parallel gradient synchronization.

Reference parity: apex.parallel.DistributedDataParallel
(parallel/distributed.py:131) and Reducer (:91). The reference implements
bucketed, multi-stream, overlapped NCCL allreduce with dynamic bucket
structure negotiation (:287-517) — roughly 600 lines of machinery whose
*entire purpose* (overlap comm with backward compute, batch small tensors)
is performed on TPU by XLA's collective scheduler given a single ``psum``
in the compiled step. What remains semantically meaningful is preserved:

- ``gradient_average`` / ``gradient_predivide_factor``: pre-divide by N
  before the sum, post-divide by N/factor after (distributed.py:439-455),
  which trades overflow headroom in fp16 grads;
- ``allreduce_always_fp32``: cast grads to fp32 around the reduce;
- param broadcast at init (distributed.py:257) — ``broadcast_params``.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.monitor.xray import ledger as xlax


def vma_tracking_live(axis_name: str) -> bool:
    """Trace-time: is varying-manual-axes tracking active for this axis?
    (``check_vma=False`` turns ``pcast`` into a no-op, so the probe's
    type stays unvarying there.) Per-trace-context constant — hoist out
    of per-leaf loops."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:  # pre-vma jax: nothing is tracked
        return False
    probe = pcast(jnp.zeros(()), axis_name, to="varying")
    try:
        return axis_name in jax.typeof(probe).vma
    except AttributeError:
        return False


def grads_already_reduced(x, axis_name: str, tracking: bool = None) -> bool:
    """Trace-time: is ``x`` ALREADY the cross-rank sum over ``axis_name``?

    Under jax's checked shard_map (``check_vma=True``, the default),
    ``jax.grad`` of an axis-varying loss w.r.t. axis-replicated params
    inserts the cross-rank psum in the transpose, so the grad leaf comes
    back UNVARYING — summed. Detection must be two-step because under
    ``check_vma=False`` every aval reads as unvarying while the auto-psum
    does NOT happen (grads stay per-rank local, measured in
    tests/test_ddp.py's harness): the ``vma_tracking_live`` probe tells
    whether unvarying proves anything (pass it in when calling per leaf).
    """
    try:
        vma = jax.typeof(x).vma
    except AttributeError:  # older tracer/no vma support: classic path
        return False
    if axis_name in vma:
        return False  # genuinely per-rank varying
    if tracking is None:
        tracking = vma_tracking_live(axis_name)
    return tracking


def all_reduce_gradients(
    grads: Any,
    axis_name: str = "dp",
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
    compression: Optional[Any] = None,
    ef_state: Optional[Any] = None,
) -> Any:
    """psum-average a grad pytree over the data-parallel axis.

    Call inside shard_map/pmap over ``axis_name`` after ``jax.grad``.

    ``compression`` (a :class:`~apex_tpu.parallel.compress
    .CompressionConfig`) replaces each classic-regime psum with the
    block-scaled quantized all-reduce of ``parallel/compress.py`` —
    gradients travel int8 (+ per-block fp32 scales) instead of
    fp32/bf16. ``ef_state`` (a matching fp32 residual pytree from
    ``compress.ef_init``) enables error feedback: when given, the
    return value is ``(grads, new_ef_state)`` instead of ``grads``.
    Non-finite grads still propagate (poisoned scales dequantize to
    NaN), so the grad scaler's found_inf consensus — which is never
    compressed — fires exactly as on the exact path. Leaves in the
    ALREADY-REDUCED regime carry no wire traffic and pass through
    compression untouched (their residual stays zero).

    TWO REGIMES, dispatched per-leaf on the varying-manual-axes type
    (``jax.typeof(g).vma``):

    - **already-reduced grads** (``axis_name`` NOT in the leaf's vma):
      under jax's checked shard_map semantics, ``jax.grad`` of a
      dp-varying loss w.r.t. dp-REPLICATED params inserts the cross-rank
      psum in the transpose automatically — the "bucketed overlapped
      allreduce" arrives for free, scheduled by XLA. The leaf is already
      the SUM over ranks, so averaging is a division by N and another
      psum would double-count (each rank would get N x the sum — the bug
      this dispatch fixes, caught by tests/test_ddp.py).
    - **per-rank local grads** (``axis_name`` in the leaf's vma — e.g.
      produced under a loss that never mixed ranks, or hand-built): the
      classic psum path, with the reference's predivide/postdivide
      ordering (distributed.py:439-455) trading fp16 overflow headroom.

    CAVEAT (differs from torch DDP): with a forward collective over
    ``axis_name`` in the loss (e.g. SyncBatchNorm), differentiate the
    GLOBAL loss — ``jax.grad(lambda p: lax.pmean(loss_fn(p), axis_name))``
    — so the cross-shard terms transpose correctly
    (tests/test_amp_convergence.py pins the patterns) — and then **skip
    this function entirely**.  Those grads arrive unvarying and ALREADY
    AVERAGED (the pmean's 1/N rides the transpose), and the unvarying
    type cannot distinguish a sum (divide by N) from a mean (already
    final): the already-reduced branch here would silently return
    mean/N.  Like ``zero_scatter_grads``, this function is ONLY for
    grads of a PER-RANK (shard-local) loss; tests/test_ddp.py pins both
    regimes.
    """
    if compression is None and ef_state is not None:
        raise ValueError(
            "ef_state without compression: the exact psum has no "
            "quantization error to feed back"
        )
    n = xlax.axis_size(axis_name)
    tracking = vma_tracking_live(axis_name)

    def _one(g, ef):
        orig = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if grads_already_reduced(g, axis_name, tracking):
            # transpose already psummed over axis_name: sum -> mean.
            # With average the predivide factor cancels exactly as in the
            # classic path ((sum/f)*(f/N) = sum/N); without it the classic
            # path returns sum/f, so divide here too for regime parity.
            if gradient_average:
                g = g / n
            elif gradient_predivide_factor != 1.0:
                g = g / gradient_predivide_factor
            return g.astype(orig), ef
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        if compression is not None:
            from apex_tpu.parallel import compress as _compress

            acc = g.astype(jnp.float32) if ef is None else (
                g.astype(jnp.float32) + ef
            )
            g, sent = _compress.quantized_psum(
                acc, axis_name, compression, return_transmitted=True
            )
            if ef is not None:
                ef = _compress.ef_update(acc, sent)
        else:
            g = xlax.psum(g, axis_name)
        if gradient_average:
            g = g * (gradient_predivide_factor / n)
        return g.astype(orig), ef

    if ef_state is None:
        return jax.tree_util.tree_map(lambda g: _one(g, None)[0], grads)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    ef_leaves, ef_treedef = jax.tree_util.tree_flatten(ef_state)
    if ef_treedef != treedef:
        # a positional zip over mismatched trees would silently pair
        # residuals with the WRONG gradients — corrupt error feedback,
        # not an error; build ef_state with compress.ef_init(grads)
        raise ValueError(
            f"ef_state structure {ef_treedef} does not match grads "
            f"{treedef}"
        )
    pairs = [_one(g, e) for g, e in zip(leaves, ef_leaves)]
    return (
        jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs]),
        jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs]),
    )


def broadcast_params(params: Any, axis_name: str = "dp") -> Any:
    """Make rank-0's params authoritative on every DP rank (ref:
    distributed.py:257 broadcasts at wrap time). Under shard_map:
    implemented as an all-gather-pick; under plain SPMD params are already
    replicated and this is identity."""

    def _one(p):
        gathered = xlax.all_gather(p, axis_name, axis=0)
        return gathered[0]

    return jax.tree_util.tree_map(_one, params)


class DistributedDataParallel:
    """Functional DDP wrapper.

    Wraps a ``loss_fn(params, batch) -> loss`` so that ``grad_fn`` returns
    DP-synchronized gradients. Unlike the reference there is no module to
    wrap — the object just carries the reduction options and the axis.
    """

    def __init__(
        self,
        loss_fn: Optional[Callable] = None,
        axis_name: str = "dp",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
        compression: Optional[Any] = None,
    ):
        self.loss_fn = loss_fn
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.compression = compression

    def reduce(self, grads: Any, ef_state: Optional[Any] = None) -> Any:
        """Sync grads; with ``compression`` + ``ef_state`` returns
        ``(grads, new_ef_state)`` (see ``all_reduce_gradients``)."""
        return all_reduce_gradients(
            grads,
            self.axis_name,
            self.gradient_average,
            self.gradient_predivide_factor,
            self.allreduce_always_fp32,
            compression=self.compression,
            ef_state=ef_state,
        )

    def value_and_grad(self, *args, **kwargs):
        """jax.value_and_grad with the gradient allreduce fused in.

        See the ``all_reduce_gradients`` caveat: not for models whose
        forward psums over the dp axis (e.g. SyncBatchNorm) — there,
        differentiate the pmean'd global loss directly."""
        vg = jax.value_and_grad(self.loss_fn, *args, **kwargs)

        def wrapped(*a, **k):
            val, grads = vg(*a, **k)
            return val, self.reduce(grads)

        return wrapped


class Reducer:
    """Manual-sync helper (ref: parallel/distributed.py:91): user calls
    ``reduce`` explicitly, no implicit hooks. Contract: the cross-rank
    MEAN of per-rank values — a leaf already replicated over the axis
    (unvarying vma) is its own mean and passes through unchanged (a psum
    there would multiply by N)."""

    def __init__(self, axis_name: str = "dp"):
        self.axis_name = axis_name

    def reduce(self, tree: Any) -> Any:
        n = xlax.axis_size(self.axis_name)
        tracking = vma_tracking_live(self.axis_name)

        def _one(x):
            if grads_already_reduced(x, self.axis_name, tracking):
                # replicated leaf: it IS the value on every rank; but
                # Reducer's contract is a MEAN of per-rank values, and a
                # replicated leaf's mean is itself
                return x
            return xlax.psum(x, self.axis_name) / n

        return jax.tree_util.tree_map(_one, tree)
