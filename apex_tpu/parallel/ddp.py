"""Data-parallel gradient synchronization.

Reference parity: apex.parallel.DistributedDataParallel
(parallel/distributed.py:131) and Reducer (:91). The reference implements
bucketed, multi-stream, overlapped NCCL allreduce with dynamic bucket
structure negotiation (:287-517) — roughly 600 lines of machinery whose
*entire purpose* (overlap comm with backward compute, batch small tensors)
is performed on TPU by XLA's collective scheduler given a single ``psum``
in the compiled step. What remains semantically meaningful is preserved:

- ``gradient_average`` / ``gradient_predivide_factor``: pre-divide by N
  before the sum, post-divide by N/factor after (distributed.py:439-455),
  which trades overflow headroom in fp16 grads;
- ``allreduce_always_fp32``: cast grads to fp32 around the reduce;
- param broadcast at init (distributed.py:257) — ``broadcast_params``.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def all_reduce_gradients(
    grads: Any,
    axis_name: str = "dp",
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
) -> Any:
    """psum-average a grad pytree over the data-parallel axis.

    Call inside shard_map/pmap over ``axis_name`` after ``jax.grad``.

    CAVEAT (differs from torch DDP): this grad-then-allreduce pattern is
    only correct when the differentiated loss contains NO collectives over
    ``axis_name``. torch's SyncBatchNorm injects its own all_reduce in its
    custom backward, so torch DDP composes with it; JAX AD transposes the
    forward psum instead, and reducing local-loss grads afterwards loses
    the cross-shard terms. With SyncBatchNorm (or any forward psum over
    the dp axis), differentiate the GLOBAL loss —
    ``jax.grad(lambda p: lax.pmean(loss_fn(p), axis_name))`` — and skip
    this function (tests/test_amp_convergence.py pins both patterns).
    """
    n = jax.lax.psum(1, axis_name)

    def _one(g):
        orig = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        g = jax.lax.psum(g, axis_name)
        if gradient_average:
            g = g * (gradient_predivide_factor / n)
        return g.astype(orig)

    return jax.tree_util.tree_map(_one, grads)


def broadcast_params(params: Any, axis_name: str = "dp") -> Any:
    """Make rank-0's params authoritative on every DP rank (ref:
    distributed.py:257 broadcasts at wrap time). Under shard_map:
    implemented as an all-gather-pick; under plain SPMD params are already
    replicated and this is identity."""

    def _one(p):
        gathered = jax.lax.all_gather(p, axis_name, axis=0)
        return gathered[0]

    return jax.tree_util.tree_map(_one, params)


class DistributedDataParallel:
    """Functional DDP wrapper.

    Wraps a ``loss_fn(params, batch) -> loss`` so that ``grad_fn`` returns
    DP-synchronized gradients. Unlike the reference there is no module to
    wrap — the object just carries the reduction options and the axis.
    """

    def __init__(
        self,
        loss_fn: Optional[Callable] = None,
        axis_name: str = "dp",
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        allreduce_always_fp32: bool = False,
    ):
        self.loss_fn = loss_fn
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32

    def reduce(self, grads: Any) -> Any:
        return all_reduce_gradients(
            grads,
            self.axis_name,
            self.gradient_average,
            self.gradient_predivide_factor,
            self.allreduce_always_fp32,
        )

    def value_and_grad(self, *args, **kwargs):
        """jax.value_and_grad with the gradient allreduce fused in.

        See the ``all_reduce_gradients`` caveat: not for models whose
        forward psums over the dp axis (e.g. SyncBatchNorm) — there,
        differentiate the pmean'd global loss directly."""
        vg = jax.value_and_grad(self.loss_fn, *args, **kwargs)

        def wrapped(*a, **k):
            val, grads = vg(*a, **k)
            return val, self.reduce(grads)

        return wrapped


class Reducer:
    """Manual-sync helper (ref: parallel/distributed.py:91): user calls
    ``reduce`` explicitly, no implicit hooks."""

    def __init__(self, axis_name: str = "dp"):
        self.axis_name = axis_name

    def reduce(self, tree: Any) -> Any:
        n = jax.lax.psum(1, self.axis_name)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, self.axis_name) / n, tree
        )
