"""Tensor-parallel layers: Column/Row-parallel linear, vocab-parallel embedding.

Reference parity: apex/transformer/tensor_parallel/layers.py —
``ColumnParallelLinear`` (:460), ``RowParallelLinear`` (:645),
``VocabParallelEmbedding`` (:174), and the fused
``LinearWithGradAccumulationAndAsyncCommunication`` autograd Function (:279).

TPU design: flax.linen modules meant to run inside ``shard_map`` over the
'tp' mesh axis. Parameters hold the *local shard* (features // tp); the
matching global arrays come out of shard_map with the right PartitionSpec.
All of the reference's manual overlap machinery (async all-gather before
wgrad, dgrad reduce-scatter overlapped with the wgrad GEMM, fused
accumulation into main_grad via fused_weight_gradient_mlp_cuda) is exactly
what XLA's latency-hiding scheduler does with the collectives emitted by the
mappings' custom_vjps — hard part #3 in SURVEY.md §7 verified by profile,
not hand scheduling.

Per-rank init matches Megatron semantics (random.py:204): initializers are
wrapped so each TP rank draws from fold_in(key, 2718 + rank).
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)


def _tp_size(axis_name: str) -> int:
    if parallel_state.model_parallel_is_initialized():
        return int(parallel_state.get_mesh().shape[axis_name])
    return 1


def tp_rank_init(init_fn: Callable, axis_name: str = "tp") -> Callable:
    """Wrap an initializer so each TP rank draws a distinct stream
    (ref seed offset semantics, tensor_parallel/random.py:204-236)."""

    def wrapped(key, shape, dtype=jnp.float32):
        key = jax.random.fold_in(key, 2718)
        try:
            key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
        except NameError:
            # not inside shard_map over axis_name: fine for tp==1, but with
            # tp>1 every rank would draw the SAME shard init — a caller bug
            # that must surface, not silently degrade (VERDICT r3 weak #4)
            if _tp_size(axis_name) > 1:
                raise RuntimeError(
                    f"tp_rank_init: initializer ran outside shard_map while "
                    f"the mesh has {_tp_size(axis_name)} {axis_name!r} shards;"
                    f" every rank would get identical params. Initialize "
                    f"inside shard_map over {axis_name!r}."
                ) from None
        return init_fn(key, shape, dtype)

    return wrapped


class ColumnParallelLinear(nn.Module):
    """Y = X A + b with A partitioned along its output (column) dim.

    Ref: layers.py:460. ``sequence_parallel_enabled`` all-gathers the
    sequence-sharded input in forward and reduce-scatters its grad in
    backward (layers.py:311-326, 345-361) — here that is the custom_vjp of
    ``gather_from_sequence_parallel_region``.
    """

    output_size: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel_enabled: bool = False
    axis_name: str = "tp"
    params_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    # keep the fp32 MXU accumulator instead of rounding back to x.dtype —
    # for heads whose consumer (e.g. vocab CE) wants full-precision logits
    output_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, sequence_parallel_override: Optional[bool] = None):
        # call-time SP override for setup-built instances whose input layout
        # changes per call — KV-cache decode feeds a replicated single token
        # through a layer constructed for sequence-sharded training inputs
        # (params are identical either way; only the gather moves)
        sp = (self.sequence_parallel_enabled
              if sequence_parallel_override is None
              else sequence_parallel_override)
        tp = _tp_size(self.axis_name)
        assert self.output_size % tp == 0, (
            f"output_size {self.output_size} not divisible by tp {tp}"
        )
        out_local = self.output_size // tp
        kernel = self.param(
            "kernel",
            tp_rank_init(self.kernel_init, self.axis_name),
            (x.shape[-1], out_local),
            self.params_dtype,
        )
        if tp > 1:
            if sp:
                x = gather_from_sequence_parallel_region(x, self.axis_name)
            else:
                x = copy_to_tensor_model_parallel_region(x, self.axis_name)
        y = jax.lax.dot_general(
            x,
            kernel.astype(x.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(self.output_dtype or x.dtype)
        if self.use_bias:
            bias = self.param(
                "bias",
                tp_rank_init(self.bias_init, self.axis_name),
                (out_local,),
                self.params_dtype,
            )
            y = y + bias.astype(y.dtype)
        if self.gather_output and tp > 1:
            assert not sp
            y = gather_from_tensor_model_parallel_region(y, self.axis_name)
        return y


class RowParallelLinear(nn.Module):
    """Y = X A + b with A partitioned along its input (row) dim.

    Ref: layers.py:645. Output is psum'ed over TP (or reduce-scattered to
    the sequence-parallel region); bias is added *after* the reduction so it
    is applied exactly once.
    """

    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel_enabled: bool = False
    axis_name: str = "tp"
    params_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        tp = _tp_size(self.axis_name)
        if tp > 1 and not self.input_is_parallel:
            x = scatter_to_tensor_model_parallel_region(x, self.axis_name)
        kernel = self.param(
            "kernel",
            tp_rank_init(self.kernel_init, self.axis_name),
            (x.shape[-1], self.output_size),
            self.params_dtype,
        )
        y = jax.lax.dot_general(
            x,
            kernel.astype(x.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)
        if tp > 1:
            if self.sequence_parallel_enabled:
                y = reduce_scatter_to_sequence_parallel_region(y, self.axis_name)
            else:
                y = reduce_from_tensor_model_parallel_region(y, self.axis_name)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.output_size,), self.params_dtype)
            if tp > 1 and self.sequence_parallel_enabled:
                # bias grad under SP is a partial sum over the local sequence
                # shard — identity-fwd/psum-bwd restores the full gradient
                # (ref: sequence_parallel_enabled grad allreduce semantics)
                bias = copy_to_tensor_model_parallel_region(bias, self.axis_name)
            y = y + bias.astype(y.dtype)
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding table partitioned along the vocab dim.

    Ref: layers.py:174 — each rank owns rows [rank*V/tp, (rank+1)*V/tp),
    out-of-range token ids produce zeros locally, and the partial lookups
    are summed over TP (:250-277).
    """

    num_embeddings: int
    embedding_dim: int
    axis_name: str = "tp"
    params_dtype: jnp.dtype = jnp.float32
    embedding_init: Callable = nn.initializers.normal(stddev=1.0)

    def setup(self):
        tp = _tp_size(self.axis_name)
        assert self.num_embeddings % tp == 0
        self.vocab_local = self.num_embeddings // tp
        self.embedding = self.param(
            "embedding",
            tp_rank_init(self.embedding_init, self.axis_name),
            (self.vocab_local, self.embedding_dim),
            self.params_dtype,
        )

    def __call__(self, ids):
        table = self.embedding
        tp = _tp_size(self.axis_name)
        if tp == 1:
            return jnp.take(table, ids, axis=0)
        rank = jax.lax.axis_index(self.axis_name)
        start = rank * self.vocab_local
        in_range = (ids >= start) & (ids < start + self.vocab_local)
        local_ids = jnp.clip(ids - start, 0, self.vocab_local - 1)
        out = jnp.take(table, local_ids, axis=0)
        out = jnp.where(in_range[..., None], out, 0.0)
        return reduce_from_tensor_model_parallel_region(out, self.axis_name)

    def attend(self, x, parallel_input: bool = False):
        """Vocab-parallel logits against the (tied) embedding table.

        Ref: parallel_lm_logits in testing/standalone_transformer_lm.py —
        copy-to-TP-region (identity fwd / psum bwd) then X @ E^T, leaving
        logits sharded along vocab for vocab_parallel_cross_entropy.
        ``parallel_input=True`` skips the copy when the caller's gather
        already carries the TP grad reduction (the reference's
        ``tensor_parallel_output_grad=True`` path) — avoids a redundant
        full psum of the hidden-grad in backward.
        """
        tp = _tp_size(self.axis_name)
        if tp > 1 and not parallel_input:
            x = copy_to_tensor_model_parallel_region(x, self.axis_name)
        table = self.embedding.astype(x.dtype)
        return jax.lax.dot_general(
            x,
            table,
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
