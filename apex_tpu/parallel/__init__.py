"""Parallelism: data/tensor/sequence/context/pipeline over a device mesh.

Reference parity: apex/parallel (DDP, SyncBatchNorm, LARC) and
apex/transformer (parallel_state, tensor_parallel, pipeline_parallel).
See SURVEY.md §2.5 for the strategy checklist; all strategies here ride
`jax.sharding.Mesh` axes ('dp','pp','cp','tp') with XLA collectives.
"""

from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.ddp import (
    DistributedDataParallel,
    Reducer,
    all_reduce_gradients,
    broadcast_params,
)
from apex_tpu.parallel.sync_batch_norm import SyncBatchNorm, convert_syncbn_model
from apex_tpu.parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.parallel import compress
from apex_tpu.parallel.compress import CompressionConfig
from apex_tpu.parallel import mappings
from apex_tpu.parallel import pipeline
from apex_tpu.optimizers.larc import LARC, larc
from apex_tpu.parallel import random
from apex_tpu.parallel.ring_attention import (
    cp_decode_attention,
    ring_attention,
    ulysses_attention,
    zigzag_shard,
    zigzag_unshard,
)
from apex_tpu.parallel.utils import (
    VocabUtility,
    broadcast_data,
    promote_to_vma,
    pvary_params,
    scan_carry_fixed_point,
    split_tensor_along_last_dim,
    vma_cond,
)

__all__ = [
    "parallel_state",
    "LARC",  # ref: apex.parallel re-exports LARC (apex/parallel/__init__.py)
    "larc",
    "DistributedDataParallel",
    "Reducer",
    "all_reduce_gradients",
    "broadcast_params",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "vocab_parallel_cross_entropy",
    "mappings",
    "pipeline",
    "random",
    "cp_decode_attention",
    "ring_attention",
    "ulysses_attention",
    "zigzag_shard",
    "zigzag_unshard",
    "VocabUtility",
    "broadcast_data",
    "promote_to_vma",
    "pvary_params",
    "scan_carry_fixed_point",
    "vma_cond",
    "split_tensor_along_last_dim",
    "compress",
    "CompressionConfig",
]
