from apex_tpu.utils.pytree import (
    tree_cast,
    tree_any_non_finite,
    tree_zeros_like,
    tree_map_with_path,
)
from apex_tpu.utils.timers import Timers, annotate, step_annotation, trace
from apex_tpu.utils.checkpoint import (
    AsyncCheckpointWriter,
    latest_step,
    load_checkpoint,
    save_checkpoint,
)
from apex_tpu.utils.autoresume import AutoResume

__all__ = [
    "tree_cast",
    "tree_any_non_finite",
    "tree_zeros_like",
    "tree_map_with_path",
    "Timers",
    "annotate",
    "trace",
    "step_annotation",
    "latest_step",
    "load_checkpoint",
    "AsyncCheckpointWriter",
    "save_checkpoint",
    "AutoResume",
]
