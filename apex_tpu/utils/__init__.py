from apex_tpu.utils.pytree import (
    tree_cast,
    tree_any_non_finite,
    tree_zeros_like,
    tree_map_with_path,
)

__all__ = [
    "tree_cast",
    "tree_any_non_finite",
    "tree_zeros_like",
    "tree_map_with_path",
]
