"""Pytree utilities shared across apex_tpu.

These replace the tensor-list plumbing of the reference (apex_C flatten /
multi_tensor lists) with pytree-native equivalents: on TPU, parameter
collections are pytrees of jax.Arrays and XLA fuses elementwise work across
leaves inside a single jit, so most of the reference's host-side bucketing
machinery disappears.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp


def tree_cast(tree: Any, dtype) -> Any:
    """Cast every inexact (floating) leaf of ``tree`` to ``dtype``.

    Integer / bool leaves are left untouched (matches the reference's
    ``convert_network`` behavior of only touching float tensors,
    ref: fp16_utils/fp16util.py:35-59).
    """
    if dtype is None:
        return tree

    def _cast(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


def tree_any_non_finite(tree: Any) -> jax.Array:
    """Return a scalar bool array: does any float leaf contain NaN/Inf?

    TPU-native replacement for the reference's ``noop_flag`` buffer that the
    CUDA multi_tensor kernels set on overflow (ref: csrc/multi_tensor_apply.cuh
    noop_flag short-circuit). Here it is a pure reduction that XLA fuses into
    whatever computation produced the leaves.
    """
    leaves = [
        x
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    ]
    if not leaves:
        return jnp.asarray(False)
    per_leaf = [jnp.logical_not(jnp.all(jnp.isfinite(x))) for x in leaves]
    return jnp.any(jnp.stack(per_leaf))


def tree_zeros_like(tree: Any, dtype=None) -> Any:
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros_like(x, dtype=dtype or jnp.asarray(x).dtype), tree
    )


def tree_map_multi(fn: Callable, n_out: int, *trees):
    """tree_map for an ``fn`` returning ``n_out`` values: returns ``n_out``
    trees, computing ``fn`` once per leaf (avoids the paired-tree_map
    double-compute pattern in multi-state optimizer updates)."""
    leaves, treedef = jax.tree_util.tree_flatten(trees[0])
    rest = [jax.tree_util.tree_leaves(t) for t in trees[1:]]
    results = [fn(*args) for args in zip(leaves, *rest)]
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [r[i] for r in results])
        for i in range(n_out)
    )


def tree_map_with_path(fn: Callable, tree: Any) -> Any:
    """tree_map where ``fn(path_str, leaf)`` receives a '/'-joined key path.

    Used by amp's keep-batchnorm-fp32 logic to select norm/bn parameters by
    name (ref: fp16_utils/fp16util.py:60-80 selects BN modules by type; in a
    functional pytree world the analogue is a path predicate).
    """

    def _fn(path, leaf):
        keys = []
        for p in path:
            if hasattr(p, "key"):
                keys.append(str(p.key))
            elif hasattr(p, "idx"):
                keys.append(str(p.idx))
            elif hasattr(p, "name"):
                keys.append(str(p.name))
            else:  # pragma: no cover
                keys.append(str(p))
        return fn("/".join(keys), leaf)

    return jax.tree_util.tree_map_with_path(_fn, tree)
