"""Named timers + profiler annotations.

Reference parity: apex/transformer/pipeline_parallel/_timers.py (`_Timer`
:6 with cuda synchronize, `Timers` with log/write). TPU translation:
``jax.block_until_ready`` replaces ``torch.cuda.synchronize`` and
``jax.profiler`` trace annotations replace NVTX ranges
(parallel/distributed.py:363 nvtx.range_push sites).

Three timing layers, three questions (don't conflate them):

- ``Timers``/``_Timer`` here — named INTERVAL averages ("how long is a
  step lately"), barriered via block_until_ready, reported per log
  interval as ``kind="timer"`` records.
- ``step_annotation``/``trace`` — DEVICE-time markers a profiler
  capture segments on; the timeline analyzer answers "where did the
  step's wall clock go".
- ``apex_tpu.monitor.goodput.span`` — run-LIFECYCLE wall-clock spans
  (``kind="span"``: compile, data_wait, step, ckpt_save/restore,
  rollback, stall...) the goodput accountant partitions into
  productive/badput; answers "where did the JOB's wall clock go"
  (docs/observability.md "Goodput & fleet health"). The examples wrap
  each loop iteration in BOTH a step span and a step annotation — same
  boundaries, different consumers.
"""

import time
from contextlib import contextmanager
from typing import Dict, Optional

import jax


class _Timer:
    """(ref: _timers.py:6)"""

    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0

    def start(self, barrier_on=None):
        assert not self.started_, f"timer {self.name} already started"
        if barrier_on is not None:
            jax.block_until_ready(barrier_on)
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, barrier_on=None):
        assert self.started_, f"timer {self.name} not started"
        if barrier_on is not None:
            jax.block_until_ready(barrier_on)
        self.elapsed_ += time.perf_counter() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """(ref: _timers.py Timers — log() prints "time (ms)"; the TB writer
    becomes an optional callback so any metrics sink plugs in)."""

    def __init__(self, write_fn=None):
        self.timers: Dict[str, _Timer] = {}
        self.write_fn = write_fn

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names, iteration: int, normalizer: float = 1.0,
              reset: bool = True):
        """Push each named timer's elapsed seconds to ``write_fn``.

        ``reset`` defaults to True (matching the reference Megatron
        ``Timers.write``): each write reports THIS interval's time. The
        old behavior hard-coded ``elapsed(reset=False)``, so successive
        writes reported an ever-growing cumulative total — pass
        ``reset=False`` only if that is genuinely what a sink wants.
        Plug ``MetricRouter.timer_write_fn`` (apex_tpu.monitor) in as
        ``write_fn`` to emit kind='timer' records.
        """
        assert normalizer > 0.0
        for name in names:
            value = self.timers[name].elapsed(reset=reset) / normalizer
            if self.write_fn is not None:
                self.write_fn(f"{name}-time", value, iteration)

    def log(self, names=None, normalizer: float = 1.0, reset: bool = True) -> str:
        names = names if names is not None else list(self.timers)
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            t = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            string += f" | {name}: {t:.2f}"
        print(string, flush=True)
        return string


@contextmanager
def annotate(name: str):
    """NVTX-range analogue: a jax.profiler trace annotation visible in
    TensorBoard/XProf captures (ref: DDP prof ranges,
    parallel/distributed.py:363-364)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def step_annotation(step: int, name: str = "train"):
    """Step marker for profiler traces (jax.profiler.StepTraceAnnotation).

    The timeline analyzer (``apex_tpu.monitor.xray.timeline``,
    docs/observability.md#timeline) segments a capture into steps on
    exactly these markers — a training loop that skips them produces a
    capture the analyzer can only treat as one undifferentiated span.
    Wrap the WHOLE step including its host sync (the
    ``block_until_ready`` / fetch), or the step's device tail is
    attributed to the next step's span.
    """
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


@contextmanager
def trace(log_dir: str, **kwargs):
    """Capture an XLA profiler trace of the enclosed block into
    ``log_dir`` (view with TensorBoard's profile plugin / XProf) — the
    TPU-native analogue of profiling the reference under nvprof/nsight
    (its NVTX ranges, parallel/distributed.py:363, exist for exactly this
    workflow). ``annotate``/``step_annotation`` ranges inside the block
    appear as named spans in the capture.

    Dispatch is async: ``jax.block_until_ready`` the block's outputs
    BEFORE the block closes, or in-flight device work leaks past the
    capture window::

        with trace("/tmp/prof"):
            out = train_step(state, batch)
            jax.block_until_ready(out)

    Thin delegation to ``jax.profiler.trace`` (``**kwargs`` forwarded:
    ``create_perfetto_link`` etc.) so the library surface carries the
    workflow docs without duplicating the mechanism. Captures are not
    just for eyeballs: ``apex_tpu.monitor.xray.timeline`` (or
    ``python -m apex_tpu.monitor.xray.timeline <log_dir>``) turns one
    into a per-step compute/collective/exposed/idle breakdown — wrap
    each step in :func:`step_annotation` so it can segment.
    """
    with jax.profiler.trace(log_dir, **kwargs):
        yield
