"""Device-time measurement that survives the axon TPU relay.

Measured relay behavior on this environment (2026-07-30, TPU v5 lite):

- ``jax.block_until_ready`` does NOT wait for device execution — a 4096^3
  bf16 matmul "completed" in 21 us (6,638 TFLOP/s, 34x the chip's peak), and
  a chain of ten 256MB elementwise passes in 20 us.  Execution is deferred
  until data is actually fetched to the host.
- A synchronous dispatch+fetch round-trip costs ~73 ms (tunnel RTT), so
  per-call wall-clock timing with a fetch measures the tunnel, not the chip.
- Compile requests are size-limited (HTTP 413): closing over a large array
  bakes it into the HLO as a constant and the remote compile is rejected.
  Benchmark inputs must be passed as jit ARGUMENTS.

The only trustworthy measurement is therefore a **slope**: run K data-
dependent iterations inside ONE jitted ``lax.fori_loop``/``scan``, force
completion with a small host fetch, and difference two K values so the RTT,
dispatch, compile-cache, and fetch costs cancel.  Calibration on the real
chip: 4096^3 bf16 matmul -> 0.758 ms/iter = 181 TFLOP/s (92% of the v5e's
197 TFLOP/s peak), i.e. the method's overhead is within a few percent.

This is the TPU-relay analogue of the reference's CUDA-event timing
(tests/L0/run_mlp/test_mlp.py:135-207 uses wall clock + torch.cuda
synchronize; CUDA's synchronize actually synchronizes — the relay's doesn't).
"""

import time
from typing import Callable, Sequence

import jax
import numpy as np

__all__ = [
    "enable_persistent_cache",
    "fetch",
    "full_reduce",
    "chained_seconds_per_iter",
    "seconds_per_iter",
]


def enable_persistent_cache(default_dir: str) -> None:
    """Point JAX's persistent compilation cache at ``APEX_TPU_COMPILE_CACHE``
    (or ``default_dir``), so a relay drop / fresh process re-pays zero
    compiles for programs an earlier attempt already compiled.  One shared
    helper so bench.py and the benchmark harness cannot drift apart on the
    cache location."""
    import os
    import sys

    cache_dir = os.environ.get("APEX_TPU_COMPILE_CACHE", default_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # older jax / read-only fs: slower, not fatal
        sys.stderr.write(f"[benchmarking] compilation cache unavailable: {e}\n")


def full_reduce(tree):
    """ONE fp32 scalar depending on every ELEMENT of every leaf.

    This reduction is load-bearing for measurement validity, not a
    convenience: fetching a single element lets XLA trace it back through a
    scan carry and dead-code-eliminate every other lane of an elementwise
    loop body (measured: 0.000 ms Adam "steps"), and one scalar output
    means one host fetch (each is a ~73 ms tunnel round-trip). Use this in
    every slope-timed ``build`` — do not re-implement it inline.
    """
    import jax.numpy as jnp

    return sum(
        jnp.sum(leaf.astype(jnp.float32))
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def fetch(out):
    """Force real device execution by materializing every output leaf on the
    host; returns the numpy leaves.  Outputs must be small (scalars/short
    vectors) — fetching a large array would time the tunnel's transfer
    instead of the computation."""
    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(out)]


def _best_of(fn, args, reps):
    out = fetch(fn(*args))  # compile + first run outside the timed region
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best, out


def chained_seconds_per_iter(
    build: Callable[[int], Callable],
    args: Sequence,
    reps: int = 5,
    target_signal: float = 0.4,
    max_span: int = 1024,
    return_output: bool = False,
    deadline: float | None = None,
):
    """Seconds per iteration of the loop body that ``build(k)`` chains k times.

    ``build(k)`` must return a function of ``*args`` whose (small) output
    data-depends on all k iterations — typically ``lax.fori_loop``/``scan``
    with the iterate as the carry, reduced via a FULL ``sum`` at the end.
    The result is the slope ``(t(k2) - t(k1)) / (k2 - k1)`` over
    best-of-``reps`` synchronized runs, which cancels every per-call constant
    (tunnel RTT, dispatch, fetch) and leaves pure device time.

    The span ``k2 - k1`` is sized adaptively: the relay's RTT jitters by
    ~±15 ms between calls (measured), so a fixed 20-iteration span turns a
    1.5 ms/iter loop into pure noise — even negative slopes.  A rough pass
    estimates the per-iteration time, then the span is chosen so the slope
    signal is ~``target_signal`` seconds, i.e. an order of magnitude above
    the jitter.

    Raises ``RuntimeError`` if the final slope comes out non-positive even
    at ``max_span`` — a garbage measurement must never be silently recorded
    as a (nonsensical, huge) throughput.

    With ``return_output=True``, returns ``(seconds, last_output)`` where
    ``last_output`` is the fetched numpy output of the longest chain —
    callers use it as a correctness gate on the exact computation timed.

    ``deadline`` (``time.monotonic()`` value) bounds span escalation: each
    escalation costs one more remote compile against a possibly-flaky relay
    (round 3's micro section burned 12,671 s this way), so past the deadline
    the next escalation raises instead of starting.  An in-flight fetch is
    never interrupted — only the decision to start another one is gated.
    """

    def _check_deadline(where):
        if deadline is not None and time.monotonic() > deadline:
            raise RuntimeError(f"measurement budget exhausted before {where}")

    _check_deadline("first compile")
    t1, _ = _best_of(jax.jit(build(1)), args, reps)
    span = 32
    while True:
        _check_deadline(f"span={span} compile")
        t2, out = _best_of(jax.jit(build(1 + span)), args, reps)
        signal = t2 - t1
        # accept once the slope signal dwarfs the jitter; otherwise escalate
        # the span geometrically (each span is one more remote compile, so
        # escalate in few, large steps rather than re-estimating precisely)
        if signal >= target_signal or span >= max_span:
            if signal <= 0:
                raise RuntimeError(
                    f"non-positive slope at span={span}: t(1)={t1:.4f}s "
                    f"t({1 + span})={t2:.4f}s — timing is noise, not signal"
                )
            sec = signal / span
            return (sec, out) if return_output else sec
        est = max(signal / span, 1e-6)
        span = min(max_span, max(span * 4, int(target_signal / est) + 1))


def seconds_per_iter(step, carry, xs_like=None, reps: int = 5) -> float:
    """Slope-time one step of ``carry -> carry`` (or ``(carry, x) -> carry``).

    Convenience wrapper for the common benchmark shape: the step function is
    chained via ``lax.scan`` over k dummy iterations with the carry threaded
    through, then reduced to one scalar per carry leaf for the fetch.
    """

    def build(k):
        def run(carry):
            def body(c, _):
                c2 = step(c) if xs_like is None else step(c, xs_like)
                return c2, None

            final, _ = jax.lax.scan(body, carry, None, length=k)
            return full_reduce(final)

        return run

    return chained_seconds_per_iter(build, (carry,), reps=reps)
