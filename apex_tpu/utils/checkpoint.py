"""Checkpoint save/restore (orbax-backed).

Reference parity: the reference's checkpoint story is pieces — amp
state_dict round-trip (amp/frontend.py:367-404), FP16_Optimizer.state_dict
(fp16_utils/fp16_optimizer.py:212-273), DistributedFusedAdam sharded state
dicts (contrib/optimizers/distributed_fused_adam.py ~:2400). On TPU one
engine covers all of it: any pytree (params, optax/amp state, scaler
state, RNG keys) round-trips through orbax, which handles sharded arrays
(each host writes its shards — the "sharded state dict" of the reference)
and atomic step directories natively.
"""

import logging
import os
import threading
from typing import Any, Callable, Optional

logger = logging.getLogger("apex_tpu.utils.checkpoint")



def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _serialize(tree: Any) -> Any:
    """Custom pytree nodes (flax structs, optax/amp state dataclasses,
    NamedTuples) -> plain nested containers. Orbax stores plain containers
    on disk, so restoring INTO a custom-node target otherwise fails with a
    treedef mismatch (observed with amp's LossScalerState)."""
    from orbax.checkpoint.utils import serialize_tree

    return serialize_tree(tree, keep_empty_nodes=True)


def save_checkpoint(directory: str, step: int, tree: Any, overwrite: bool = True) -> str:
    """Write ``tree`` to ``directory/step_<N>``; returns the path.

    ``tree`` may contain params, optimizer state, scaler state, metadata —
    any pytree of arrays/scalars (ref: the save side of amp.state_dict +
    optimizer state_dict composition).
    """
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    _checkpointer().save(path, _serialize(tree), force=overwrite)
    return path


def load_checkpoint(directory: str, step: Optional[int] = None, target: Any = None) -> Any:
    """Restore the pytree saved at ``step`` (default: latest). ``target``
    (a pytree of like-shaped arrays) restores dtypes/shardings exactly —
    pass the freshly-initialized state for a true resume.

    Structure migration: a raw-pytree restore requires the saved and
    target trees to match. When a state dataclass gains a field across
    versions (e.g. LossScalerState.hysteresis_tracker), resume older
    checkpoints through the component's ``state_dict``/``load_state_dict``
    pair, which is tolerant of missing keys (amp/scaler.py), instead of
    the raw tree."""
    directory = os.path.abspath(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(directory, f"step_{step}")
    if target is not None:
        import orbax.checkpoint as ocp
        from orbax.checkpoint.utils import deserialize_tree

        plain = _checkpointer().restore(
            path,
            restore_args=ocp.checkpoint_utils.construct_restore_args(
                _serialize(target)
            ),
        )
        # rebuild the caller's structure (dataclasses etc.) from the plain
        # on-disk containers
        return deserialize_tree(plain, target, keep_empty_nodes=True)
    return _checkpointer().restore(path)


class AsyncCheckpointWriter:
    """Checkpoint writes overlapped with training (orbax AsyncCheckpointer).

    ``save`` snapshots device arrays to host and returns once the write is
    handed to a background thread — the next train step runs while the
    bytes hit disk (the standard TPU practice for large states; the
    reference's ``torch.save`` path blocks the step for the full write).
    ``wait`` blocks until every pending write is durable; call it before
    reading the checkpoint back, at auto-resume consensus points
    (utils/autoresume.py), and at shutdown.

    One writer serializes its own saves: a save issued while the previous
    one is in flight waits for it first (orbax semantics), so step_N
    directories never interleave.
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, directory: str, step: int, tree: Any, overwrite: bool = True) -> str:
        path = os.path.join(os.path.abspath(directory), f"step_{step}")
        self._ckptr.save(path, _serialize(tree), force=overwrite)
        return path

    def wait(self) -> None:
        self._ckptr.wait_until_finished()

    def finalize_async(
        self,
        fn: Callable[[], None],
        on_error: Optional[Callable[[BaseException], None]] = None,
        name: str = "apex-tpu-ckpt-finalize",
    ) -> threading.Thread:
        """Run ``fn()`` on a background thread once every pending write is
        durable — the background half of async VERIFIED checkpointing.

        The verified-checkpoint machinery (resilience.integrity +
        utils/autoresume.py) uses this to move manifest fingerprinting —
        the per-file sha256 re-read and per-leaf crc32 — off the save
        critical path: issuance returns after the serialization hand-off,
        and verification completes in here before the manifest commit
        marker lands. A crash mid-``fn`` leaves a step dir with no
        manifest, which every verified restore walk already skips.

        Returns the (daemon) thread; join it before claiming durability.
        Errors from the wait or ``fn`` route to ``on_error`` (default: a
        warning log) — a background thread's traceback-to-stderr death
        would otherwise be the only signal.
        """

        def run() -> None:
            try:
                self.wait()
                fn()
            except Exception as e:  # noqa: BLE001 - surfaced via on_error
                if on_error is not None:
                    on_error(e)
                else:
                    logger.warning(
                        "background checkpoint finalize failed: %s", e
                    )

        thread = threading.Thread(target=run, name=name, daemon=True)
        thread.start()
        return thread

    def close(self) -> None:
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()  # close() waits for pending writes first
        return False


# an in-progress (not yet atomically renamed) orbax save lives at
# "<name>.orbax-checkpoint-tmp-<ts>"; it must never be offered for restore
ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp-"


def _is_complete_step_dir(path: str) -> bool:
    """Reject step directories that are still being (or were never fully)
    written: orbax tmp names from an interrupted async save, and empty or
    file-typed ``step_N`` entries from a torn copy / non-atomic backend
    (the GCS-style layout where the final name exists before the commit
    marker lands). Content-level corruption needs the checksum manifest
    (resilience.integrity.verify_checkpoint) — this is the cheap gate
    every ``latest_step`` caller gets for free."""
    if ORBAX_TMP_MARKER in os.path.basename(path):
        return False
    if not os.path.isdir(path):
        return False
    try:
        return bool(os.listdir(path))
    except OSError:
        return False


def finalized_steps(directory: str) -> list:
    """Ascending step numbers of complete ``step_N`` dirs in ``directory``.

    A crash during an async save used to leave the torn directory where
    the next ``restore()`` would pick it up; in-progress/tmp and empty
    step dirs are excluded here (see ``_is_complete_step_dir``).
    """
    if not os.path.isdir(directory):
        return []
    steps = []
    for d in os.listdir(directory):
        if not d.startswith("step_"):
            continue
        tail = d.split("_", 1)[1]
        if not tail.isdigit():
            continue
        if _is_complete_step_dir(os.path.join(directory, d)):
            steps.append(int(tail))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    steps = finalized_steps(directory)
    return steps[-1] if steps else None
