"""Preemption-safe training: signal-triggered checkpoint + resume.

Reference parity: the reference's only failure-recovery hook is the ADLR
cluster auto-resume object surfaced through
``testing/global_vars.get_adlr_autoresume`` (ref global_vars.py:75) and
polled via ``pipeline_parallel/utils.get_autoresume`` — an external object
with ``termination_requested()`` / ``request_resume()`` that the training
loop is expected to poll, save, and exit on. There is no in-tree
implementation.

TPU design: preemptible TPU VMs deliver SIGTERM ahead of eviction, so the
capability is first-class here instead of an external hook:

- ``AutoResume`` installs a signal handler that only flips a host-local
  flag (async-signal-safe; no IO in the handler).
- On multi-host meshes the flag must become a CONSENSUS before anyone
  saves: hosts receive SIGTERM at different wall-clock times, and a host
  that checkpoints at step N while others continue to N+3 produces a torn
  checkpoint. ``termination_requested()`` therefore ORs the host-local
  flags across all devices (a tiny jitted ``jnp.max`` over a
  process-spanning global array), so every host sees True at the same
  step boundary and they all save the same step. Single-host meshes skip
  the collective.
- ``step()`` combines the periodic-interval save (ref
  ``--adlr-autoresume-interval`` semantics) with the termination save;
  ``restore()`` resumes from the newest step directory.

The consensus collective costs one scalar all-reduce per *polled* step;
poll every step (it is negligible next to a train step) or at a cadence.
"""

import os
import signal as _signal
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.utils.checkpoint import (
    latest_step,
    load_checkpoint,
    save_checkpoint,
)

__all__ = ["AutoResume"]


class AutoResume:
    """Poll-based preemption handling for training loops.

    Usage::

        ar = AutoResume(save_dir, interval=1000)
        step0, state = ar.restore(init_state)          # 0, init on fresh start
        for step in range(step0, total_steps):
            state = train_step(state)
            if ar.step(step + 1, state):               # saved-for-termination
                break                                  # exit; scheduler restarts

    ``state`` may be any checkpointable pytree. The object is also usable
    as the ``get_adlr_autoresume()`` global in the testing harness — it
    implements ``termination_requested()`` and ``request_resume()`` with
    the reference's polling contract.
    """

    def __init__(
        self,
        directory: str,
        interval: Optional[int] = None,
        signals: Sequence[int] = (_signal.SIGTERM,),
        install_handlers: bool = True,
    ):
        self.directory = os.path.abspath(directory)
        self.interval = interval
        self._requested = False
        self._saved_for_termination = False
        self._prev_handlers = {}
        self._consensus = None  # lazily-built (sharding, jitted max) pair
        if install_handlers:
            for sig in signals:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)

    # -- signal plumbing ---------------------------------------------------

    def _on_signal(self, signum, frame):
        # flag only: checkpoint IO from inside a signal handler could fire
        # mid-XLA-dispatch; the training loop polls at a safe boundary
        self._requested = True

    def close(self):
        """Restore previously-installed signal handlers."""
        for sig, h in self._prev_handlers.items():
            _signal.signal(sig, h)
        self._prev_handlers = {}

    def request_resume(self):
        """Programmatic preemption request (ref ADLR ``request_resume``)."""
        self._requested = True

    # -- consensus ---------------------------------------------------------

    def termination_requested(self) -> bool:
        """True once ANY host has received a termination signal.

        Multi-host: each host contributes its local flag through a global
        array spanning all processes; one jitted max reduces it. All hosts
        reach the same answer for the same poll, so they checkpoint the
        same step. (Mirrors the reference polling contract,
        pipeline_parallel/utils.get_autoresume — but distributed-safe.)
        """
        if jax.device_count() == 1:
            return self._requested
        # the collective path runs on ANY multi-device mesh so the CPU-mesh
        # tests exercise the code multi-host actually uses (on one process
        # it reduces identical flags; the cost is one scalar all-reduce).
        # The mesh/sharding/jitted reduction are built ONCE and reused —
        # a fresh jax.jit per poll would re-trace and re-dispatch every
        # step, dwarfing the advertised one-scalar-all-reduce cost.
        if self._consensus is None:
            mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("hosts",))
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("hosts")
            )
            reduce = jax.jit(jnp.max, out_shardings=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()))
            self._consensus = (sharding, reduce)
        sharding, reduce = self._consensus
        local = np.asarray([np.float32(self._requested)])
        # every device in this process carries the process-local flag
        per_dev = [
            jax.device_put(local, d) for d in jax.local_devices()
        ]
        global_flags = jax.make_array_from_single_device_arrays(
            (jax.device_count(),), sharding, per_dev
        )
        anyone = reduce(global_flags)
        return bool(np.asarray(anyone)[()] > 0)

    # -- loop API ----------------------------------------------------------

    def step(self, step: int, state: Any) -> bool:
        """Call after each training step with the POST-step state.

        Saves on the periodic interval and on termination request; returns
        True when the caller should exit (a termination checkpoint was
        written).
        """
        terminating = self.termination_requested()
        if terminating and not self._saved_for_termination:
            save_checkpoint(self.directory, step, state)
            self._saved_for_termination = True
            return True
        if terminating:
            return True
        if self.interval and step % self.interval == 0:
            save_checkpoint(self.directory, step, state)
        return False

    def restore(self, init_state: Any) -> Tuple[int, Any]:
        """(step, state): latest checkpoint if one exists, else (0, init).

        ``init_state`` also serves as the restore target so dtypes and
        shardings round-trip exactly (see utils/checkpoint.py).
        """
        step = latest_step(self.directory)
        if step is None:
            return 0, init_state
        return step, load_checkpoint(self.directory, step, target=init_state)
