"""Preemption-safe training: signal-triggered checkpoint + resume.

Reference parity: the reference's only failure-recovery hook is the ADLR
cluster auto-resume object surfaced through
``testing/global_vars.get_adlr_autoresume`` (ref global_vars.py:75) and
polled via ``pipeline_parallel/utils.get_autoresume`` — an external object
with ``termination_requested()`` / ``request_resume()`` that the training
loop is expected to poll, save, and exit on. There is no in-tree
implementation.

TPU design: preemptible TPU VMs deliver SIGTERM ahead of eviction, so the
capability is first-class here instead of an external hook:

- ``AutoResume`` installs a signal handler that only flips a host-local
  flag (async-signal-safe; no IO in the handler).
- On multi-host meshes the flag must become a CONSENSUS before anyone
  saves: hosts receive SIGTERM at different wall-clock times, and a host
  that checkpoints at step N while others continue to N+3 produces a torn
  checkpoint. ``termination_requested()`` therefore ORs the host-local
  flags across all devices (a tiny jitted ``jnp.max`` over a
  process-spanning global array), so every host sees True at the same
  step boundary and they all save the same step. Single-host meshes skip
  the collective.
- ``step()`` combines the periodic-interval save (ref
  ``--adlr-autoresume-interval`` semantics) with the termination save;
  ``restore()`` resumes from the newest step directory.

The consensus collective costs one scalar all-reduce per *polled* step;
poll every step (it is negligible next to a train step) or at a cadence.

Deadline-budgeted termination saves: preemption grace windows are FIXED
(the scheduler kills the process ``grace_s`` seconds after SIGTERM,
saved or not), so blindly starting a full sync save on termination can
be worse than not saving — a save that outlives the grace window leaves
a torn, uncommitted step dir AND burned the time that finalizing an
already-in-flight save would have used. ``AutoResume`` therefore
measures its own recent save durations (EMAs, persisted in the
integrity manifest so a restarted job inherits them) and, when a grace
budget is configured (``grace_s=`` or ``APEX_TPU_PREEMPTION_GRACE_S``),
picks the most durable action that provably fits the remaining budget:

- ``save``      — full durable save of the CURRENT step (budget covers
  the measured full-save EMA, or no history/budget to reason from);
- ``finalize``  — commit only the pending async interval save (budget
  covers the finalize EMA but not a fresh save): the job loses the
  steps since the last interval, not the whole run;
- ``skip``      — abandon even the pending save's manifest commit and
  rely on the last already-verified checkpoint: a manifest commit that
  might land after the kill is exactly the torn-but-plausible state the
  integrity machinery exists to prevent. No torn manifest is ever
  treated as durable.

The decision is emitted as a ``kind="span"`` ckpt_save slice (with a
``decision`` field) plus a ``kind="preemption"`` event through the
goodput stream, so post-mortems can audit what the job chose and why.

Elastic restart: ``restore()`` compares the newest verified manifest's
topology block against the live mesh and, on a mismatch, routes through
``resilience.elastic.restore_resharded`` — params re-laid-out onto the
new mesh, ZeRO flat optimizer state regrouped across the changed dp
size, refuse-don't-guess on anything else (docs/resilience.md "Elastic
restart").

Async VERIFIED checkpointing: for overlapped interval saves the manifest
work — the per-leaf crc32 fingerprint and the per-file sha256 digests
(a full re-read of the checkpoint bytes inside ``write_manifest``) —
runs in ``AsyncCheckpointWriter.finalize_async``'s background thread,
AFTER the write is durable and BEFORE the commit marker lands. Issuance
only pays the device->host snapshot (needed anyway: the caller may
donate the buffers the moment ``step()`` returns) plus the orbax
hand-off, so the goodput accountant books ``ckpt_save`` badput at
issuance-only for training-overlapped saves; a crash mid-fingerprint
leaves a step dir with no manifest, which every verified restore walk
already skips. Durable saves (termination, first-save calibration) keep
the blocking finalize — their EMAs must measure a REAL full save.

Incident exit: ``prepare_incident_exit()`` is the bounded hook the
hung-job responder (``resilience.health``) calls from its watchdog
thread before self-terminating — it abandons the un-committed pending
save (tombstone manifest) WITHOUT ever blocking on the possibly-wedged
writer, so the next incarnation restores the last verified step.
"""

import functools
import logging
import os
import signal as _signal
import threading
import time
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.monitor.goodput.spans import get_router as _goodput_router
from apex_tpu.monitor.goodput.spans import span as _goodput_span
from apex_tpu.utils.checkpoint import (
    AsyncCheckpointWriter,
    latest_step,
    load_checkpoint,
)

__all__ = ["AutoResume", "TerminationNotice", "GRACE_ENV"]

logger = logging.getLogger("apex_tpu.utils.autoresume")

#: environment default for the preemption grace budget (seconds between
#: SIGTERM and the scheduler's kill); unset/empty means "no budget" and
#: termination always attempts the full durable save
GRACE_ENV = "APEX_TPU_PREEMPTION_GRACE_S"


def _env_grace() -> Optional[float]:
    raw = os.environ.get(GRACE_ENV, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring unparseable %s=%r", GRACE_ENV, raw)
        return None


def _ema(old: Optional[float], x: float, alpha: float = 0.5) -> float:
    """Recent-weighted EMA; seeds from the first sample."""
    return x if old is None else (1.0 - alpha) * old + alpha * x


class TerminationNotice:
    """Flag-only SIGTERM latch for non-checkpoint consumers.

    :class:`AutoResume` couples the SIGTERM flag to checkpoint IO; a
    consumer that only needs to KNOW a termination arrived — the serving
    engine's graceful drain (docs/serving.md) stops admitting and
    deadline-evicts in-flight decodes, it has no training state to
    save — needs the flag without the directory. This latch lives here
    because ``utils/autoresume.py`` is blessed home #1 of raw signal
    registration (``lint.signal-handlers``): the handler stores one
    bool + one monotonic float (async-signal-safe, no IO) and then
    CHAINS to whatever flag-style handler was installed before it
    (AutoResume's preemption flag), so stacking loses neither. The one
    handler it deliberately does NOT chain is the router module's
    SIGTERM teardown hook, which flushes and then re-raises to DIE by
    the signal — with a notice installed the signal means "drain
    gracefully", not "die", so that hook is superseded (see
    :meth:`_on_signal`).

    ``grace_s`` defaults to the PR-8 preemption budget
    (``APEX_TPU_PREEMPTION_GRACE_S``): :meth:`grace_deadline` is the
    monotonic instant by which a drain must be done.
    """

    def __init__(self, signals: Sequence[int] = (_signal.SIGTERM,),
                 install_handlers: bool = True,
                 grace_s: Optional[float] = None):
        self.grace_s = grace_s if grace_s is not None else _env_grace()
        self._signaled = False
        self._signal_t: Optional[float] = None
        self._prev_handlers = {}
        if install_handlers:
            for sig in signals:
                self._prev_handlers[sig] = _signal.signal(
                    sig, self._on_signal
                )

    def _on_signal(self, signum, frame):
        # flag + timestamp only (async-signal-safe), then chain: a
        # previously-installed AutoResume handler (flag-only, like this
        # one) still runs — a notice must observe, not preempt. The ONE
        # exception is the router module's teardown hook (monitor/
        # router.py): it exists to flush spans before an otherwise-
        # FATAL SIGTERM and re-raises the signal to die by it — chained
        # from here it would kill the very process the notice exists to
        # drain gracefully. With a notice installed the signal is no
        # longer fatal, so that hook is superseded: the flush happens
        # at the drain's normal router close / atexit instead.
        self._signaled = True
        if self._signal_t is None:
            self._signal_t = time.monotonic()
        prev = self._prev_handlers.get(signum)
        if (callable(prev)
                and not getattr(prev, "_apex_tpu_router_teardown", False)):
            prev(signum, frame)

    @property
    def signaled(self) -> bool:
        """True once a termination signal arrived (host-local)."""
        return self._signaled

    def request(self) -> None:
        """Arm the latch programmatically (tests; in-process drills)."""
        self._signaled = True
        if self._signal_t is None:
            self._signal_t = time.monotonic()

    def grace_deadline(self) -> Optional[float]:
        """Monotonic deadline for post-signal work (arrival +
        ``grace_s``); None while un-signaled or with no budget."""
        if self._signal_t is None or self.grace_s is None:
            return None
        return self._signal_t + self.grace_s

    def close(self) -> None:
        """Restore the previous handlers (idempotent)."""
        for sig, h in self._prev_handlers.items():
            try:
                _signal.signal(sig, h)
            except (ValueError, OSError):  # non-main thread teardown
                pass
        self._prev_handlers = {}


class AutoResume:
    """Poll-based preemption handling for training loops.

    Usage::

        ar = AutoResume(save_dir, interval=1000)
        step0, state = ar.restore(init_state)          # 0, init on fresh start
        for step in range(step0, total_steps):
            state = train_step(state)
            if ar.step(step + 1, state):               # saved-for-termination
                break                                  # exit; scheduler restarts

    ``state`` may be any checkpointable pytree. The object is also usable
    as the ``get_adlr_autoresume()`` global in the testing harness — it
    implements ``termination_requested()`` and ``request_resume()`` with
    the reference's polling contract.

    Durability & integrity (resilience.integrity wiring):

    - interval saves are ASYNC (the next train step overlaps the write)
      and VERIFY in the background: the checksum-manifest fingerprint +
      commit + optional ``keep_last_n`` retention run on the writer's
      finalize thread once the write is durable, so issuance is the only
      blocking slice; :meth:`finalize` / :meth:`close` (and the next
      save) are the join points;
    - a TERMINATION save is finalized before ``step()`` returns True, so
      "saved, you may exit" is never claimed for bytes still in flight —
      unless a configured grace budget (``grace_s`` /
      ``APEX_TPU_PREEMPTION_GRACE_S``) provably cannot fit it, in which
      case the deadline decision (module docstring) downgrades to
      finalize-pending-only or skip-and-rely-on-last-verified;
    - ``restore()`` skips torn or corrupt step directories (manifest
      verification) and falls back to the newest verified checkpoint;
      when the saved topology disagrees with the live mesh it reshards
      through ``resilience.elastic`` (pass ``mesh=`` explicitly if the
      state leaves carry no ``NamedSharding`` to derive it from).

    Deadline-decision caveat (multi-host): the decision inputs — signal
    arrival time and save-duration EMAs — are host-local, so hosts could
    in principle pick different actions. In practice the EMAs track the
    same collective saves and the grace budget is a cluster constant;
    deployments that need hard agreement should pin ``grace_s`` and rely
    on the consensus flag making every host decide at the same step.
    """

    #: headroom multiplier on the measured EMAs before an action is
    #: considered to fit the remaining grace budget
    safety_factor = 1.25

    def __init__(
        self,
        directory: str,
        interval: Optional[int] = None,
        signals: Sequence[int] = (_signal.SIGTERM,),
        install_handlers: bool = True,
        keep_last_n: Optional[int] = None,
        use_async: bool = True,
        verify: bool = True,
        save_retries: int = 3,
        save_backoff: float = 0.1,
        leaf_fingerprint: bool = True,
        grace_s: Optional[float] = None,
        mesh=None,
        background_finalize: bool = True,
        journal=None,
    ):
        self.directory = os.path.abspath(directory)
        self.interval = interval
        self.keep_last_n = keep_last_n
        self.use_async = use_async
        self.verify = verify
        self.save_retries = save_retries
        self.save_backoff = save_backoff
        # per-leaf crc32 fingerprints enable restore-time deep verification
        # but cost a synchronous full-state device->host copy per save —
        # and for an overlapped async save that snapshot stays ALIVE in
        # host RAM until the background finalize fingerprints it (one
        # extra full host copy for the write's duration, on top of the
        # one orbax's own async snapshot already holds over the same
        # window). The manifest's per-file digests (computed at finalize,
        # off the saved bytes) still catch disk corruption with this off
        # — hosts sized for one state copy should turn it off.
        self.leaf_fingerprint = leaf_fingerprint
        self.grace_s = grace_s if grace_s is not None else _env_grace()
        self.mesh = mesh
        # optional flight recorder (resilience.replay.FlightRecorder, or
        # anything with .anchor/.event/.flush): every save becomes a
        # replay ANCHOR, and the sidecar is flushed wherever a manifest
        # commits — the journal is durable exactly when the checkpoint
        # is, including the termination-save and incident-exit paths
        self.journal = journal
        # async VERIFIED checkpointing (module docstring): overlapped
        # interval saves verify + commit their manifest on the writer's
        # background finalize thread. False restores the pre-incident
        # blocking behavior (manifest committed at the NEXT finalize
        # point on the training thread) — a debugging/compat knob and the
        # deterministic mode the deadline-decision tests pin.
        self.background_finalize = background_finalize
        self._requested = False
        self._saved_for_termination = False
        #: the deadline decision taken on termination ("save" /
        #: "finalize" / "skip"; None until then) — callers print it so a
        #: skipped save is never reported as a checkpoint
        self.termination_decision: Optional[str] = None
        self._prev_handlers = {}
        self._consensus = None  # lazily-built (sharding, jitted max) pair
        self._writer: Optional[AsyncCheckpointWriter] = None
        # async save whose manifest is not yet committed — finalized
        # before the next save / restore / close, and IMMEDIATELY for a
        # termination save (durability claim). Keys: step, host_state
        # (device->host snapshot taken at issuance: the caller may donate
        # the buffers the moment step() returns), fingerprint (computed
        # from it at finalize — background thread for overlapped saves),
        # topology, issue_s (the synchronous issuance cost, folded into
        # the save EMA at finalize), fold_full. The abandon paths swap
        # self._pending to None (a GIL-atomic store) so _commit's
        # identity check refuses the marker for a disowned save.
        self._pending: Optional[dict] = None
        self._bg_thread: Optional[threading.Thread] = None
        self._abandoned_step: Optional[int] = None
        # monotonic arrival time of the first termination signal — the
        # grace budget counts down from HERE, not from the poll that
        # noticed it (polls can lag the signal by most of a train step)
        self._sigterm_t: Optional[float] = None
        # measured durable-save cost EMAs (seconds): full save and
        # finalize-only. Persisted in the manifest ("autoresume" block)
        # and re-seeded by restore(), so a freshly restarted job can make
        # a deadline decision before its own first save completes.
        self._save_ema: Optional[float] = None
        self._finalize_ema: Optional[float] = None
        if install_handlers:
            for sig in signals:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)

    # -- checkpoint IO -----------------------------------------------------

    def _integrity(self):
        # lazy: apex_tpu.resilience imports this module's package
        from apex_tpu.resilience import integrity

        return integrity

    def _manifest_extra(self) -> dict:
        return {"autoresume": {
            "save_ema_s": self._save_ema,
            "finalize_ema_s": self._finalize_ema,
        }}

    def _retry(self, fn, what: str, deadline_s: Optional[float] = None):
        """Shared bounded-retry policy (resilience/retry.py), imported
        lazily — the resilience package init must not run during this
        module's import. Jittered so multi-host retries de-stampede."""
        from apex_tpu.resilience.retry import retry_with_backoff

        return retry_with_backoff(
            fn, retries=self.save_retries, backoff=self.save_backoff,
            jitter=0.25, deadline_s=deadline_s, what=what,
        )

    def _fingerprint_pending(self, pending: dict) -> None:
        """Compute the per-leaf crc32 fingerprint from the issuance-time
        host snapshot (background thread for overlapped saves) and free
        the snapshot."""
        if (pending["fingerprint"] is None
                and pending["host_state"] is not None):
            pending["fingerprint"] = self._integrity().tree_fingerprint(
                pending["host_state"]
            )
        pending["host_state"] = None

    def _commit(self, pending: dict) -> None:
        """Land the manifest commit marker + retention for ``pending``.

        Refuses when ``self._pending`` no longer IS ``pending`` — the
        abandon paths (deadline ``skip`` arm, incident exit) swap it to
        None, and a commit marker for a save the job disowned is exactly
        the torn-but-plausible state the tombstone exists to prevent.
        The residual race (abandon landing between this check and the
        marker write) resolves to whichever ``os.replace`` runs last;
        both outcomes are safe — a tombstoned dir restores from the
        previous verified step, a committed dir is genuinely durable
        because the write AND fingerprint completed before the marker.
        """
        if self._pending is not pending:
            return
        if jax.process_index() == 0:
            integrity = self._integrity()
            # retried, and _pending is only cleared on success: a
            # transient manifest-write failure is re-attempted at the
            # next finalize point instead of silently losing the
            # commit marker
            self._retry(
                lambda: integrity.write_manifest(
                    os.path.join(self.directory, f"step_{pending['step']}"),
                    fingerprint=pending["fingerprint"],
                    topology=pending["topology"],
                    extra=self._manifest_extra(),
                ),
                what="manifest commit",
            )
            if self.keep_last_n is not None:
                integrity.apply_retention(self.directory, self.keep_last_n)
        if self.journal is not None:
            # the checkpoint is now durable — make its journal anchor
            # durable too (sidecar fsync; may run on the background
            # finalize thread, FlightRecorder is thread-safe)
            self.journal.flush()
        if self._pending is pending:
            self._pending = None

    def _finalize_pending_background(self, pending: dict) -> None:
        """The background-finalize body: runs on the writer's finalize
        thread AFTER the write is durable. Fingerprint + commit marker,
        entirely off the training thread — the ckpt_save badput of an
        overlapped save collapses to its issuance slice."""
        self._fingerprint_pending(pending)
        self._commit(pending)

    def _bg_finalize_failed(self, pending: dict, error: BaseException) -> None:
        logger.warning(
            "background finalize of step_%d failed (%s); the manifest "
            "commit will be re-attempted synchronously at the next "
            "finalize point", pending["step"], error,
        )

    def finalize(self) -> None:
        """Block until every issued save is durable AND committed.

        ``AsyncCheckpointWriter.wait()``-style finalization plus the
        integrity manifest (the commit marker) and retention sweep. A
        save is only as durable as this call — ``step()`` performs it
        before reporting a termination save, and interval saves commit in
        the background (module docstring) with this as the join point.

        Emits a blocking ``ckpt_save`` span ONLY when it actually blocks:
        joining an already-finished background finalize is free, which is
        what lets the accountant book an overlapped save at
        issuance-only.
        """
        thread = self._bg_thread
        if thread is not None:
            if thread.is_alive():
                pend = self._pending
                t0 = time.monotonic()
                # goodput span: host wall time BLOCKED on the background
                # finalize — the piece the async overlap did NOT hide
                with _goodput_span(
                        "ckpt_save",
                        step=pend["step"] if pend else -1):
                    thread.join()
                # the blocked-join cost is the real "finalize the pending
                # save" sample the deadline decision's finalize arm needs
                self._finalize_ema = _ema(
                    self._finalize_ema, time.monotonic() - t0)
            else:
                thread.join()
            self._bg_thread = None
        if self._pending is None:
            return
        # synchronous commit: durable saves, the first-save calibration,
        # and the fallback when a background finalize failed
        pending = self._pending
        step = pending["step"]
        t0 = time.monotonic()
        # goodput span: host wall time BLOCKED on checkpoint durability
        # (the wait + fingerprint + manifest commit + retention sweep)
        with _goodput_span("ckpt_save", step=step):
            self._writer.wait()
            self._fingerprint_pending(pending)
            # EMAs folded BEFORE the manifest write so THIS save's cost
            # is already in the persisted block (a restarted job inherits
            # it from its very first checkpoint). The manifest write +
            # retention sweep are excluded from the sample.
            #
            # The FULL-save EMA only folds UNOVERLAPPED samples
            # (fold_full: durable saves and the first-save calibration,
            # where finalize immediately follows issuance). An interval
            # save finalized after overlap observes wait ~ 0 because
            # training HID the write — folding that would converge the
            # EMA to the issuance cost alone, and the deadline decision
            # would pick "save" for grace budgets a fresh (nothing to
            # hide behind) termination save cannot fit.
            wait_s = time.monotonic() - t0
            self._finalize_ema = _ema(self._finalize_ema, wait_s)
            if pending["fold_full"]:
                self._save_ema = _ema(
                    self._save_ema, pending["issue_s"] + wait_s)
            self._commit(pending)

    def _topology(self, state) -> Optional[dict]:
        from apex_tpu.resilience.elastic import topology_block

        try:
            return topology_block(state)
        except Exception as e:  # noqa: BLE001 - durability outranks metadata
            logger.warning("topology block skipped: %s", e)
            return None

    def _save(self, step: int, state: Any, durable: bool) -> None:
        integrity = self._integrity()
        if self.journal is not None:
            # replay-anchor convention: the checkpoint labeled ``step``
            # holds the state ENTERING step ``step`` (the caller passes
            # the post-step state as step+1). The replayer re-verifies
            # the manifest before trusting the anchor, so recording at
            # issuance (before the async commit lands) is safe.
            self.journal.anchor(step)
        if not self.use_async:
            t0 = time.monotonic()
            with _goodput_span("ckpt_save", step=step):
                integrity.save_checkpoint_verified(
                    self.directory, step, state,
                    retries=self.save_retries, backoff=self.save_backoff,
                    keep_last_n=(self.keep_last_n
                                 if jax.process_index() == 0 else None),
                    extra=self._manifest_extra(),
                )
            self._save_ema = _ema(self._save_ema, time.monotonic() - t0)
            return
        self.finalize()  # previous pending save first (ordering + bounded lag)
        if self._writer is None:
            self._writer = AsyncCheckpointWriter()
        t0 = time.monotonic()
        # goodput span: the synchronous slice of an async save — the
        # device->host snapshot and the write ISSUANCE. The fingerprint
        # crc32s and the manifest's per-file sha256 moved OFF this slice
        # into the background finalize (module docstring); only the
        # snapshot stays, because the caller may donate/mutate the
        # buffers the moment step() returns and the bytes must be
        # captured before that.
        with _goodput_span("ckpt_save", step=step):
            host_state = (
                jax.device_get(state) if self.leaf_fingerprint else None
            )
            topology = self._topology(state)
            # the retry covers save ISSUANCE (snapshot-to-host + handoff);
            # an error in the background write itself surfaces at the
            # finalize — by then the source buffers may be donated, so
            # there is nothing left to re-save from
            self._retry(
                lambda: self._writer.save(self.directory, step, state),
                what="checkpoint save issuance",
            )
        # first-save calibration: with no full-cost sample yet, finalize
        # immediately so the EMA's seed measures a REAL durable save
        # (issuance + the whole write, nothing overlapped) — one blocking
        # save, paid when the run is cheapest to pause
        calibrate = self._save_ema is None
        self._pending = {
            "step": step, "host_state": host_state, "fingerprint": None,
            "topology": topology,
            "issue_s": time.monotonic() - t0,
            "fold_full": durable or calibrate,
        }
        if durable or calibrate:
            self.finalize()
        elif self.background_finalize:
            pending = self._pending
            self._bg_thread = self._writer.finalize_async(
                functools.partial(self._finalize_pending_background,
                                  pending),
                on_error=functools.partial(self._bg_finalize_failed,
                                           pending),
            )

    def _abandon_pending(self) -> None:
        """Drop the pending save WITHOUT committing its manifest.

        The deadline decision's ``skip`` arm (and the incident exit's
        only arm): the background write may still land its bytes, but
        with no manifest the step dir is uncommitted and every verified
        restore skips it — torn, but cleanly so. The last verified
        checkpoint stays the durable one. The ``self._pending = None``
        store is the (GIL-atomic) handshake with the background
        finalize's ``_commit`` identity check; never blocks on the
        writer, so it is safe from the watchdog thread against a wedged
        save.
        """
        if self._pending is None:
            return
        self._abandoned_step = self._pending["step"]
        logger.warning(
            "abandoning un-finalized async save of step_%d (grace budget): "
            "no manifest will be committed; restore uses the last verified "
            "step", self._abandoned_step,
        )
        self._pending = None
        # tombstone manifest: the background write may still complete the
        # dir, and without this a legacy-tolerant restore would accept
        # the un-vouched-for state (integrity.write_abandoned_marker)
        if jax.process_index() == 0:
            try:
                self._integrity().write_abandoned_marker(
                    os.path.join(self.directory,
                                 f"step_{self._abandoned_step}")
                )
            except OSError as e:
                logger.warning("abandoned-marker write failed: %s", e)
        if self.journal is not None:
            # the anchor recorded at issuance now points at a tombstoned
            # dir (the replayer's verification rejects it anyway) — note
            # the abandonment for forensics and make the journal durable
            self.journal.event(self._abandoned_step, "anchor_abandoned")
            self.journal.flush()

    def prepare_incident_exit(self) -> Optional[int]:
        """Bounded preparation for an incident self-termination.

        Called by the hung-job responder (``resilience.health``) from its
        WATCHDOG thread just before ``os._exit``: abandon the
        un-committed pending async save — tombstone manifest included —
        so the next incarnation restores the last VERIFIED step instead
        of a maybe-torn one. Deliberately never waits on the writer or
        joins the background finalize (either may be part of the wedge);
        a save whose background finalize already committed is left
        durable (nothing pending, nothing to abandon). Returns the
        abandoned step, or None when nothing was pending.
        """
        if self._pending is None:
            if self.journal is not None:
                # even with nothing pending, the incident post-mortem
                # needs the journal durable (the wedged main thread may
                # never reach the recorder's own close)
                self.journal.flush()
            return None
        self._abandon_pending()
        return self._abandoned_step

    # -- signal plumbing ---------------------------------------------------

    def _on_signal(self, signum, frame):
        # flag only: checkpoint IO from inside a signal handler could fire
        # mid-XLA-dispatch; the training loop polls at a safe boundary.
        # The timestamp is one float store — async-signal-safe — and
        # anchors the grace-budget countdown at signal ARRIVAL.
        if self._sigterm_t is None:
            self._sigterm_t = time.monotonic()
        self._requested = True

    def close(self):
        """Finalize pending saves and restore previous signal handlers."""
        self.finalize()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for sig, h in self._prev_handlers.items():
            _signal.signal(sig, h)
        self._prev_handlers = {}

    def request_resume(self):
        """Programmatic preemption request (ref ADLR ``request_resume``)."""
        if self._sigterm_t is None:
            self._sigterm_t = time.monotonic()
        self._requested = True

    @property
    def termination_signaled(self) -> bool:
        """Host-LOCAL signal hint: True once THIS process saw a
        termination signal or ``request_resume``. No consensus collective
        (unlike :meth:`termination_requested`), so it is free to poll —
        callers use it to stand down machinery that must not misread the
        upcoming blocking termination save as a fault (the GPT example
        stops its incident responder on it: a minutes-long durable save
        is not a wedged step)."""
        return self._requested

    # -- consensus ---------------------------------------------------------

    def termination_requested(self) -> bool:
        """True once ANY host has received a termination signal.

        Multi-host: each host contributes its local flag through a global
        array spanning all processes; one jitted max reduces it. All hosts
        reach the same answer for the same poll, so they checkpoint the
        same step. (Mirrors the reference polling contract,
        pipeline_parallel/utils.get_autoresume — but distributed-safe.)
        """
        if jax.device_count() == 1:
            return self._requested
        # the collective path runs on ANY multi-device mesh so the CPU-mesh
        # tests exercise the code multi-host actually uses (on one process
        # it reduces identical flags; the cost is one scalar all-reduce).
        # The mesh/sharding/jitted reduction are built ONCE and reused —
        # a fresh jax.jit per poll would re-trace and re-dispatch every
        # step, dwarfing the advertised one-scalar-all-reduce cost.
        if self._consensus is None:
            mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("hosts",))
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("hosts")
            )
            reduce = jax.jit(jnp.max, out_shardings=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()))
            self._consensus = (sharding, reduce)
        sharding, reduce = self._consensus
        local = np.asarray([np.float32(self._requested)])
        # every device in this process carries the process-local flag
        per_dev = [
            jax.device_put(local, d) for d in jax.local_devices()
        ]
        global_flags = jax.make_array_from_single_device_arrays(
            (jax.device_count(),), sharding, per_dev
        )
        anyone = reduce(global_flags)
        return bool(np.asarray(anyone)[()] > 0)

    # -- deadline budget ---------------------------------------------------

    def _emergency_decision(self, now: Optional[float] = None
                            ) -> Tuple[str, dict]:
        """(action, info) for the termination save: ``save`` /
        ``finalize`` / ``skip`` (module docstring). Pure function of the
        grace budget, signal arrival time, EMAs, and pending state —
        seedable and unit-testable.
        """
        now = time.monotonic() if now is None else now
        info = {
            "grace_s": self.grace_s,
            "save_ema_s": self._save_ema,
            "finalize_ema_s": self._finalize_ema,
            "pending_step": (self._pending["step"]
                             if self._pending else None),
            "remaining_s": None,
        }
        if self.grace_s is None:
            return "save", info  # no budget: durability wins
        anchor = self._sigterm_t if self._sigterm_t is not None else now
        remaining = (anchor + self.grace_s) - now
        info["remaining_s"] = remaining
        if self._save_ema is None:
            # no measured history to reason from: attempt the save (the
            # conservative-for-durability default; a first-save job has
            # nothing pending to finalize anyway)
            return "save", info
        if remaining >= self.safety_factor * self._save_ema:
            return "save", info
        est_fin = (self._finalize_ema
                   if self._finalize_ema is not None else self._save_ema)
        if self._pending is not None and remaining >= (
                self.safety_factor * est_fin):
            return "finalize", info
        return "skip", info

    # -- loop API ----------------------------------------------------------

    def step(self, step: int, state: Any) -> bool:
        """Call after each training step with the POST-step state.

        Saves on the periodic interval and on termination request; returns
        True when the caller should exit. On termination the deadline
        decision (module docstring) picks save / finalize-pending /
        skip-and-rely-on-last-verified so the manifest commit always
        lands inside the grace budget; the decision is emitted as a
        ckpt_save span slice plus a ``kind="preemption"`` event.
        """
        terminating = self.termination_requested()
        if terminating and not self._saved_for_termination:
            decision, info = self._emergency_decision()
            self.termination_decision = decision
            # durable semantics per arm: "save" waits for the write AND
            # commits the manifest BEFORE telling the caller it may exit
            # — an exit on an un-finalized async save is exactly the torn
            # checkpoint this machinery exists to prevent; "finalize"
            # commits only the in-flight interval save; "skip" abandons
            # even that commit (a marker racing the kill is worse than a
            # clean fallback to the last verified step)
            with _goodput_span("ckpt_save", step=step, decision=decision):
                if decision == "save":
                    self._save(step, state, durable=True)
                    saved_step = step
                elif decision == "finalize":
                    saved_step = info["pending_step"]
                    self.finalize()
                else:
                    self._abandon_pending()
                    saved_step = None
            router = _goodput_router()
            if router is not None:
                router.event(
                    "preemption", step, decision=decision,
                    saved_step=saved_step, **info,
                )
            logger.info(
                "termination at step %d: decision=%s saved_step=%s "
                "(grace_s=%s save_ema_s=%s remaining_s=%s)",
                step, decision, saved_step, info["grace_s"],
                info["save_ema_s"], info["remaining_s"],
            )
            self._saved_for_termination = True
            return True
        if terminating:
            return True
        if self.interval and step % self.interval == 0:
            self._save(step, state, durable=False)
        return False

    def _seed_emas(self, step: int) -> None:
        """Inherit persisted save-duration EMAs from the restored step's
        manifest (only when this process has no measurements yet)."""
        manifest = self._integrity().read_manifest(
            os.path.join(self.directory, f"step_{step}")
        ) or {}
        block = manifest.get("autoresume") or {}
        if self._save_ema is None and block.get("save_ema_s") is not None:
            self._save_ema = float(block["save_ema_s"])
        if (self._finalize_ema is None
                and block.get("finalize_ema_s") is not None):
            self._finalize_ema = float(block["finalize_ema_s"])

    def restore(self, init_state: Any) -> Tuple[int, Any]:
        """(step, state): newest RESTORABLE checkpoint, else (0, init).

        ``init_state`` also serves as the restore target so dtypes and
        shardings round-trip exactly (see utils/checkpoint.py).

        With ``verify=True`` (default) restoration walks step dirs
        newest-first, checks each integrity manifest, and falls back past
        torn / bit-flipped / uncommitted checkpoints to the newest step
        that verifies (pre-manifest legacy checkpoints are accepted, as
        their corruption is undetectable). When the newest verified
        manifest's topology block disagrees with the live mesh (derived
        from ``init_state``'s shardings, or passed as ``mesh=``), the
        restore reshards through ``resilience.elastic`` — and REFUSES
        (``ElasticRestoreError``) on layout changes it cannot prove
        resharddable, rather than misloading. ``verify=False`` restores
        the raw latest step and lets corruption crash the run.
        """
        self.finalize()
        # goodput span: restart recovery cost (badput phase ckpt_restore)
        with _goodput_span("ckpt_restore"):
            if not self.verify:
                step = latest_step(self.directory)
                if step is None:
                    return 0, init_state
                # retried: a transient IO hiccup on the restore read must
                # not crash the restart (the verified path gets its
                # resilience from the newest-first fallback walk instead)
                return step, self._retry(
                    lambda: load_checkpoint(
                        self.directory, step, target=init_state
                    ),
                    what="checkpoint restore",
                )
            from apex_tpu.resilience import elastic

            mesh = self.mesh
            if mesh is None:
                mesh = elastic.derive_mesh(init_state)
            if mesh is not None and elastic.needs_reshard(
                    self.directory, mesh):
                step, state = elastic.restore_resharded(
                    self.directory, init_state, mesh=mesh
                )
                logger.info(
                    "elastic restore: resharded step_%d onto the live "
                    "mesh %s", step, dict(mesh.shape),
                )
                self._seed_emas(step)
                return step, state
            try:
                step, state = self._integrity().load_checkpoint_verified(
                    self.directory, target=init_state, allow_unverified=True
                )
            except FileNotFoundError:
                return 0, init_state
            self._seed_emas(step)
            return step, state
