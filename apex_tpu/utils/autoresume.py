"""Preemption-safe training: signal-triggered checkpoint + resume.

Reference parity: the reference's only failure-recovery hook is the ADLR
cluster auto-resume object surfaced through
``testing/global_vars.get_adlr_autoresume`` (ref global_vars.py:75) and
polled via ``pipeline_parallel/utils.get_autoresume`` — an external object
with ``termination_requested()`` / ``request_resume()`` that the training
loop is expected to poll, save, and exit on. There is no in-tree
implementation.

TPU design: preemptible TPU VMs deliver SIGTERM ahead of eviction, so the
capability is first-class here instead of an external hook:

- ``AutoResume`` installs a signal handler that only flips a host-local
  flag (async-signal-safe; no IO in the handler).
- On multi-host meshes the flag must become a CONSENSUS before anyone
  saves: hosts receive SIGTERM at different wall-clock times, and a host
  that checkpoints at step N while others continue to N+3 produces a torn
  checkpoint. ``termination_requested()`` therefore ORs the host-local
  flags across all devices (a tiny jitted ``jnp.max`` over a
  process-spanning global array), so every host sees True at the same
  step boundary and they all save the same step. Single-host meshes skip
  the collective.
- ``step()`` combines the periodic-interval save (ref
  ``--adlr-autoresume-interval`` semantics) with the termination save;
  ``restore()`` resumes from the newest step directory.

The consensus collective costs one scalar all-reduce per *polled* step;
poll every step (it is negligible next to a train step) or at a cadence.

Deadline-budgeted termination saves: preemption grace windows are FIXED
(the scheduler kills the process ``grace_s`` seconds after SIGTERM,
saved or not), so blindly starting a full sync save on termination can
be worse than not saving — a save that outlives the grace window leaves
a torn, uncommitted step dir AND burned the time that finalizing an
already-in-flight save would have used. ``AutoResume`` therefore
measures its own recent save durations (EMAs, persisted in the
integrity manifest so a restarted job inherits them) and, when a grace
budget is configured (``grace_s=`` or ``APEX_TPU_PREEMPTION_GRACE_S``),
picks the most durable action that provably fits the remaining budget:

- ``save``      — full durable save of the CURRENT step (budget covers
  the measured full-save EMA, or no history/budget to reason from);
- ``finalize``  — commit only the pending async interval save (budget
  covers the finalize EMA but not a fresh save): the job loses the
  steps since the last interval, not the whole run;
- ``skip``      — abandon even the pending save's manifest commit and
  rely on the last already-verified checkpoint: a manifest commit that
  might land after the kill is exactly the torn-but-plausible state the
  integrity machinery exists to prevent. No torn manifest is ever
  treated as durable.

The decision is emitted as a ``kind="span"`` ckpt_save slice (with a
``decision`` field) plus a ``kind="preemption"`` event through the
goodput stream, so post-mortems can audit what the job chose and why.

Elastic restart: ``restore()`` compares the newest verified manifest's
topology block against the live mesh and, on a mismatch, routes through
``resilience.elastic.restore_resharded`` — params re-laid-out onto the
new mesh, ZeRO flat optimizer state regrouped across the changed dp
size, refuse-don't-guess on anything else (docs/resilience.md "Elastic
restart").
"""

import logging
import os
import signal as _signal
import time
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.monitor.goodput.spans import get_router as _goodput_router
from apex_tpu.monitor.goodput.spans import span as _goodput_span
from apex_tpu.utils.checkpoint import (
    AsyncCheckpointWriter,
    latest_step,
    load_checkpoint,
)

__all__ = ["AutoResume", "GRACE_ENV"]

logger = logging.getLogger("apex_tpu.utils.autoresume")

#: environment default for the preemption grace budget (seconds between
#: SIGTERM and the scheduler's kill); unset/empty means "no budget" and
#: termination always attempts the full durable save
GRACE_ENV = "APEX_TPU_PREEMPTION_GRACE_S"


def _env_grace() -> Optional[float]:
    raw = os.environ.get(GRACE_ENV, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring unparseable %s=%r", GRACE_ENV, raw)
        return None


def _ema(old: Optional[float], x: float, alpha: float = 0.5) -> float:
    """Recent-weighted EMA; seeds from the first sample."""
    return x if old is None else (1.0 - alpha) * old + alpha * x


class AutoResume:
    """Poll-based preemption handling for training loops.

    Usage::

        ar = AutoResume(save_dir, interval=1000)
        step0, state = ar.restore(init_state)          # 0, init on fresh start
        for step in range(step0, total_steps):
            state = train_step(state)
            if ar.step(step + 1, state):               # saved-for-termination
                break                                  # exit; scheduler restarts

    ``state`` may be any checkpointable pytree. The object is also usable
    as the ``get_adlr_autoresume()`` global in the testing harness — it
    implements ``termination_requested()`` and ``request_resume()`` with
    the reference's polling contract.

    Durability & integrity (resilience.integrity wiring):

    - interval saves are ASYNC (the next train step overlaps the write);
      each is finalized — ``wait()`` + checksum-manifest commit + optional
      ``keep_last_n`` retention — before the next save is issued, or
      explicitly via :meth:`finalize` / :meth:`close`;
    - a TERMINATION save is finalized before ``step()`` returns True, so
      "saved, you may exit" is never claimed for bytes still in flight —
      unless a configured grace budget (``grace_s`` /
      ``APEX_TPU_PREEMPTION_GRACE_S``) provably cannot fit it, in which
      case the deadline decision (module docstring) downgrades to
      finalize-pending-only or skip-and-rely-on-last-verified;
    - ``restore()`` skips torn or corrupt step directories (manifest
      verification) and falls back to the newest verified checkpoint;
      when the saved topology disagrees with the live mesh it reshards
      through ``resilience.elastic`` (pass ``mesh=`` explicitly if the
      state leaves carry no ``NamedSharding`` to derive it from).

    Deadline-decision caveat (multi-host): the decision inputs — signal
    arrival time and save-duration EMAs — are host-local, so hosts could
    in principle pick different actions. In practice the EMAs track the
    same collective saves and the grace budget is a cluster constant;
    deployments that need hard agreement should pin ``grace_s`` and rely
    on the consensus flag making every host decide at the same step.
    """

    #: headroom multiplier on the measured EMAs before an action is
    #: considered to fit the remaining grace budget
    safety_factor = 1.25

    def __init__(
        self,
        directory: str,
        interval: Optional[int] = None,
        signals: Sequence[int] = (_signal.SIGTERM,),
        install_handlers: bool = True,
        keep_last_n: Optional[int] = None,
        use_async: bool = True,
        verify: bool = True,
        save_retries: int = 3,
        save_backoff: float = 0.1,
        leaf_fingerprint: bool = True,
        grace_s: Optional[float] = None,
        mesh=None,
    ):
        self.directory = os.path.abspath(directory)
        self.interval = interval
        self.keep_last_n = keep_last_n
        self.use_async = use_async
        self.verify = verify
        self.save_retries = save_retries
        self.save_backoff = save_backoff
        # per-leaf crc32 fingerprints enable restore-time deep verification
        # but cost a synchronous full-state device->host copy per save; the
        # manifest's per-file digests (computed at finalize, off the saved
        # bytes) still catch disk corruption with this off
        self.leaf_fingerprint = leaf_fingerprint
        self.grace_s = grace_s if grace_s is not None else _env_grace()
        self.mesh = mesh
        self._requested = False
        self._saved_for_termination = False
        #: the deadline decision taken on termination ("save" /
        #: "finalize" / "skip"; None until then) — callers print it so a
        #: skipped save is never reported as a checkpoint
        self.termination_decision: Optional[str] = None
        self._prev_handlers = {}
        self._consensus = None  # lazily-built (sharding, jitted max) pair
        self._writer: Optional[AsyncCheckpointWriter] = None
        # async save whose manifest is not yet committed — finalized
        # before the next save / restore / close, and IMMEDIATELY for a
        # termination save (durability claim). Keys: step, fingerprint,
        # topology (both captured at save time: the caller may donate the
        # buffers the moment step() returns), issue_s (the synchronous
        # issuance cost, folded into the save EMA at finalize)
        self._pending: Optional[dict] = None
        self._abandoned_step: Optional[int] = None
        # monotonic arrival time of the first termination signal — the
        # grace budget counts down from HERE, not from the poll that
        # noticed it (polls can lag the signal by most of a train step)
        self._sigterm_t: Optional[float] = None
        # measured durable-save cost EMAs (seconds): full save and
        # finalize-only. Persisted in the manifest ("autoresume" block)
        # and re-seeded by restore(), so a freshly restarted job can make
        # a deadline decision before its own first save completes.
        self._save_ema: Optional[float] = None
        self._finalize_ema: Optional[float] = None
        if install_handlers:
            for sig in signals:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)

    # -- checkpoint IO -----------------------------------------------------

    def _integrity(self):
        # lazy: apex_tpu.resilience imports this module's package
        from apex_tpu.resilience import integrity

        return integrity

    def _manifest_extra(self) -> dict:
        return {"autoresume": {
            "save_ema_s": self._save_ema,
            "finalize_ema_s": self._finalize_ema,
        }}

    def finalize(self) -> None:
        """Block until every issued save is durable AND committed.

        ``AsyncCheckpointWriter.wait()``-style finalization plus the
        integrity manifest (the commit marker) and retention sweep. A
        save is only as durable as this call — ``step()`` performs it
        before reporting a termination save, and interval saves are
        finalized before the next save is issued (one step of overlap).
        """
        if self._pending is None:
            return
        pending = self._pending
        step = pending["step"]
        t0 = time.monotonic()
        # goodput span: host wall time BLOCKED on checkpoint durability
        # (the wait + manifest commit + retention sweep) — the piece of
        # ckpt_save badput the async overlap did NOT hide
        with _goodput_span("ckpt_save", step=step):
            self._writer.wait()
            # EMAs folded BEFORE the manifest write so THIS save's cost
            # is already in the persisted block (a restarted job inherits
            # it from its very first checkpoint). The manifest write +
            # retention sweep are excluded from the sample — ms-scale
            # next to the checkpoint bytes.
            #
            # The FULL-save EMA only folds UNOVERLAPPED samples
            # (fold_full: durable saves and the first-save calibration,
            # where finalize immediately follows issuance). An interval
            # save finalized many steps later observes wait ~ 0 because
            # training HID the write — folding that would converge the
            # EMA to the issuance cost alone, and the deadline decision
            # would pick "save" for grace budgets a fresh (nothing to
            # hide behind) termination save cannot fit.
            wait_s = time.monotonic() - t0
            self._finalize_ema = _ema(self._finalize_ema, wait_s)
            if pending["fold_full"]:
                self._save_ema = _ema(
                    self._save_ema, pending["issue_s"] + wait_s)
            if jax.process_index() == 0:
                integrity = self._integrity()
                # retried, and _pending is only cleared on success: a
                # transient manifest-write failure is re-attempted at the
                # next finalize point instead of silently losing the
                # commit marker
                integrity.save_with_retry(
                    lambda: integrity.write_manifest(
                        os.path.join(self.directory, f"step_{step}"),
                        fingerprint=pending["fingerprint"],
                        topology=pending["topology"],
                        extra=self._manifest_extra(),
                    ),
                    retries=self.save_retries, backoff=self.save_backoff,
                )
                if self.keep_last_n is not None:
                    integrity.apply_retention(self.directory,
                                              self.keep_last_n)
        self._pending = None

    def _topology(self, state) -> Optional[dict]:
        from apex_tpu.resilience.elastic import topology_block

        try:
            return topology_block(state)
        except Exception as e:  # noqa: BLE001 - durability outranks metadata
            logger.warning("topology block skipped: %s", e)
            return None

    def _save(self, step: int, state: Any, durable: bool) -> None:
        integrity = self._integrity()
        if not self.use_async:
            t0 = time.monotonic()
            with _goodput_span("ckpt_save", step=step):
                integrity.save_checkpoint_verified(
                    self.directory, step, state,
                    retries=self.save_retries, backoff=self.save_backoff,
                    keep_last_n=(self.keep_last_n
                                 if jax.process_index() == 0 else None),
                    extra=self._manifest_extra(),
                )
            self._save_ema = _ema(self._save_ema, time.monotonic() - t0)
            return
        self.finalize()  # previous pending save first (ordering + bounded lag)
        if self._writer is None:
            self._writer = AsyncCheckpointWriter()
        t0 = time.monotonic()
        # goodput span: the synchronous slice of an async save — the
        # fingerprint's device->host copy and the write ISSUANCE (the
        # background write itself overlaps training and is accounted by
        # finalize()'s span when it blocks)
        with _goodput_span("ckpt_save", step=step):
            # fingerprint + topology NOW: the caller may donate/mutate
            # these buffers the moment step() returns, and the manifest
            # commits later
            fingerprint = (
                integrity.tree_fingerprint(state)
                if self.leaf_fingerprint else None
            )
            topology = self._topology(state)
            # the retry covers save ISSUANCE (snapshot-to-host + handoff);
            # an error in the background write itself surfaces un-retried
            # at the next finalize()'s wait() — by then the source buffers
            # may be donated, so there is nothing left to re-save from
            integrity.save_with_retry(
                lambda: self._writer.save(self.directory, step, state),
                retries=self.save_retries, backoff=self.save_backoff,
            )
        # first-save calibration: with no full-cost sample yet, finalize
        # immediately so the EMA's seed measures a REAL durable save
        # (issuance + the whole write, nothing overlapped) — one blocking
        # save, paid when the run is cheapest to pause
        calibrate = self._save_ema is None
        self._pending = {
            "step": step, "fingerprint": fingerprint, "topology": topology,
            "issue_s": time.monotonic() - t0,
            "fold_full": durable or calibrate,
        }
        if durable or calibrate:
            self.finalize()

    def _abandon_pending(self) -> None:
        """Drop the pending save WITHOUT committing its manifest.

        The deadline decision's ``skip`` arm: the background write may
        still land its bytes, but with no manifest the step dir is
        uncommitted and every verified restore skips it — torn, but
        cleanly so. The last verified checkpoint stays the durable one.
        """
        if self._pending is None:
            return
        self._abandoned_step = self._pending["step"]
        logger.warning(
            "abandoning un-finalized async save of step_%d (grace budget): "
            "no manifest will be committed; restore uses the last verified "
            "step", self._abandoned_step,
        )
        self._pending = None
        # tombstone manifest: the background write may still complete the
        # dir, and without this a legacy-tolerant restore would accept
        # the un-vouched-for state (integrity.write_abandoned_marker)
        if jax.process_index() == 0:
            try:
                self._integrity().write_abandoned_marker(
                    os.path.join(self.directory,
                                 f"step_{self._abandoned_step}")
                )
            except OSError as e:
                logger.warning("abandoned-marker write failed: %s", e)

    # -- signal plumbing ---------------------------------------------------

    def _on_signal(self, signum, frame):
        # flag only: checkpoint IO from inside a signal handler could fire
        # mid-XLA-dispatch; the training loop polls at a safe boundary.
        # The timestamp is one float store — async-signal-safe — and
        # anchors the grace-budget countdown at signal ARRIVAL.
        if self._sigterm_t is None:
            self._sigterm_t = time.monotonic()
        self._requested = True

    def close(self):
        """Finalize pending saves and restore previous signal handlers."""
        self.finalize()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for sig, h in self._prev_handlers.items():
            _signal.signal(sig, h)
        self._prev_handlers = {}

    def request_resume(self):
        """Programmatic preemption request (ref ADLR ``request_resume``)."""
        if self._sigterm_t is None:
            self._sigterm_t = time.monotonic()
        self._requested = True

    # -- consensus ---------------------------------------------------------

    def termination_requested(self) -> bool:
        """True once ANY host has received a termination signal.

        Multi-host: each host contributes its local flag through a global
        array spanning all processes; one jitted max reduces it. All hosts
        reach the same answer for the same poll, so they checkpoint the
        same step. (Mirrors the reference polling contract,
        pipeline_parallel/utils.get_autoresume — but distributed-safe.)
        """
        if jax.device_count() == 1:
            return self._requested
        # the collective path runs on ANY multi-device mesh so the CPU-mesh
        # tests exercise the code multi-host actually uses (on one process
        # it reduces identical flags; the cost is one scalar all-reduce).
        # The mesh/sharding/jitted reduction are built ONCE and reused —
        # a fresh jax.jit per poll would re-trace and re-dispatch every
        # step, dwarfing the advertised one-scalar-all-reduce cost.
        if self._consensus is None:
            mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("hosts",))
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("hosts")
            )
            reduce = jax.jit(jnp.max, out_shardings=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()))
            self._consensus = (sharding, reduce)
        sharding, reduce = self._consensus
        local = np.asarray([np.float32(self._requested)])
        # every device in this process carries the process-local flag
        per_dev = [
            jax.device_put(local, d) for d in jax.local_devices()
        ]
        global_flags = jax.make_array_from_single_device_arrays(
            (jax.device_count(),), sharding, per_dev
        )
        anyone = reduce(global_flags)
        return bool(np.asarray(anyone)[()] > 0)

    # -- deadline budget ---------------------------------------------------

    def _emergency_decision(self, now: Optional[float] = None
                            ) -> Tuple[str, dict]:
        """(action, info) for the termination save: ``save`` /
        ``finalize`` / ``skip`` (module docstring). Pure function of the
        grace budget, signal arrival time, EMAs, and pending state —
        seedable and unit-testable.
        """
        now = time.monotonic() if now is None else now
        info = {
            "grace_s": self.grace_s,
            "save_ema_s": self._save_ema,
            "finalize_ema_s": self._finalize_ema,
            "pending_step": (self._pending["step"]
                             if self._pending else None),
            "remaining_s": None,
        }
        if self.grace_s is None:
            return "save", info  # no budget: durability wins
        anchor = self._sigterm_t if self._sigterm_t is not None else now
        remaining = (anchor + self.grace_s) - now
        info["remaining_s"] = remaining
        if self._save_ema is None:
            # no measured history to reason from: attempt the save (the
            # conservative-for-durability default; a first-save job has
            # nothing pending to finalize anyway)
            return "save", info
        if remaining >= self.safety_factor * self._save_ema:
            return "save", info
        est_fin = (self._finalize_ema
                   if self._finalize_ema is not None else self._save_ema)
        if self._pending is not None and remaining >= (
                self.safety_factor * est_fin):
            return "finalize", info
        return "skip", info

    # -- loop API ----------------------------------------------------------

    def step(self, step: int, state: Any) -> bool:
        """Call after each training step with the POST-step state.

        Saves on the periodic interval and on termination request; returns
        True when the caller should exit. On termination the deadline
        decision (module docstring) picks save / finalize-pending /
        skip-and-rely-on-last-verified so the manifest commit always
        lands inside the grace budget; the decision is emitted as a
        ckpt_save span slice plus a ``kind="preemption"`` event.
        """
        terminating = self.termination_requested()
        if terminating and not self._saved_for_termination:
            decision, info = self._emergency_decision()
            self.termination_decision = decision
            # durable semantics per arm: "save" waits for the write AND
            # commits the manifest BEFORE telling the caller it may exit
            # — an exit on an un-finalized async save is exactly the torn
            # checkpoint this machinery exists to prevent; "finalize"
            # commits only the in-flight interval save; "skip" abandons
            # even that commit (a marker racing the kill is worse than a
            # clean fallback to the last verified step)
            with _goodput_span("ckpt_save", step=step, decision=decision):
                if decision == "save":
                    self._save(step, state, durable=True)
                    saved_step = step
                elif decision == "finalize":
                    saved_step = info["pending_step"]
                    self.finalize()
                else:
                    self._abandon_pending()
                    saved_step = None
            router = _goodput_router()
            if router is not None:
                router.event(
                    "preemption", step, decision=decision,
                    saved_step=saved_step, **info,
                )
            logger.info(
                "termination at step %d: decision=%s saved_step=%s "
                "(grace_s=%s save_ema_s=%s remaining_s=%s)",
                step, decision, saved_step, info["grace_s"],
                info["save_ema_s"], info["remaining_s"],
            )
            self._saved_for_termination = True
            return True
        if terminating:
            return True
        if self.interval and step % self.interval == 0:
            self._save(step, state, durable=False)
        return False

    def _seed_emas(self, step: int) -> None:
        """Inherit persisted save-duration EMAs from the restored step's
        manifest (only when this process has no measurements yet)."""
        manifest = self._integrity().read_manifest(
            os.path.join(self.directory, f"step_{step}")
        ) or {}
        block = manifest.get("autoresume") or {}
        if self._save_ema is None and block.get("save_ema_s") is not None:
            self._save_ema = float(block["save_ema_s"])
        if (self._finalize_ema is None
                and block.get("finalize_ema_s") is not None):
            self._finalize_ema = float(block["finalize_ema_s"])

    def restore(self, init_state: Any) -> Tuple[int, Any]:
        """(step, state): newest RESTORABLE checkpoint, else (0, init).

        ``init_state`` also serves as the restore target so dtypes and
        shardings round-trip exactly (see utils/checkpoint.py).

        With ``verify=True`` (default) restoration walks step dirs
        newest-first, checks each integrity manifest, and falls back past
        torn / bit-flipped / uncommitted checkpoints to the newest step
        that verifies (pre-manifest legacy checkpoints are accepted, as
        their corruption is undetectable). When the newest verified
        manifest's topology block disagrees with the live mesh (derived
        from ``init_state``'s shardings, or passed as ``mesh=``), the
        restore reshards through ``resilience.elastic`` — and REFUSES
        (``ElasticRestoreError``) on layout changes it cannot prove
        resharddable, rather than misloading. ``verify=False`` restores
        the raw latest step and lets corruption crash the run.
        """
        self.finalize()
        # goodput span: restart recovery cost (badput phase ckpt_restore)
        with _goodput_span("ckpt_restore"):
            if not self.verify:
                step = latest_step(self.directory)
                if step is None:
                    return 0, init_state
                return step, load_checkpoint(
                    self.directory, step, target=init_state
                )
            from apex_tpu.resilience import elastic

            mesh = self.mesh
            if mesh is None:
                mesh = elastic.derive_mesh(init_state)
            if mesh is not None and elastic.needs_reshard(
                    self.directory, mesh):
                step, state = elastic.restore_resharded(
                    self.directory, init_state, mesh=mesh
                )
                logger.info(
                    "elastic restore: resharded step_%d onto the live "
                    "mesh %s", step, dict(mesh.shape),
                )
                self._seed_emas(step)
                return step, state
            try:
                step, state = self._integrity().load_checkpoint_verified(
                    self.directory, target=init_state, allow_unverified=True
                )
            except FileNotFoundError:
                return 0, init_state
            self._seed_emas(step)
            return step, state
