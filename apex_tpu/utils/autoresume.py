"""Preemption-safe training: signal-triggered checkpoint + resume.

Reference parity: the reference's only failure-recovery hook is the ADLR
cluster auto-resume object surfaced through
``testing/global_vars.get_adlr_autoresume`` (ref global_vars.py:75) and
polled via ``pipeline_parallel/utils.get_autoresume`` — an external object
with ``termination_requested()`` / ``request_resume()`` that the training
loop is expected to poll, save, and exit on. There is no in-tree
implementation.

TPU design: preemptible TPU VMs deliver SIGTERM ahead of eviction, so the
capability is first-class here instead of an external hook:

- ``AutoResume`` installs a signal handler that only flips a host-local
  flag (async-signal-safe; no IO in the handler).
- On multi-host meshes the flag must become a CONSENSUS before anyone
  saves: hosts receive SIGTERM at different wall-clock times, and a host
  that checkpoints at step N while others continue to N+3 produces a torn
  checkpoint. ``termination_requested()`` therefore ORs the host-local
  flags across all devices (a tiny jitted ``jnp.max`` over a
  process-spanning global array), so every host sees True at the same
  step boundary and they all save the same step. Single-host meshes skip
  the collective.
- ``step()`` combines the periodic-interval save (ref
  ``--adlr-autoresume-interval`` semantics) with the termination save;
  ``restore()`` resumes from the newest step directory.

The consensus collective costs one scalar all-reduce per *polled* step;
poll every step (it is negligible next to a train step) or at a cadence.
"""

import logging
import os
import signal as _signal
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.monitor.goodput.spans import span as _goodput_span
from apex_tpu.utils.checkpoint import (
    AsyncCheckpointWriter,
    latest_step,
    load_checkpoint,
)

__all__ = ["AutoResume"]

logger = logging.getLogger("apex_tpu.utils.autoresume")


class AutoResume:
    """Poll-based preemption handling for training loops.

    Usage::

        ar = AutoResume(save_dir, interval=1000)
        step0, state = ar.restore(init_state)          # 0, init on fresh start
        for step in range(step0, total_steps):
            state = train_step(state)
            if ar.step(step + 1, state):               # saved-for-termination
                break                                  # exit; scheduler restarts

    ``state`` may be any checkpointable pytree. The object is also usable
    as the ``get_adlr_autoresume()`` global in the testing harness — it
    implements ``termination_requested()`` and ``request_resume()`` with
    the reference's polling contract.

    Durability & integrity (resilience.integrity wiring):

    - interval saves are ASYNC (the next train step overlaps the write);
      each is finalized — ``wait()`` + checksum-manifest commit + optional
      ``keep_last_n`` retention — before the next save is issued, or
      explicitly via :meth:`finalize` / :meth:`close`;
    - a TERMINATION save is finalized before ``step()`` returns True, so
      "saved, you may exit" is never claimed for bytes still in flight;
    - ``restore()`` skips torn or corrupt step directories (manifest
      verification) and falls back to the newest verified checkpoint.
    """

    def __init__(
        self,
        directory: str,
        interval: Optional[int] = None,
        signals: Sequence[int] = (_signal.SIGTERM,),
        install_handlers: bool = True,
        keep_last_n: Optional[int] = None,
        use_async: bool = True,
        verify: bool = True,
        save_retries: int = 3,
        save_backoff: float = 0.1,
        leaf_fingerprint: bool = True,
    ):
        self.directory = os.path.abspath(directory)
        self.interval = interval
        self.keep_last_n = keep_last_n
        self.use_async = use_async
        self.verify = verify
        self.save_retries = save_retries
        self.save_backoff = save_backoff
        # per-leaf crc32 fingerprints enable restore-time deep verification
        # but cost a synchronous full-state device->host copy per save; the
        # manifest's per-file digests (computed at finalize, off the saved
        # bytes) still catch disk corruption with this off
        self.leaf_fingerprint = leaf_fingerprint
        self._requested = False
        self._saved_for_termination = False
        self._prev_handlers = {}
        self._consensus = None  # lazily-built (sharding, jitted max) pair
        self._writer: Optional[AsyncCheckpointWriter] = None
        # (step, fingerprint) of an async save whose manifest is not yet
        # committed — finalized before the next save / restore / close,
        # and IMMEDIATELY for a termination save (durability claim)
        self._pending: Optional[Tuple[int, Optional[dict]]] = None
        if install_handlers:
            for sig in signals:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)

    # -- checkpoint IO -----------------------------------------------------

    def _integrity(self):
        # lazy: apex_tpu.resilience imports this module's package
        from apex_tpu.resilience import integrity

        return integrity

    def finalize(self) -> None:
        """Block until every issued save is durable AND committed.

        ``AsyncCheckpointWriter.wait()``-style finalization plus the
        integrity manifest (the commit marker) and retention sweep. A
        save is only as durable as this call — ``step()`` performs it
        before reporting a termination save, and interval saves are
        finalized before the next save is issued (one step of overlap).
        """
        if self._pending is None:
            return
        step, fingerprint = self._pending
        # goodput span: host wall time BLOCKED on checkpoint durability
        # (the wait + manifest commit + retention sweep) — the piece of
        # ckpt_save badput the async overlap did NOT hide
        with _goodput_span("ckpt_save", step=step):
            self._writer.wait()
            if jax.process_index() == 0:
                integrity = self._integrity()
                # retried, and _pending is only cleared on success: a
                # transient manifest-write failure is re-attempted at the
                # next finalize point instead of silently losing the
                # commit marker
                integrity.save_with_retry(
                    lambda: integrity.write_manifest(
                        os.path.join(self.directory, f"step_{step}"),
                        fingerprint=fingerprint,
                    ),
                    retries=self.save_retries, backoff=self.save_backoff,
                )
                if self.keep_last_n is not None:
                    integrity.apply_retention(self.directory,
                                              self.keep_last_n)
        self._pending = None

    def _save(self, step: int, state: Any, durable: bool) -> None:
        integrity = self._integrity()
        if not self.use_async:
            with _goodput_span("ckpt_save", step=step):
                integrity.save_checkpoint_verified(
                    self.directory, step, state,
                    retries=self.save_retries, backoff=self.save_backoff,
                    keep_last_n=(self.keep_last_n
                                 if jax.process_index() == 0 else None),
                )
            return
        self.finalize()  # previous pending save first (ordering + bounded lag)
        if self._writer is None:
            self._writer = AsyncCheckpointWriter()
        # goodput span: the synchronous slice of an async save — the
        # fingerprint's device->host copy and the write ISSUANCE (the
        # background write itself overlaps training and is accounted by
        # finalize()'s span when it blocks)
        with _goodput_span("ckpt_save", step=step):
            # fingerprint NOW: the caller may donate/mutate these buffers
            # the moment step() returns, and the manifest commits later
            fingerprint = (
                integrity.tree_fingerprint(state)
                if self.leaf_fingerprint else None
            )
            # the retry covers save ISSUANCE (snapshot-to-host + handoff);
            # an error in the background write itself surfaces un-retried
            # at the next finalize()'s wait() — by then the source buffers
            # may be donated, so there is nothing left to re-save from
            integrity.save_with_retry(
                lambda: self._writer.save(self.directory, step, state),
                retries=self.save_retries, backoff=self.save_backoff,
            )
        self._pending = (step, fingerprint)
        if durable:
            self.finalize()

    # -- signal plumbing ---------------------------------------------------

    def _on_signal(self, signum, frame):
        # flag only: checkpoint IO from inside a signal handler could fire
        # mid-XLA-dispatch; the training loop polls at a safe boundary
        self._requested = True

    def close(self):
        """Finalize pending saves and restore previous signal handlers."""
        self.finalize()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for sig, h in self._prev_handlers.items():
            _signal.signal(sig, h)
        self._prev_handlers = {}

    def request_resume(self):
        """Programmatic preemption request (ref ADLR ``request_resume``)."""
        self._requested = True

    # -- consensus ---------------------------------------------------------

    def termination_requested(self) -> bool:
        """True once ANY host has received a termination signal.

        Multi-host: each host contributes its local flag through a global
        array spanning all processes; one jitted max reduces it. All hosts
        reach the same answer for the same poll, so they checkpoint the
        same step. (Mirrors the reference polling contract,
        pipeline_parallel/utils.get_autoresume — but distributed-safe.)
        """
        if jax.device_count() == 1:
            return self._requested
        # the collective path runs on ANY multi-device mesh so the CPU-mesh
        # tests exercise the code multi-host actually uses (on one process
        # it reduces identical flags; the cost is one scalar all-reduce).
        # The mesh/sharding/jitted reduction are built ONCE and reused —
        # a fresh jax.jit per poll would re-trace and re-dispatch every
        # step, dwarfing the advertised one-scalar-all-reduce cost.
        if self._consensus is None:
            mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("hosts",))
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("hosts")
            )
            reduce = jax.jit(jnp.max, out_shardings=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()))
            self._consensus = (sharding, reduce)
        sharding, reduce = self._consensus
        local = np.asarray([np.float32(self._requested)])
        # every device in this process carries the process-local flag
        per_dev = [
            jax.device_put(local, d) for d in jax.local_devices()
        ]
        global_flags = jax.make_array_from_single_device_arrays(
            (jax.device_count(),), sharding, per_dev
        )
        anyone = reduce(global_flags)
        return bool(np.asarray(anyone)[()] > 0)

    # -- loop API ----------------------------------------------------------

    def step(self, step: int, state: Any) -> bool:
        """Call after each training step with the POST-step state.

        Saves on the periodic interval and on termination request; returns
        True when the caller should exit (a termination checkpoint was
        written).
        """
        terminating = self.termination_requested()
        if terminating and not self._saved_for_termination:
            # durable=True: wait for the write AND commit the manifest
            # BEFORE telling the caller it may exit — an exit on an
            # un-finalized async save is exactly the torn checkpoint this
            # machinery exists to prevent
            self._save(step, state, durable=True)
            self._saved_for_termination = True
            return True
        if terminating:
            return True
        if self.interval and step % self.interval == 0:
            self._save(step, state, durable=False)
        return False

    def restore(self, init_state: Any) -> Tuple[int, Any]:
        """(step, state): newest RESTORABLE checkpoint, else (0, init).

        ``init_state`` also serves as the restore target so dtypes and
        shardings round-trip exactly (see utils/checkpoint.py).

        With ``verify=True`` (default) restoration walks step dirs
        newest-first, checks each integrity manifest, and falls back past
        torn / bit-flipped / uncommitted checkpoints to the newest step
        that verifies (pre-manifest legacy checkpoints are accepted, as
        their corruption is undetectable). ``verify=False`` restores the
        raw latest step and lets corruption crash the run.
        """
        self.finalize()
        # goodput span: restart recovery cost (badput phase ckpt_restore)
        with _goodput_span("ckpt_restore"):
            if not self.verify:
                step = latest_step(self.directory)
                if step is None:
                    return 0, init_state
                return step, load_checkpoint(
                    self.directory, step, target=init_state
                )
            try:
                return self._integrity().load_checkpoint_verified(
                    self.directory, target=init_state, allow_unverified=True
                )
            except FileNotFoundError:
                return 0, init_state
