"""ctypes loader + numpy fallback for the C++ host runtime (csrc/).

Reference parity: the import layer for the reference's native extensions
(apex imports amp_C/apex_C and degrades gracefully when extensions were
not built — README.md:141-170). Same contract here: ``available()``
reports whether the shared library loaded; every wrapper silently falls
back to a numpy implementation with identical semantics, so the framework
never hard-requires a compiler at runtime.

The library is compiled on demand with g++ (baked into the image) into
``csrc/build/`` and cached; pybind11 is unavailable so the ABI is plain C
consumed via ctypes.
"""

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "csrc", "apex_tpu_C.cpp")
_BUILD_DIR = os.path.join(_ROOT, "csrc", "build")
_SO = os.path.join(_BUILD_DIR, "libapex_tpu_C.so")


def _installed_ext() -> Optional[str]:
    """A wheel/editable install may have built the extension as
    ``apex_tpu/_C.*.so`` (setup.py, optional) — prefer it over an on-demand
    compile, which needs the repo-layout ``csrc/`` next to the package."""
    import glob

    hits = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "_C*.so")))
    return hits[0] if hits else None


def _compile() -> Optional[str]:
    if not os.path.exists(_SRC):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # compile to a per-pid temp and rename atomically: an interrupted or
    # concurrent build must never leave a half-written .so that the mtime
    # cache then trusts forever
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return _SO


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        so = _installed_ext() or _compile()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        i64 = ctypes.c_int64
        u64 = ctypes.c_uint64
        p = ctypes.POINTER
        # version gate FIRST: a stale .so from an older ABI may lack the
        # newer symbols, and a ctypes attribute lookup on a missing symbol
        # raises — the numpy fallback must win instead
        try:
            lib.apex_tpu_native_abi_version.restype = i64
            if lib.apex_tpu_native_abi_version() != 2:
                return None
        except AttributeError:
            return None
        lib.gather_rows_i32.argtypes = [
            p(ctypes.c_int32), p(i64), i64, i64, p(ctypes.c_int32)
        ]
        lib.gather_rows_u16.argtypes = [
            p(ctypes.c_uint16), p(i64), i64, i64, p(ctypes.c_uint16)
        ]
        lib.gather_rows_i32_mt.argtypes = [
            p(ctypes.c_int32), p(i64), i64, i64, p(ctypes.c_int32), i64
        ]
        lib.gather_rows_u16_mt.argtypes = [
            p(ctypes.c_uint16), p(i64), i64, i64, p(ctypes.c_uint16), i64
        ]
        lib.flatten_f32.argtypes = [
            p(p(ctypes.c_float)), p(i64), i64, p(ctypes.c_float)
        ]
        lib.unflatten_f32.argtypes = [
            p(ctypes.c_float), p(i64), i64, p(p(ctypes.c_float))
        ]
        lib.permutation_i64.argtypes = [i64, u64, p(i64)]
        lib.build_lm_sample_offsets.argtypes = [i64, i64, p(i64), i64]
        lib.build_lm_sample_offsets.restype = i64
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


# staging batches past ~8 MB get striped over threads (host DRAM bandwidth
# spans cores); below that the spawn cost exceeds the copy
_MT_BYTES_THRESHOLD = 8 << 20
_MT_THREADS = min(8, os.cpu_count() or 1)


def gather_rows(data: np.ndarray, offsets: np.ndarray, row_len: int) -> np.ndarray:
    """out[i] = data[offsets[i] : offsets[i]+row_len]; data 1-D int32/uint16.

    The data-loader hot path: one native memcpy per sample out of the
    token memmap (threaded across cores for large batches)."""
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = offsets.shape[0]
    if np.any(offsets < 0) or np.any(offsets + row_len > data.shape[0]):
        raise IndexError("gather_rows: offsets out of bounds")
    lib = _load()
    if lib is None or data.dtype not in (np.int32, np.uint16):
        return np.stack([data[o : o + row_len] for o in offsets]) if n else (
            np.empty((0, row_len), data.dtype)
        )
    data = np.ascontiguousarray(data)
    out = np.empty((n, row_len), data.dtype)
    threads = (
        _MT_THREADS if out.nbytes >= _MT_BYTES_THRESHOLD and _MT_THREADS > 1
        else 1
    )
    if data.dtype == np.int32:
        lib.gather_rows_i32_mt(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            _i64ptr(offsets), n, row_len,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), threads,
        )
    else:
        lib.gather_rows_u16_mt(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            _i64ptr(offsets), n, row_len,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), threads,
        )
    return out


def flatten(buffers: List[np.ndarray]) -> np.ndarray:
    """apex_C.flatten analogue over host fp32 buffers."""
    bufs = [np.ascontiguousarray(b, np.float32) for b in buffers]
    sizes = np.asarray([b.size for b in bufs], np.int64)
    total = int(sizes.sum())
    lib = _load()
    if lib is None:
        return (
            np.concatenate([b.ravel() for b in bufs])
            if bufs
            else np.empty((0,), np.float32)
        )
    out = np.empty((total,), np.float32)
    ptrs = (ctypes.POINTER(ctypes.c_float) * len(bufs))(
        *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for b in bufs]
    )
    lib.flatten_f32(ptrs, _i64ptr(sizes), len(bufs), out.ctypes.data_as(
        ctypes.POINTER(ctypes.c_float)
    ))
    return out


def unflatten(flat: np.ndarray, shapes: List[tuple]) -> List[np.ndarray]:
    """apex_C.unflatten analogue."""
    flat = np.ascontiguousarray(flat, np.float32)
    sizes = np.asarray([int(np.prod(s)) if s else 1 for s in shapes], np.int64)
    if int(sizes.sum()) > flat.size:
        raise ValueError("unflatten: shapes exceed flat buffer")
    lib = _load()
    outs = [np.empty(s, np.float32) for s in shapes]
    if lib is None:
        off = 0
        for o, n in zip(outs, sizes):
            o[...] = flat[off : off + n].reshape(o.shape)
            off += int(n)
        return outs
    ptrs = (ctypes.POINTER(ctypes.c_float) * len(outs))(
        *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for o in outs]
    )
    lib.unflatten_f32(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _i64ptr(sizes), len(outs), ptrs,
    )
    return outs


def _splitmix64(state: int) -> tuple:
    state = (state + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return state, z ^ (z >> 31)


def permutation(n: int, seed: int) -> np.ndarray:
    """Deterministic Fisher-Yates shuffle (epoch shuffles for
    billion-sample datasets). The fallback runs the SAME splitmix64
    algorithm in Python, so the shuffle — and therefore the data order of
    a resumed run — is identical whether or not the native library loaded
    (slower, but bit-equal)."""
    lib = _load()
    if lib is None:
        out = np.arange(n, dtype=np.int64)
        state = (seed ^ 0xD6E8FEB86659FD93) & 0xFFFFFFFFFFFFFFFF
        for i in range(n - 1, 0, -1):
            state, r = _splitmix64(state)
            j = r % (i + 1)
            out[i], out[j] = out[j], out[i]
        return out
    out = np.empty((n,), np.int64)
    lib.permutation_i64(n, seed, _i64ptr(out))
    return out


def lm_sample_offsets(n_tokens: int, seq_len: int) -> np.ndarray:
    """Start offsets of fixed-length LM samples over a token stream."""
    max_out = max((n_tokens - 1) // seq_len, 0)
    lib = _load()
    if lib is None:
        return (np.arange(max_out, dtype=np.int64) * seq_len)
    out = np.empty((max_out,), np.int64)
    n = lib.build_lm_sample_offsets(n_tokens, seq_len, _i64ptr(out), max_out)
    return out[:n]
