"""Fused NovoGrad.

Reference parity: apex.optimizers.FusedNovoGrad (optimizers/fused_novograd.py)
backed by amp_C.multi_tensor_novograd — Adam with a *layer-wise* (per-tensor
scalar) second moment: v_t = beta2*v + (1-beta2)*||g||^2 (norm_type=2),
m_t = beta1*m + (1-beta1)*(g/(sqrt(v_t)+eps) + wd*p), p -= lr*m_t.
``init_zero`` selects v_0 = 0 vs v_0 = ||g_1||^2 (reference's two init modes).
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax


class FusedNovoGradState(NamedTuple):
    step: jax.Array
    exp_avg: Any  # per-parameter first moment
    exp_avg_sq: Any  # per-tensor scalar second moment


def fused_novograd(
    lr: float = 1e-3,
    betas: Tuple[float, float] = (0.95, 0.98),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    init_zero: bool = False,
    norm_type: int = 2,
    bias_correction: bool = True,
) -> optax.GradientTransformation:
    if norm_type != 2:
        raise ValueError("only norm_type=2 is supported (matches reference default)")
    beta1, beta2 = betas

    def init_fn(params):
        m = jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        # -1 sentinel -> "uninitialized", replaced by ||g||^2 on first step
        # unless init_zero (ref: fused_novograd.py v init modes)
        v = jax.tree_util.tree_map(
            lambda x: jnp.zeros((), jnp.float32) if init_zero else -jnp.ones((), jnp.float32),
            params,
        )
        return FusedNovoGradState(step=jnp.zeros((), jnp.int32), exp_avg=m, exp_avg_sq=v)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if bias_correction else jnp.asarray(1.0)
        bc2 = 1.0 - beta2**stepf if bias_correction else jnp.asarray(1.0)
        grad_coeff = (1.0 - beta1) if grad_averaging else 1.0

        def _v(g, v):
            gn2 = jnp.sum(jnp.square(g.astype(jnp.float32)))
            v_boot = jnp.where(v < 0, gn2, v)  # first-step bootstrap
            return jnp.where(v < 0, v_boot, beta2 * v + (1.0 - beta2) * gn2)

        v = jax.tree_util.tree_map(_v, grads, state.exp_avg_sq)

        def _m(g, p, m, v):
            gf = g.astype(jnp.float32)
            denom = jnp.sqrt(v / bc2) + eps
            gscaled = gf / denom
            if weight_decay != 0.0:
                gscaled = gscaled + weight_decay * p.astype(jnp.float32)
            return beta1 * m + grad_coeff * gscaled

        m = jax.tree_util.tree_map(_m, grads, params, state.exp_avg, v)
        updates = jax.tree_util.tree_map(
            lambda p, m: (-lr * m / bc1).astype(p.dtype), params, m
        )
        return updates, FusedNovoGradState(step=step, exp_avg=m, exp_avg_sq=v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedNovoGrad:
    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.95, 0.98),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        grad_averaging: bool = True,
        norm_type: int = 2,
        init_zero: bool = False,
        set_grad_none: bool = True,
        **_unused,
    ):
        del set_grad_none
        return fused_novograd(
            lr=lr,
            betas=betas,
            eps=eps,
            weight_decay=weight_decay,
            grad_averaging=grad_averaging,
            init_zero=init_zero,
            norm_type=norm_type,
            bias_correction=bias_correction,
        )
