"""Fused LAMB.

Reference parity: apex.optimizers.FusedLAMB (optimizers/fused_lamb.py) —
two multi_tensor_l2norm passes (global grad norm + per-layer norms) followed
by multi_tensor_lamb: Adam-style moments, global grad-norm clipping, and the
per-tensor trust ratio ||p|| / ||update||. Also covers
FusedMixedPrecisionLamb (fused_mixed_precision_lamb.py) — the mixed
model/optim dtype handling lives in amp.AmpOptimizer, the math here is
identical and all hyperparameters are device-resident under jit.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops.multi_tensor import multi_tensor_l2norm
from apex_tpu.utils.pytree import tree_map_multi


class FusedLAMBState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


def fused_lamb(
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    max_grad_norm: float = 1.0,
    adam_w_mode: bool = True,
    use_nvlamb: bool = False,
) -> optax.GradientTransformation:
    beta1, beta2 = betas

    def init_fn(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), t
        )
        return FusedLAMBState(
            step=jnp.zeros((), jnp.int32), exp_avg=zeros(params), exp_avg_sq=zeros(params)
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if bias_correction else jnp.asarray(1.0)
        bc2 = 1.0 - beta2**stepf if bias_correction else jnp.asarray(1.0)

        # stage 1: global grad norm -> clip coefficient (ref: fused_lamb.py
        # step computes multi_tensor_l2norm over all grads, then passes
        # global_grad_norm into multi_tensor_lamb which divides grads)
        global_norm = multi_tensor_l2norm(grads)
        clip = jnp.where(
            (max_grad_norm > 0) & (global_norm > max_grad_norm),
            global_norm / max_grad_norm,
            1.0,
        )

        def _moments(g, m, v):
            gf = g.astype(jnp.float32) / clip
            m_new = beta1 * m + (1.0 - beta1) * gf
            v_new = beta2 * v + (1.0 - beta2) * gf * gf
            return m_new, v_new

        m, v = tree_map_multi(_moments, 2, grads, state.exp_avg, state.exp_avg_sq)

        def _update(p, m, v):
            pf = p.astype(jnp.float32)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * pf
            # per-tensor trust ratio (stage 2 of multi_tensor_lamb)
            w_norm = jnp.sqrt(jnp.sum(pf * pf))
            u_norm = jnp.sqrt(jnp.sum(u * u))
            if use_nvlamb:
                ratio = jnp.where(u_norm > 0, w_norm / u_norm, 1.0)
            else:
                # standard LAMB: ratio only when both norms nonzero
                ratio = jnp.where(
                    (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0
                )
            return (-lr * ratio * u).astype(p.dtype)

        # note: decoupled decay is the only mode the reference kernels use;
        # adam_w_mode is accepted for signature parity.
        updates = jax.tree_util.tree_map(_update, params, m, v)
        return updates, FusedLAMBState(step=step, exp_avg=m, exp_avg_sq=v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedLAMB:
    """Class-style wrapper mirroring the reference constructor."""

    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        set_grad_none: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        **_unused,
    ):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        del grad_averaging, set_grad_none
        return fused_lamb(
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            weight_decay=weight_decay,
            max_grad_norm=max_grad_norm,
            adam_w_mode=adam_w_mode,
            use_nvlamb=use_nvlamb,
        )


class FusedMixedPrecisionLamb:
    """Mixed-precision LAMB (ref: fused_mixed_precision_lamb.py).

    The reference keeps fp32 master state over fp16 model params with
    GPU-resident hyperparameters; here that composition is
    amp.AmpOptimizer(fused_lamb(...), O2 policy) — this alias builds the
    underlying transform.
    """

    def __new__(cls, *args, **kwargs):
        return FusedLAMB(*args, **kwargs)
