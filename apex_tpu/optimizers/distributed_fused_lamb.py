"""Distributed (ZeRO) fused LAMB.

Reference parity: apex.contrib.optimizers.DistributedFusedLAMB
(contrib/optimizers/distributed_fused_lamb.py:24 — ~1k lines of sharded
full-pipeline fusion: reduce-scatter grads, sharded Adam moments,
clip-after-allreduce, per-tensor trust ratios, NCCL all-gather of params).

TPU design: same skeleton as distributed_fused_adam (psum_scatter →
local math on the 1/N state shard → all_gather), with the LAMB-specific
twist that trust ratios are PER TENSOR while the state lives in one flat
shard. Per-leaf ||p|| and ||update|| are computed with a segment-sum over
the local shard (each flat position carries its leaf id) followed by one
``psum`` — so the 3k-line fragment bookkeeping of the reference becomes a
static segment-id array. Math matches apex's multi_tensor_lamb exactly
(see fused_lamb.py): global grad-norm clip, Adam moments with bias
correction, decoupled weight decay, trust ratio ||p||/||update||.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.ops.multi_tensor import FlatSpec
from apex_tpu.optimizers.distributed_fused_adam import (
    bucket_grid,
    choose_overlap_buckets,
    zero_gather_updates,
    zero_init_master_shard,
    zero_prefetch_gather,
    zero_scatter_with_ef,
    zero_updates_from_flat,
)


class DistributedFusedLAMBState(NamedTuple):
    step: jax.Array
    master_shard: jax.Array  # fp32 params shard
    exp_avg: jax.Array
    exp_avg_sq: jax.Array
    # compressed-reduce error-feedback residual — same contract as
    # DistributedFusedAdamState.ef_residual (scalar 0 when off)
    ef_residual: jax.Array


def _segment_ids(spec: FlatSpec) -> np.ndarray:
    """Flat position -> leaf index; padding -> num_leaves (host-side,
    static — the TPU replacement for the reference's ParameterFragment
    bookkeeping, distributed_fused_adam.py:370)."""
    ids = np.full((spec.padded_total,), spec.num_leaves, np.int32)
    for i, (off, shape) in enumerate(zip(spec.offsets, spec.shapes)):
        n = int(np.prod(shape)) if shape else 1
        ids[off : off + n] = i
    return ids


def distributed_fused_lamb(
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    axis_name: str = "dp",
    axis_size: int = None,
    average_grads: bool = True,
    compression=None,
    param_gather_buckets: int = None,
) -> optax.GradientTransformation:
    """ZeRO LAMB over the ``axis_name`` mesh axis; use inside shard_map.

    ``compression``: same contract as ``distributed_fused_adam`` — the
    grad reduce-scatter travels block-scaled int8 with error feedback in
    ``state.ef_residual``; the trust-ratio/master math stays fp32.

    ``param_gather_buckets``: the param all-gather prefetch depth, same
    contract as ``distributed_fused_adam`` (None = roofline-derived, 1 =
    whole-shard gather). LAMB's moments/norms/trust ratios need the full
    shard (the segment psums), so only the final per-tensor-scaled
    master write is bucketed — each bucket's gather still overlaps the
    next bucket's scale math and the unflatten fan-out, through the one
    blessed ``zero_prefetch_gather`` pipeline. Bitwise-identical at
    every depth.
    """
    beta1, beta2 = betas
    if axis_size is None:
        from apex_tpu.parallel import parallel_state

        axis_size = parallel_state.get_data_parallel_world_size()
    use_ef = compression is not None and getattr(
        compression, "error_feedback", False
    )

    def init_fn(params):
        master, shard = zero_init_master_shard(params, axis_name, axis_size)
        return DistributedFusedLAMBState(
            step=jnp.zeros((), jnp.int32),
            master_shard=master,
            exp_avg=jnp.zeros((shard,), jnp.float32),
            exp_avg_sq=jnp.zeros((shard,), jnp.float32),
            ef_residual=(
                jnp.zeros((shard * axis_size,), jnp.float32)
                if use_ef else jnp.zeros((), jnp.float32)
            ),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_lamb requires params")
        gshard, spec, new_ef = zero_scatter_with_ef(
            grads, axis_name, axis_size, average_grads, compression,
            state.ef_residual,
        )
        shard = gshard.shape[0]

        # local shard's segment ids (static slice per rank)
        seg_all = jnp.asarray(_segment_ids(spec))
        idx = jax.lax.axis_index(axis_name)
        seg = jax.lax.dynamic_slice(seg_all, (idx * shard,), (shard,))
        nseg = spec.num_leaves + 1  # + padding bucket

        # stage 1: GLOBAL grad norm (clip-after-allreduce, ref
        # distributed_fused_lamb.py _pipeline_step): local shard sum-of-
        # squares through the flat Pallas reduction (the shard is already
        # one flat buffer — the case where flat wins, BENCH.md), then psum
        from apex_tpu.optimizers._fused_kernels import sumsq_flat

        sq = xlax.psum(sumsq_flat(gshard), axis_name)
        global_norm = jnp.sqrt(sq)
        clip = jnp.where(
            (max_grad_norm > 0) & (global_norm > max_grad_norm),
            global_norm / max_grad_norm,
            1.0,
        )
        g = gshard / clip

        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if bias_correction else jnp.asarray(1.0)
        bc2 = 1.0 - beta2**stepf if bias_correction else jnp.asarray(1.0)

        p = state.master_shard
        m = beta1 * state.exp_avg + (1.0 - beta1) * g
        v = beta2 * state.exp_avg_sq + (1.0 - beta2) * g * g
        u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        if weight_decay != 0.0:
            u = u + weight_decay * p

        # per-TENSOR trust ratios across the flat shard: segment sums of
        # squares, combined over dp ranks
        w_norm_sq = xlax.psum(
            jax.ops.segment_sum(p * p, seg, num_segments=nseg), axis_name
        )
        u_norm_sq = xlax.psum(
            jax.ops.segment_sum(u * u, seg, num_segments=nseg), axis_name
        )
        w_norm = jnp.sqrt(w_norm_sq)
        u_norm = jnp.sqrt(u_norm_sq)
        if use_nvlamb:
            ratios = jnp.where(u_norm > 0, w_norm / jnp.maximum(u_norm, 1e-30), 1.0)
        else:
            ratios = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                w_norm / jnp.maximum(u_norm, 1e-30),
                1.0,
            )
        nb = (
            param_gather_buckets if param_gather_buckets is not None
            else choose_overlap_buckets(shard * 4, axis_size)
        )
        if nb > 1:
            bs, pad = bucket_grid(shard, nb)

            def padto(a):
                return jnp.pad(a, (0, pad)) if pad else a

            # padded seg indexes the padding bucket -> ratio row nseg-1,
            # a real (finite) entry; the tail is stripped before storing
            pw, uw = padto(p), padto(u)
            segw = jnp.pad(seg, (0, pad), constant_values=nseg - 1) if pad else seg

            def bucket(b, bsz):
                sl = slice(b * bsz, (b + 1) * bsz)
                return pw[sl] - lr * jnp.take(ratios, segw[sl]) * uw[sl]

            buckets, new_flat = zero_prefetch_gather(
                bucket, nb, shard, axis_name, axis_size
            )
            new_master = jnp.concatenate(buckets)[:shard]
            updates = zero_updates_from_flat(new_flat, params, spec)
        else:
            new_master = p - lr * jnp.take(ratios, seg) * u
            updates = zero_gather_updates(new_master, params, spec, axis_name)
        new_state = DistributedFusedLAMBState(
            step=step, master_shard=new_master, exp_avg=m, exp_avg_sq=v,
            ef_residual=new_ef,
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


class DistributedFusedLAMB:
    """Class-style wrapper mirroring the reference constructor (the NCCL
    tuning surface — dwu_group_size, overlap_reductions, num_blocks… —
    is intentionally absent: XLA owns comm scheduling)."""

    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        axis_name: str = "dp",
        axis_size: int = None,
        average_grads: bool = True,
        compression=None,
        param_gather_buckets: int = None,
        **_unused,
    ):
        return distributed_fused_lamb(
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            weight_decay=weight_decay,
            max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb,
            axis_name=axis_name,
            axis_size=axis_size,
            average_grads=average_grads,
            compression=compression,
            param_gather_buckets=param_gather_buckets,
        )
