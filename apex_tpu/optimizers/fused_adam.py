"""Fused Adam / AdamW.

Reference parity: apex.optimizers.FusedAdam (optimizers/fused_adam.py:4,
step :127) backed by amp_C.multi_tensor_adam (csrc/multi_tensor_adam.cu) —
``adam_w_mode`` selects decoupled weight decay, ``bias_correction`` the
1/(1-beta^t) terms. The CUDA "capturable" mode (GPU-resident lr/step for
CUDA graphs) is inherent here: everything, including the step count, lives
on device inside jit.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax


class FusedAdamState(NamedTuple):
    step: jax.Array
    exp_avg: Any  # first moment, fp32
    exp_avg_sq: Any  # second moment, fp32


def fused_adam(
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    fuse: str = "tree",
) -> optax.GradientTransformation:
    """Optax transform matching amp_C.multi_tensor_adam semantics.

    ``fuse`` selects the update engine:
    - ``"tree"``: per-leaf tree_map math, fused by XLA inside the caller's
      jit. The default: on CPU it measures 1.6x faster than flat (the
      flatten/unflatten round-trip dominates; BENCH.md, bench_optimizers.py);
      the compiled-Mosaic comparison reruns when a TPU backend answers;
    - ``"flat"``: the reference's multi_tensor design — moments live in one
      CHUNK_SIZE-padded fp32 buffer and a single Pallas kernel
      (``_fused_kernels.adam_flat``) updates everything per step.
    """
    beta1, beta2 = betas
    if fuse not in ("tree", "flat"):
        raise ValueError(f"unknown fuse mode {fuse!r}; expected tree|flat")

    def _bias_corrections(stepf):
        if bias_correction:
            return 1.0 - beta1**stepf, 1.0 - beta2**stepf
        one = jnp.asarray(1.0, jnp.float32)
        return one, one

    def init_fn(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), t
        )
        return FusedAdamState(
            step=jnp.zeros((), jnp.int32), exp_avg=zeros(params), exp_avg_sq=zeros(params)
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        bc1, bc2 = _bias_corrections(step.astype(jnp.float32))

        def _g(g, p):
            # master-accumulation contract: bf16/f16 grads enter the Adam
            # math in f32 exactly once, here (precision-auditor allowlist
            # entry "apex_tpu/optimizers/", apex_tpu/analysis/allowlist.py)
            gf = g.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)  # L2 mode (ADAM_MODE_1)
            return gf

        geff = jax.tree_util.tree_map(_g, grads, params)
        m = jax.tree_util.tree_map(
            lambda g, m: beta1 * m + (1.0 - beta1) * g, geff, state.exp_avg
        )
        v = jax.tree_util.tree_map(
            lambda g, v: beta2 * v + (1.0 - beta2) * g * g, geff, state.exp_avg_sq
        )

        def _upd(p, m, v):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p.astype(jnp.float32)  # decoupled (ADAM_MODE_0)
            return (-lr * upd).astype(p.dtype)

        updates = jax.tree_util.tree_map(_upd, params, m, v)
        return updates, FusedAdamState(step=step, exp_avg=m, exp_avg_sq=v)

    def flat_init_fn(params):
        from apex_tpu.ops.multi_tensor import CHUNK_SIZE

        # padded length from shapes alone — no transient fp32 flat copy
        total = sum(
            int(x.size) for x in jax.tree_util.tree_leaves(params)
        )
        padded = max(CHUNK_SIZE, -(-total // CHUNK_SIZE) * CHUNK_SIZE)
        zeros = jnp.zeros((padded,), jnp.float32)
        return FusedAdamState(
            step=jnp.zeros((), jnp.int32), exp_avg=zeros, exp_avg_sq=zeros
        )

    def flat_update_fn(grads, state, params=None):
        from apex_tpu.optimizers._fused_kernels import adam_flat
        from apex_tpu.ops.multi_tensor import flatten_pytree, unflatten_pytree

        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        bc1, bc2 = _bias_corrections(step.astype(jnp.float32))
        g_flat, _ = flatten_pytree(grads, dtype=jnp.float32)
        p_flat, spec = flatten_pytree(params, dtype=jnp.float32)
        upd_flat, m_flat, v_flat = adam_flat(
            g_flat, p_flat, state.exp_avg, state.exp_avg_sq, bc1, bc2,
            lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
        )
        # spec carries params' dtypes, so updates cast back per leaf
        updates = unflatten_pytree(upd_flat, spec)
        return updates, FusedAdamState(
            step=step, exp_avg=m_flat, exp_avg_sq=v_flat
        )

    if fuse == "flat":
        return optax.GradientTransformation(flat_init_fn, flat_update_fn)
    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdam:
    """Class-style wrapper mirroring the reference constructor signature."""

    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        capturable: bool = False,
        master_weights: bool = False,
        **_unused,
    ):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        del capturable, master_weights  # inherent under jit / see amp.AmpOptimizer
        return fused_adam(
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            adam_w_mode=adam_w_mode,
            weight_decay=weight_decay,
        )
