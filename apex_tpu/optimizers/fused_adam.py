"""Fused Adam / AdamW.

Reference parity: apex.optimizers.FusedAdam (optimizers/fused_adam.py:4,
step :127) backed by amp_C.multi_tensor_adam (csrc/multi_tensor_adam.cu) —
``adam_w_mode`` selects decoupled weight decay, ``bias_correction`` the
1/(1-beta^t) terms. The CUDA "capturable" mode (GPU-resident lr/step for
CUDA graphs) is inherent here: everything, including the step count, lives
on device inside jit.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax


class FusedAdamState(NamedTuple):
    step: jax.Array
    exp_avg: Any  # first moment, fp32
    exp_avg_sq: Any  # second moment, fp32


def fused_adam(
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Optax transform matching amp_C.multi_tensor_adam semantics."""
    beta1, beta2 = betas

    def init_fn(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), t
        )
        return FusedAdamState(
            step=jnp.zeros((), jnp.int32), exp_avg=zeros(params), exp_avg_sq=zeros(params)
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - beta1**stepf
            bc2 = 1.0 - beta2**stepf
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def _g(g, p):
            gf = g.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)  # L2 mode (ADAM_MODE_1)
            return gf

        geff = jax.tree_util.tree_map(_g, grads, params)
        m = jax.tree_util.tree_map(
            lambda g, m: beta1 * m + (1.0 - beta1) * g, geff, state.exp_avg
        )
        v = jax.tree_util.tree_map(
            lambda g, v: beta2 * v + (1.0 - beta2) * g * g, geff, state.exp_avg_sq
        )

        def _upd(p, m, v):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p.astype(jnp.float32)  # decoupled (ADAM_MODE_0)
            return (-lr * upd).astype(p.dtype)

        updates = jax.tree_util.tree_map(_upd, params, m, v)
        return updates, FusedAdamState(step=step, exp_avg=m, exp_avg_sq=v)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdam:
    """Class-style wrapper mirroring the reference constructor signature."""

    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        capturable: bool = False,
        master_weights: bool = False,
        **_unused,
    ):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        del capturable, master_weights  # inherent under jit / see amp.AmpOptimizer
        return fused_adam(
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            adam_w_mode=adam_w_mode,
            weight_decay=weight_decay,
        )
