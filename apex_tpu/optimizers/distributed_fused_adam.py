"""Distributed (ZeRO-2) fused Adam.

Reference parity: apex.contrib.optimizers.DistributedFusedAdam
(contrib/optimizers/distributed_fused_adam.py:266 — 3k lines of bucket
fragments, reduce-scatter hooks, stream pipelining) and
DistributedFusedLAMB (distributed_fused_lamb.py:24).

TPU design (SURVEY.md §7 stage 5): the whole machine collapses to three
collectives over the 'dp' mesh axis inside shard_map:

    grads  --flatten-->  psum_scatter  --> local Adam on the state shard
    new master shard --all_gather--> flat params --> unflatten

Optimizer state (m, v, fp32 master shard) is 1/N per device — ZeRO-2.
Overlap of the reduce-scatter with backward is XLA's latency-hiding
scheduler's job (the reference does it manually with backward hooks and
side streams); correctness here needs none of that machinery.

Must be used inside shard_map over ``axis_name``. ``average_grads=True``
(default) means the incoming grads still need dividing by N for the DP
mean: per-rank partials under ``check_vma=False``, or the cross-rank SUMS
that checked shard_map's grad-transpose produces for a per-rank local
loss. Pass ``average_grads=False`` when the grads are already final —
e.g. you differentiated a pmean'd GLOBAL loss (see
``zero_scatter_grads``).
"""

import dataclasses
import math
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.ops.multi_tensor import FlatSpec, flatten_pytree, unflatten_pytree


class DistributedFusedAdamState(NamedTuple):
    step: jax.Array
    # fp32 params shard (padded_total / N,) — or, with
    # ``store_param_remainders=True``, the uint16 LOW bits of the fp32
    # master whose high bits live in the bf16 params themselves
    master_shard: jax.Array
    exp_avg: jax.Array  # (padded_total / N,)
    exp_avg_sq: jax.Array  # (padded_total / N,)
    # error-feedback residual of the compressed grad reduce-scatter
    # (parallel/compress.py): fp32 (padded_total,) PER RANK — each rank
    # keeps its OWN phase-1 quantization error over its contribution to
    # every chunk, so the leaf crosses the shard_map boundary dp-SHARDED
    # (zero_state_specs: P(axis); global shape (dp * padded_total,)).
    # A scalar 0 when compression (or its error feedback) is off, so
    # the state structure — and therefore checkpoints and
    # zero_state_specs — stays uniform. The manifest
    # marks it advisory (``ef`` in the topology block): the elastic
    # restore regroups it like the flat buffers where the padding-only
    # length change allows, else resets it to zero with a warning.
    ef_residual: jax.Array


def zero_state_specs(
    axis_name: str = "dp", compression=None
) -> "DistributedFusedAdamState":
    """PartitionSpecs for moving DistributedFusedAdamState across the
    shard_map boundary (out_specs on save, in_specs on restore): the
    per-rank shards concatenate into ONE global flat array per field, which
    is exactly the layout ``utils.checkpoint`` saves/restores (orbax handles
    the sharded global arrays natively).  Ref: the reference's sharded
    state_dict machinery, contrib/optimizers/distributed_fused_adam.py
    (~:2158 onward) — here the single-controller global-array view replaces
    all of it.

    Pass the optimizer's ``compression`` config when its error feedback is
    on: each rank then carries its OWN (padded_total,) residual, so the
    leaf crosses the boundary dp-sharded — global shape
    ``(dp * padded_total,)`` — instead of the scalar placeholder's
    replicated ``P()``."""
    from jax.sharding import PartitionSpec as P

    ef_on = compression is not None and getattr(
        compression, "error_feedback", False
    )
    return DistributedFusedAdamState(
        step=P(),
        master_shard=P(axis_name),
        exp_avg=P(axis_name),
        exp_avg_sq=P(axis_name),
        ef_residual=P(axis_name) if ef_on else P(),
    )


def _master_from_remainder(param_shard_bf16, rem_u16):
    """Exact fp32 master = (bf16 param bits << 16) | remainder bits.
    Ref: store_param_remainders, contrib DistributedFusedAdam — the bf16
    param IS the high half of the fp32 master, so only 16 remainder bits
    per element need storing (half the master-shard memory)."""
    hi = jax.lax.bitcast_convert_type(param_shard_bf16, jnp.uint16).astype(jnp.uint32)
    lo = rem_u16.astype(jnp.uint32)
    return jax.lax.bitcast_convert_type((hi << 16) | lo, jnp.float32)


def _split_master(master_f32):
    """fp32 master -> (bf16 high half [the param], uint16 remainder)."""
    bits = jax.lax.bitcast_convert_type(master_f32, jnp.uint32)
    hi = jax.lax.bitcast_convert_type((bits >> 16).astype(jnp.uint16), jnp.bfloat16)
    lo = (bits & jnp.uint32(0xFFFF)).astype(jnp.uint16)
    return hi, lo


def _padded_flatten(tree, axis_size):
    flat, spec = flatten_pytree(tree, dtype=jnp.float32)
    pad_to = ((flat.shape[0] + axis_size - 1) // axis_size) * axis_size
    if pad_to != flat.shape[0]:
        flat = jnp.pad(flat, (0, pad_to - flat.shape[0]))
        spec = dataclasses.replace(spec, padded_total=pad_to)
    return flat, spec


def zero_init_master_shard(params, axis_name: str, axis_size: int):
    """Shared ZeRO init: flatten+pad params, keep this rank's fp32 shard.
    Returns (master_shard, shard_len)."""
    flat, _ = _padded_flatten(params, axis_size)
    shard = flat.shape[0] // axis_size
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice(flat, (idx * shard,), (shard,)), shard


def zero_scatter_grads(grads, axis_name: str, axis_size: int, average: bool,
                       compression=None, ef=None):
    """Shared ZeRO grad reduce-scatter. Returns (grad_shard, spec) — or,
    with ``compression`` set, (grad_shard, spec, new_ef).

    ``compression`` (a ``parallel.compress.CompressionConfig``) swaps the
    fused ``psum_scatter`` for the quantized reduce-scatter of
    ``parallel/compress.py``: the flat grad buffer travels int8 (+
    per-block fp32 scales) while the returned shard — and the master
    update consuming it — stays fp32. ``ef`` is the error-feedback
    residual (fp32, the flat buffer's padded length; keep it in the
    optimizer state): the residual is added before quantizing and the
    new residual is returned third. In the already-reduced regime the
    summed leaves move no bytes (compression and EF pass through them
    untouched) and any per-rank STRAGGLER leaves take a stateless
    quantized psum — mixed trees never silently fall back to full-fat
    fp32 on the wire.

    Two regimes, dispatched on the varying-manual-axes type (the same
    dispatch as ``parallel.ddp.all_reduce_gradients``):

    - grads VARYING over ``axis_name`` (true per-rank partials): the
      classic ``psum_scatter``; ``average`` divides by N for the mean.
    - grads UNVARYING under live vma tracking (jax's checked shard_map:
      ``jax.grad`` w.r.t. dp-replicated params already psums in the
      transpose, so each leaf is the cross-rank SUM): the collective
      collapses to slicing the local shard; ``average`` still divides by
      N (sum -> mean). A ``psum_scatter`` here would hand every rank
      N x the sum. Under ``check_vma=False`` everything reads unvarying
      while grads stay per-rank local, so detection defers to
      ``parallel.ddp.grads_already_reduced``'s probe.

    The dispatch is PER LEAF, before flattening: jax auto-pvarys the
    unvarying operands of a concatenate that mixes vma types, so a tree
    with one varying leaf would otherwise read fully varying and the
    already-summed leaves would be psummed AGAIN. Varying leaves are
    psummed individually first; after that every leaf is a cross-rank
    sum and the flat buffer slices locally. (The all-varying tree skips
    that and keeps the single fused ``psum_scatter`` — reduce-scatter
    moves 1/N the bytes of a psum.)

    ``average`` semantics by regime:

    - ``check_vma=False`` (vma tracking off): pass ``average=True``
      ALWAYS — it is correct both for per-rank partials (psum/N = mean)
      and for replicated already-averaged grads from a pmean'd loss
      (psum_scatter sums the N identical replicas, /N restores the
      mean). ``average=False`` on replicated means yields N x the mean.
    - checked shard_map (default): ``average=True`` for the un-normalized
      SUMS that grads of a per-rank LOCAL mean loss arrive as (the usual
      case); ``average=False`` if you differentiated a pmean'd GLOBAL
      loss (SyncBatchNorm pattern) — those grads are already the mean
      and slice through unchanged.
    """
    from apex_tpu.parallel.ddp import grads_already_reduced, vma_tracking_live

    leaves = jax.tree_util.tree_leaves(grads)
    tracking = vma_tracking_live(axis_name)
    reduced = [grads_already_reduced(l, axis_name, tracking) for l in leaves]
    new_ef = ef
    if not any(reduced):
        # classic regime: one fused reduce-scatter over the flat buffer
        gflat, spec = _padded_flatten(grads, axis_size)
        if compression is not None:
            from apex_tpu.parallel import compress as _compress

            acc = gflat if ef is None else gflat + ef
            gshard, sent = _compress.quantized_psum_scatter(
                acc, axis_name, compression, return_transmitted=True
            )
            if ef is not None:
                new_ef = _compress.ef_update(acc, sent)
        else:
            gshard = xlax.psum_scatter(gflat, axis_name, tiled=True)
    else:
        # normalize every leaf to "cross-rank sum" BEFORE flattening
        # (psum the stragglers), then the collective is a local slice.
        # With compression on, the straggler psums — the ONLY wire
        # traffic this regime moves — go quantized too (stateless: the
        # flat EF residual's positions don't map onto per-leaf psums, so
        # these bounded one-shot errors are not error-fed; the
        # already-summed leaves move no bytes either way)
        if compression is not None:
            from apex_tpu.parallel import compress as _compress

            def _straggler(l):
                return _compress.quantized_psum(l, axis_name, compression)
        else:
            def _straggler(l):
                return xlax.psum(l, axis_name)

        flat_leaves = [
            l if r else _straggler(l) for l, r in zip(leaves, reduced)
        ]
        grads = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(grads), flat_leaves
        )
        gflat, spec = _padded_flatten(grads, axis_size)
        shard = gflat.shape[0] // axis_size
        idx = jax.lax.axis_index(axis_name)
        gshard = jax.lax.dynamic_slice(gflat, (idx * shard,), (shard,))
    if average:
        gshard = gshard / axis_size
    if compression is not None:
        return gshard, spec, new_ef
    return gshard, spec


def zero_scatter_with_ef(grads, axis_name: str, axis_size: int,
                         average: bool, compression, ef_residual):
    """The ZeRO optimizers' shared scatter dispatch: always returns
    ``(gshard, spec, new_ef)``, with ``new_ef`` falling back to the
    caller's current residual when compression (or its error feedback,
    or the wire itself in the already-reduced regime) leaves it
    untouched — so adam and lamb cannot drift on the arity handling."""
    if compression is None:
        gshard, spec = zero_scatter_grads(
            grads, axis_name, axis_size, average
        )
        return gshard, spec, ef_residual
    use_ef = getattr(compression, "error_feedback", False)
    gshard, spec, new_ef = zero_scatter_grads(
        grads, axis_name, axis_size, average,
        compression=compression,
        ef=ef_residual if use_ef else None,
    )
    return gshard, spec, ef_residual if new_ef is None else new_ef


def zero_regroup_flat(flat, target_len: int):
    """Host-side regroup of a saved padded ZeRO flat buffer to a new dp
    size: the global flat buffer is the true param/moment vector of
    length T zero-padded to a multiple of the dp size
    (``_padded_flatten``), so changing dp only changes the PADDING —
    truncate (dropping zeros) or zero-extend to ``target_len``.

    Refuses (``ValueError``) when truncation would drop a NONZERO value:
    that is optimizer state, not padding, and means the buffer is not a
    padded flat shard of the claimed layout. The elastic restore
    (``resilience.elastic.reshard``) is the caller; it wraps the refusal
    in its reasoned ``ElasticRestoreError``.
    """
    import numpy as np

    arr = np.asarray(flat)
    if arr.ndim != 1:
        raise ValueError(f"ZeRO flat buffer must be 1-D, got {arr.shape}")
    n = arr.shape[0]
    target_len = int(target_len)
    if target_len == n:
        return arr
    if target_len < n:
        tail = arr[target_len:]
        if np.any(tail != 0):
            raise ValueError(
                f"regroup {n} -> {target_len} would truncate "
                f"{int(np.count_nonzero(tail))} nonzero value(s) — the "
                f"dropped region is state, not dp padding; the target "
                f"layout is too small for the saved flat buffer"
            )
        return arr[:target_len]
    return np.concatenate([arr, np.zeros(target_len - n, dtype=arr.dtype)])


def zero_updates_from_flat(new_flat, params, spec):
    """The ONE home of the ZeRO update-dtype rule: unflatten a gathered
    flat buffer and return optax-style updates (new - old, differenced
    in f32) in the params' dtypes — shared by the whole-shard and
    prefetched gather paths of both ZeRO optimizers, so the rule cannot
    drift between them."""
    new_params = unflatten_pytree(
        new_flat, spec_like(spec, params), cast_back=True
    )
    return jax.tree_util.tree_map(
        lambda n, o: (
            n.astype(jnp.float32) - o.astype(jnp.float32)
        ).astype(o.dtype),
        new_params,
        params,
    )


def zero_gather_updates(new_master, params, spec, axis_name: str):
    """Shared ZeRO epilogue: all-gather the updated master shard and return
    optax-style updates (new - old) in the params' dtypes."""
    new_flat = xlax.all_gather(new_master, axis_name, tiled=True)
    return zero_updates_from_flat(new_flat, params, spec)


# -- double-buffered param all-gather prefetch -------------------------------


def choose_overlap_buckets(
    shard_bytes: int,
    axis_size: int,
    bandwidth: float = None,
    target_bucket_s: float = 5e-4,
    max_buckets: int = 8,
) -> int:
    """Overlap depth for the ZeRO param all-gather, derived from the
    PR-3 ICI roofline model instead of a magic constant.

    The whole-shard gather's predicted per-chip wire time is the ring
    cost ``(n-1) * shard_bytes / bandwidth`` (the ledger's all_gather
    convention). Splitting it into ``k`` buckets lets bucket b's wire
    time hide behind bucket b+1's update compute, but each extra bucket
    pays one collective's fixed launch cost — so the depth is the number
    of buckets at which each bucket's wire time is ~``target_bucket_s``
    (the latency quantum below which per-collective overhead, not wire,
    dominates — ~0.5 ms at ICI scale), clamped to [1, ``max_buckets``].

    A gather already cheaper than one quantum gets depth 1 (nothing
    worth hiding); an unknown bandwidth (no table entry, no
    ``APEX_TPU_ICI_BANDWIDTH``) falls back to plain double-buffering
    (2) rather than inventing a roofline.
    """
    if axis_size <= 1:
        return 1
    if bandwidth is None:
        bandwidth = xlax.ici_bandwidth_per_device()
    if not bandwidth:
        return 2
    gather_s = (axis_size - 1) * shard_bytes / bandwidth
    return max(1, min(max_buckets, math.ceil(gather_s / target_bucket_s)))


def bucket_grid(shard_len: int, num_buckets: int):
    """The ONE bucket-grid rule: ``(bucket_size, pad)`` for splitting a
    shard into equal prefetch buckets. Callers pad their working buffers
    with THIS pad and ``zero_prefetch_gather`` slices with THIS size —
    one formula, so a rounding change cannot silently desynchronize the
    callers' padding from the pipeline's slicing (out-of-range static
    slices clip silently in jax; agreement here is what prevents that)."""
    bs = -(-shard_len // num_buckets)
    return bs, bs * num_buckets - shard_len


def _interleave_gathered(gathered, shard_len: int, axis_size: int):
    """Rebuild the rank-major ZeRO flat buffer from bucket-major
    all-gathers: ``gathered[b]`` is ``concat_r shard_r[bucket b]``, so
    the full flat (``concat_r shard_r``) is a static transpose — exact,
    zero wire traffic. Per-rank bucket padding (``nb * bs >= shard``) is
    stripped from each rank's tail before concatenation."""
    nb = len(gathered)
    bs = gathered[0].shape[0] // axis_size
    stacked = jnp.stack(gathered)  # (nb, n * bs)
    return (
        stacked.reshape(nb, axis_size, bs)
        .transpose(1, 0, 2)
        .reshape(axis_size, nb * bs)[:, :shard_len]
        .reshape(-1)
    )


def zero_prefetch_gather(bucket_fn, num_buckets: int, shard_len: int,
                         axis_name: str, axis_size: int):
    """The ONE home of the bucketed ZeRO param-gather pipeline (the
    ``lint.prefetch-gather`` blessed site — both ZeRO optimizers route
    through here so overlap depth stays roofline-derived in one place).

    ``bucket_fn(b, bs)`` computes bucket ``b``'s updated master values
    (a ``(bs,)`` slice of this rank's padded shard). Each bucket's
    ledgered ``all_gather`` is issued the moment that bucket's update
    math produces it, BEFORE bucket b+1's math — the gathers depend only
    on their own bucket's chain, so XLA's latency-hiding scheduler
    overlaps gather b's wire time with bucket b+1's compute (the
    double-buffered prefetch of the reference's DistributedFusedAdam,
    expressed as dataflow instead of stream juggling). Predicted ledger
    bytes stay exact: nb gathers of bs elements == the padded shard.

    Returns ``(buckets, new_flat)``: the per-bucket master values (for
    the caller's state concat) and the reconstructed full flat buffer.
    """
    bs, _ = bucket_grid(shard_len, num_buckets)
    buckets, gathered = [], []
    for b in range(num_buckets):
        nm_b = bucket_fn(b, bs)
        gathered.append(xlax.all_gather(nm_b, axis_name, tiled=True))
        buckets.append(nm_b)
    return buckets, _interleave_gathered(gathered, shard_len, axis_size)


def distributed_fused_adam(
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    axis_name: str = "dp",
    axis_size: int = None,
    average_grads: bool = True,
    max_grad_norm: float = None,
    store_param_remainders: bool = False,
    compression=None,
    param_gather_buckets: int = None,
) -> optax.GradientTransformation:
    """ZeRO-2 Adam over the ``axis_name`` mesh axis.

    ``compression`` (a ``parallel.compress.CompressionConfig``): the
    grad reduce-scatter travels block-scaled int8 instead of fp32 —
    the fp32 master-shard update itself is untouched. With
    ``compression.error_feedback`` (default) the state carries the
    residual (``ef_residual``, fp32 at the flat buffer's padded
    length) so convergence matches the exact path; overflow still
    reaches found_inf (the poisoned-scale contract,
    parallel/compress.py), and the caller's found_inf consensus psum
    stays exact.

    ``axis_size`` defaults to the initialized parallel_state data-parallel
    size (parallel_state must be initialized, or pass it explicitly).

    ``max_grad_norm``: clip the GLOBAL (all-shards) grad norm before the
    Adam math, computed on the sharded flat buffer — one ``sumsq_flat``
    per rank + one scalar psum, never materializing the full grad (ref:
    clip_grad_norm on the bucketed grads, contrib
    distributed_fused_adam.py ~:2158; torch convention
    ``min(1, max_norm/(norm+1e-6))``).

    ``store_param_remainders``: requires every param leaf to be bfloat16.
    The optimizer state keeps only the uint16 LOW half of each fp32 master
    element — the high half is the bf16 param itself — halving the
    master-shard memory exactly like the reference's
    ``store_param_remainders``.  Updates are returned in fp32 so
    ``optax.apply_updates``'s f32 addition lands the param exactly on the
    master's high half.

    ``param_gather_buckets``: overlap depth of the param all-gather
    prefetch. The update math and the gather run bucket-by-bucket —
    bucket b's ledgered ``all_gather`` is issued while bucket b+1's Adam
    math computes (``zero_prefetch_gather``), hiding the gather's wire
    time behind update compute exactly like the reference's
    double-buffered pipeline.  ``None`` (default) derives the depth from
    the ICI roofline (``choose_overlap_buckets``); ``1`` restores the
    single whole-shard gather. Updates are bitwise-identical at every
    depth (elementwise math on slices + an exact reconstruction
    transpose), so the knob trades only schedule, never numerics.
    """
    beta1, beta2 = betas
    if axis_size is None:
        from apex_tpu.parallel import parallel_state

        axis_size = parallel_state.get_data_parallel_world_size()

    def init_fn(params):
        if store_param_remainders:
            bad = [
                jnp.asarray(l).dtype
                for l in jax.tree_util.tree_leaves(params)
                if jnp.asarray(l).dtype != jnp.bfloat16
            ]
            if bad:
                raise ValueError(
                    "store_param_remainders requires bfloat16 params (the "
                    f"bf16 param is the master's high half); got {bad[0]}"
                )
        master, shard = zero_init_master_shard(params, axis_name, axis_size)
        if store_param_remainders:
            # master == f32(bf16 params) exactly at init -> low bits all 0
            master = jnp.zeros((shard,), jnp.uint16)
        use_ef = compression is not None and getattr(
            compression, "error_feedback", False
        )
        return DistributedFusedAdamState(
            step=jnp.zeros((), jnp.int32),
            master_shard=master,
            exp_avg=jnp.zeros((shard,), jnp.float32),
            exp_avg_sq=jnp.zeros((shard,), jnp.float32),
            ef_residual=(
                jnp.zeros((shard * axis_size,), jnp.float32)
                if use_ef else jnp.zeros((), jnp.float32)
            ),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_adam requires params")
        gshard, spec, new_ef = zero_scatter_with_ef(
            grads, axis_name, axis_size, average_grads, compression,
            state.ef_residual,
        )

        if max_grad_norm is not None:
            from apex_tpu.optimizers._fused_kernels import sumsq_flat

            total = xlax.psum(sumsq_flat(gshard), axis_name)
            clip = jnp.minimum(1.0, max_grad_norm / (jnp.sqrt(total) + 1e-6))
            gshard = gshard * clip

        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if bias_correction else jnp.asarray(1.0)
        bc2 = 1.0 - beta2**stepf if bias_correction else jnp.asarray(1.0)

        if store_param_remainders:
            pflat, _ = flatten_pytree(params, dtype=jnp.bfloat16)
            pad_to = ((pflat.shape[0] + axis_size - 1) // axis_size) * axis_size
            if pad_to != pflat.shape[0]:
                pflat = jnp.pad(pflat, (0, pad_to - pflat.shape[0]))
            shard = pflat.shape[0] // axis_size
            idx = jax.lax.axis_index(axis_name)
            p_hi = jax.lax.dynamic_slice(pflat, (idx * shard,), (shard,))
            p = _master_from_remainder(p_hi, state.master_shard)
        else:
            p = state.master_shard

        def adam_math(p, m, v, g):
            """The elementwise Adam update — shared verbatim by the
            whole-shard and per-bucket paths, so bucketing cannot change
            a single bit of the trajectory."""
            if not adam_w_mode and weight_decay != 0.0:
                g = g + weight_decay * p
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * g * g
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p
            return p - lr * upd, m, v

        shard = p.shape[0]
        gathered_itemsize = 2 if store_param_remainders else 4
        nb = (
            param_gather_buckets if param_gather_buckets is not None
            else choose_overlap_buckets(shard * gathered_itemsize, axis_size)
        )
        if nb > 1:
            # prefetched path: pad every working buffer to the bucket
            # grid (zeros are Adam-inert: eps keeps the pad finite and
            # the tails are stripped before anything is stored)
            bs, pad = bucket_grid(shard, nb)

            def padto(a):
                return jnp.pad(a, (0, pad)) if pad else a

            pw, mw, vw, gw = map(
                padto, (p, state.exp_avg, state.exp_avg_sq, gshard)
            )
            state_buckets = []

            def bucket(b, bsz):
                sl = slice(b * bsz, (b + 1) * bsz)
                nm_b, m_b, v_b = adam_math(pw[sl], mw[sl], vw[sl], gw[sl])
                if store_param_remainders:
                    hi_b, lo_b = _split_master(nm_b)
                    state_buckets.append((m_b, v_b, lo_b))
                    return hi_b
                state_buckets.append((m_b, v_b, nm_b))
                return nm_b

            _, new_flat = zero_prefetch_gather(
                bucket, nb, shard, axis_name, axis_size
            )
            m = jnp.concatenate([t[0] for t in state_buckets])[:shard]
            v = jnp.concatenate([t[1] for t in state_buckets])[:shard]
            new_shard_state = jnp.concatenate(
                [t[2] for t in state_buckets]
            )[:shard]
        else:
            new_master, m, v = adam_math(
                p, state.exp_avg, state.exp_avg_sq, gshard
            )
            if store_param_remainders:
                hi, new_shard_state = _split_master(new_master)
                new_flat = xlax.all_gather(hi, axis_name, tiled=True)
            else:
                new_flat = xlax.all_gather(new_master, axis_name, tiled=True)
                new_shard_state = new_master

        if store_param_remainders:
            # fp32 updates: apply_updates promotes p + u to f32, so the
            # result rounds back to exactly the master's bf16 high half
            new_params = unflatten_pytree(
                new_flat, spec_like(spec, params), cast_back=True
            )
            updates = jax.tree_util.tree_map(
                lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
                new_params,
                params,
            )
        else:
            updates = zero_updates_from_flat(new_flat, params, spec)
        new_state = DistributedFusedAdamState(
            step=step, master_shard=new_shard_state, exp_avg=m,
            exp_avg_sq=v, ef_residual=new_ef,
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def spec_like(spec: FlatSpec, params: Any) -> FlatSpec:
    """Rebuild a FlatSpec whose dtypes match ``params`` (grads may be a
    different dtype than the params we unflatten into)."""
    leaves = jax.tree_util.tree_leaves(params)
    return dataclasses.replace(spec, dtypes=tuple(l.dtype for l in leaves))


class DistributedFusedAdam:
    """Class-style wrapper mirroring the reference constructor (the long
    tail of bucket/pipeline tuning knobs is intentionally absent — XLA owns
    scheduling)."""

    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        axis_name: str = "dp",
        axis_size: int = None,
        average_grads: bool = True,
        max_grad_norm: float = None,
        store_param_remainders: bool = False,
        compression=None,
        param_gather_buckets: int = None,
        **_unused,
    ):
        return distributed_fused_adam(
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            adam_w_mode=adam_w_mode,
            weight_decay=weight_decay,
            axis_name=axis_name,
            axis_size=axis_size,
            average_grads=average_grads,
            max_grad_norm=max_grad_norm,
            store_param_remainders=store_param_remainders,
            compression=compression,
            param_gather_buckets=param_gather_buckets,
        )
