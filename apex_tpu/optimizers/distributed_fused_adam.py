"""Distributed (ZeRO-2) fused Adam.

Reference parity: apex.contrib.optimizers.DistributedFusedAdam
(contrib/optimizers/distributed_fused_adam.py:266 — 3k lines of bucket
fragments, reduce-scatter hooks, stream pipelining) and
DistributedFusedLAMB (distributed_fused_lamb.py:24).

TPU design (SURVEY.md §7 stage 5): the whole machine collapses to three
collectives over the 'dp' mesh axis inside shard_map:

    grads  --flatten-->  psum_scatter  --> local Adam on the state shard
    new master shard --all_gather--> flat params --> unflatten

Optimizer state (m, v, fp32 master shard) is 1/N per device — ZeRO-2.
Overlap of the reduce-scatter with backward is XLA's latency-hiding
scheduler's job (the reference does it manually with backward hooks and
side streams); correctness here needs none of that machinery.

Must be used inside shard_map over ``axis_name`` (grads replicated or
per-device partial — pass ``average_grads=True`` when grads are per-shard
partials that still need the mean, i.e. the usual DDP case).
"""

import dataclasses
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops.multi_tensor import FlatSpec, flatten_pytree, unflatten_pytree


class DistributedFusedAdamState(NamedTuple):
    step: jax.Array
    master_shard: jax.Array  # fp32 params shard, (padded_total / N,)
    exp_avg: jax.Array  # (padded_total / N,)
    exp_avg_sq: jax.Array  # (padded_total / N,)


def _padded_flatten(tree, axis_size):
    flat, spec = flatten_pytree(tree, dtype=jnp.float32)
    pad_to = ((flat.shape[0] + axis_size - 1) // axis_size) * axis_size
    if pad_to != flat.shape[0]:
        flat = jnp.pad(flat, (0, pad_to - flat.shape[0]))
        spec = dataclasses.replace(spec, padded_total=pad_to)
    return flat, spec


def zero_init_master_shard(params, axis_name: str, axis_size: int):
    """Shared ZeRO init: flatten+pad params, keep this rank's fp32 shard.
    Returns (master_shard, shard_len)."""
    flat, _ = _padded_flatten(params, axis_size)
    shard = flat.shape[0] // axis_size
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice(flat, (idx * shard,), (shard,)), shard


def zero_scatter_grads(grads, axis_name: str, axis_size: int, average: bool):
    """Shared ZeRO grad reduce-scatter. Returns (grad_shard, spec)."""
    gflat, spec = _padded_flatten(grads, axis_size)
    gshard = jax.lax.psum_scatter(gflat, axis_name, tiled=True)
    if average:
        gshard = gshard / axis_size
    return gshard, spec


def zero_gather_updates(new_master, params, spec, axis_name: str):
    """Shared ZeRO epilogue: all-gather the updated master shard and return
    optax-style updates (new - old) in the params' dtypes."""
    new_flat = jax.lax.all_gather(new_master, axis_name, tiled=True)
    new_params = unflatten_pytree(new_flat, spec_like(spec, params), cast_back=True)
    return jax.tree_util.tree_map(
        lambda n, o: (
            n.astype(jnp.float32) - o.astype(jnp.float32)
        ).astype(o.dtype),
        new_params,
        params,
    )


def distributed_fused_adam(
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_w_mode: bool = True,
    weight_decay: float = 0.0,
    axis_name: str = "dp",
    axis_size: int = None,
    average_grads: bool = True,
) -> optax.GradientTransformation:
    """ZeRO-2 Adam over the ``axis_name`` mesh axis.

    ``axis_size`` defaults to the initialized parallel_state data-parallel
    size (parallel_state must be initialized, or pass it explicitly).
    """
    beta1, beta2 = betas
    if axis_size is None:
        from apex_tpu.parallel import parallel_state

        axis_size = parallel_state.get_data_parallel_world_size()

    def init_fn(params):
        master, shard = zero_init_master_shard(params, axis_name, axis_size)
        return DistributedFusedAdamState(
            step=jnp.zeros((), jnp.int32),
            master_shard=master,
            exp_avg=jnp.zeros((shard,), jnp.float32),
            exp_avg_sq=jnp.zeros((shard,), jnp.float32),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_adam requires params")
        gshard, spec = zero_scatter_grads(grads, axis_name, axis_size, average_grads)

        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if bias_correction else jnp.asarray(1.0)
        bc2 = 1.0 - beta2**stepf if bias_correction else jnp.asarray(1.0)

        p = state.master_shard
        g = gshard
        if not adam_w_mode and weight_decay != 0.0:
            g = g + weight_decay * p
        m = beta1 * state.exp_avg + (1.0 - beta1) * g
        v = beta2 * state.exp_avg_sq + (1.0 - beta2) * g * g
        upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            upd = upd + weight_decay * p
        new_master = p - lr * upd

        # ZeRO param all-gather
        updates = zero_gather_updates(new_master, params, spec, axis_name)
        new_state = DistributedFusedAdamState(
            step=step, master_shard=new_master, exp_avg=m, exp_avg_sq=v
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def spec_like(spec: FlatSpec, params: Any) -> FlatSpec:
    """Rebuild a FlatSpec whose dtypes match ``params`` (grads may be a
    different dtype than the params we unflatten into)."""
    leaves = jax.tree_util.tree_leaves(params)
    return dataclasses.replace(spec, dtypes=tuple(l.dtype for l in leaves))


class DistributedFusedAdam:
    """Class-style wrapper mirroring the reference constructor (the long
    tail of bucket/pipeline tuning knobs is intentionally absent — XLA owns
    scheduling)."""

    def __new__(
        cls,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        axis_name: str = "dp",
        axis_size: int = None,
        average_grads: bool = True,
        **_unused,
    ):
        return distributed_fused_adam(
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            adam_w_mode=adam_w_mode,
            weight_decay=weight_decay,
            axis_name=axis_name,
            axis_size=axis_size,
            average_grads=average_grads,
        )
