"""Fused momentum SGD.

Reference parity: apex.optimizers.FusedSGD (optimizers/fused_sgd.py) backed
by amp_C.multi_tensor_sgd — momentum, dampening, nesterov, L2 weight decay,
first-step momentum bootstrap. The amp master-weight integration
(materialize_master_grads / most_recent_scale plumbing) is handled one level
up by apex_tpu.amp.AmpOptimizer, so none of it leaks in here.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.utils.pytree import tree_map_multi


class FusedSGDState(NamedTuple):
    step: jax.Array
    momentum_buffer: Any


def fused_sgd(
    lr: float = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init_fn(params):
        buf = jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), params
        )
        return FusedSGDState(step=jnp.zeros((), jnp.int32), momentum_buffer=buf)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        step = state.step + 1
        first = state.step == 0

        def _leaf(g, p, b):
            gf = g.astype(jnp.float32)
            if weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)
            if momentum != 0.0:
                # first step: buf = grad (torch semantics); else EMA
                b_new = jnp.where(first, gf, momentum * b + (1.0 - dampening) * gf)
                d = gf + momentum * b_new if nesterov else b_new
            else:
                b_new = b
                d = gf
            return (-lr * d).astype(p.dtype), b_new

        upd, buf = tree_map_multi(_leaf, 2, grads, params, state.momentum_buffer)
        return upd, FusedSGDState(step=step, momentum_buffer=buf)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedSGD:
    """Class-style wrapper mirroring the reference constructor."""

    def __new__(
        cls,
        lr: float = 1e-3,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        **_unused,
    ):
        return fused_sgd(
            lr=lr,
            momentum=momentum,
            dampening=dampening,
            weight_decay=weight_decay,
            nesterov=nesterov,
        )
