"""Fused Adagrad.

Reference parity: apex.optimizers.FusedAdagrad (optimizers/fused_adagrad.py)
backed by amp_C.multi_tensor_adagrad: h += g^2; p -= lr * g / (sqrt(h)+eps),
with "adagrad_w_mode"-style decoupled weight decay.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.utils.pytree import tree_map_multi


class FusedAdagradState(NamedTuple):
    sum: Any  # accumulated squared gradients, fp32


def fused_adagrad(
    lr: float = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
) -> optax.GradientTransformation:
    def init_fn(params):
        return FusedAdagradState(
            sum=jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, jnp.float32), params
            )
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params")

        def _leaf(g, p, h):
            gf = g.astype(jnp.float32)
            if not adagrad_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)
            h_new = h + gf * gf
            upd = gf / (jnp.sqrt(h_new) + eps)
            if adagrad_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return (-lr * upd).astype(p.dtype), h_new

        updates, h = tree_map_multi(_leaf, 2, grads, params, state.sum)
        return updates, FusedAdagradState(sum=h)

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdagrad:
    def __new__(
        cls,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        set_grad_none: bool = True,
        **_unused,
    ):
        del set_grad_none
        return fused_adagrad(
            lr=lr, eps=eps, weight_decay=weight_decay, adagrad_w_mode=adagrad_w_mode
        )
