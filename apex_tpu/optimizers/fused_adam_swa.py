"""Fused Adam + stochastic weight averaging (openfold).

Reference parity: apex.contrib.openfold_triton.fused_adam_swa.FusedAdamSWA
(fused_adam_swa.py:208) — one kernel that per step (a) clips grads by a
scale, (b) runs Adam on fp32 state params, (c) EMA-averages the result into
a second fp32 SWA param stream (``_swa_math``: first step copies, then
``swa += (1-decay)*(param-swa)``), and (d) re-materializes the bf16 compute
params. The three ``adam_math_mode``s collapse to two on inspection:
kApexAdam and kPyTorchAdam share identical update algebra
((m/bc1)/(sqrt(v/bc2)+eps) == (1/bc1)*m/(sqrt(v)/sqrt(bc2)+eps)) with L2
weight decay folded into the grad, while kApexAdamW applies decoupled
decay — so the knob maps onto ``adam_w_mode`` exactly like fused_adam.

TPU design: an optax-style transform whose state carries the fp32 master
params AND the SWA stream; ``update`` returns deltas in the compute dtype
(the bf16 re-materialization) and the caller reads averaged weights with
``swa_params(state)``. Everything is one fused XLA computation under the
caller's jit — the Triton chunk machinery (:120-200) has no TPU meaning.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

# ref fused_adam_swa.py:30-32
kApexAdam = 0
kApexAdamW = 1
kPyTorchAdam = 2
_ADAM_MODES = {
    kApexAdam: False,  # adam_w_mode=False: L2 decay into the grad
    kApexAdamW: True,  # decoupled decay
    kPyTorchAdam: False,  # same algebra as kApexAdam (see module docstring)
    "apex": False,
    "apexw": True,
    "pytorch": False,
}


class FusedAdamSWAState(NamedTuple):
    step: jax.Array
    exp_avg: Any  # fp32
    exp_avg_sq: Any  # fp32
    master: Any  # fp32 state params (ref ``params`` group)
    swa: Any  # fp32 averaged params (ref ``swa_params`` group)
    n_averaged: jax.Array


def fused_adam_swa(
    swa_decay_rate: float,
    lr: float = 1e-3,
    bias_correction: bool = True,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    adam_math_mode=kPyTorchAdam,
    weight_decay: float = 0.0,
    grad_clip_scale: float = 1.0,
) -> optax.GradientTransformation:
    """Optax transform with FusedAdamSWA semantics.

    ``params`` passed to init/update are the COMPUTE params (bf16 in
    openfold); fp32 masters and the SWA stream live in the state, mirroring
    the reference's three parallel param lists (fused_adam_swa.py:210-213).
    """
    if adam_math_mode not in _ADAM_MODES:
        raise ValueError(
            f"Unknown Adam math mode {adam_math_mode!r}; expected "
            f"kApexAdam(0) / kApexAdamW(1) / kPyTorchAdam(2)"
        )
    adam_w_mode = _ADAM_MODES[adam_math_mode]
    beta1, beta2 = betas

    def init_fn(params):
        f32 = lambda t: jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, jnp.float32), t
        )
        zeros = jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), params
        )
        return FusedAdamSWAState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree_util.tree_map(jnp.copy, zeros),
            master=f32(params),
            swa=f32(params),
            n_averaged=jnp.zeros((), jnp.int32),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam_swa requires params")
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - beta1**stepf if bias_correction else jnp.asarray(1.0)
        bc2 = 1.0 - beta2**stepf if bias_correction else jnp.asarray(1.0)

        def one(g, p, m, v, s):
            g = g.astype(jnp.float32) * grad_clip_scale  # ref grad-clip step
            if not adam_w_mode and weight_decay != 0.0:
                g = g + weight_decay * p
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * g * g
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * p
            p = p - lr * upd
            # _swa_math: copy on the first average, EMA afterwards
            s = jnp.where(
                state.n_averaged == 0, p, s + (1.0 - swa_decay_rate) * (p - s)
            )
            return p, m, v, s

        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        results = [
            one(g, p, m, v, s)
            for g, p, m, v, s in zip(
                g_leaves,
                treedef.flatten_up_to(state.master),
                treedef.flatten_up_to(state.exp_avg),
                treedef.flatten_up_to(state.exp_avg_sq),
                treedef.flatten_up_to(state.swa),
            )
        ]
        master, m, v, swa = (
            jax.tree_util.tree_unflatten(treedef, [r[i] for r in results])
            for i in range(4)
        )
        # updates re-materialize the compute params from the new masters
        updates = jax.tree_util.tree_map(
            lambda new, p: new.astype(p.dtype) - p, master, params
        )
        return updates, FusedAdamSWAState(
            step=step, exp_avg=m, exp_avg_sq=v, master=master, swa=swa,
            n_averaged=state.n_averaged + 1,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def swa_params(state: FusedAdamSWAState, like: Any = None) -> Any:
    """The averaged weights (ref swa_param_groups), optionally cast to the
    dtypes of ``like`` (e.g. the bf16 compute params for evaluation)."""
    if like is None:
        return state.swa
    return jax.tree_util.tree_map(
        lambda s, p: s.astype(p.dtype), state.swa, like
    )


class FusedAdamSWA:
    """Class-style wrapper mirroring the reference constructor; the three
    param lists are implicit (masters/SWA live in optimizer state)."""

    def __new__(
        cls,
        swa_decay_rate: float,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_math_mode=kPyTorchAdam,
        weight_decay: float = 0.0,
        grad_clip_scale: float = 1.0,
        amsgrad: bool = False,
        capturable: bool = False,
        master_weights: bool = False,
        **_unused,
    ):
        if amsgrad:
            raise NotImplementedError("amsgrad is not supported by FusedAdamSWA")
        del capturable, master_weights  # inherent under jit / state-carried
        return fused_adam_swa(
            swa_decay_rate=swa_decay_rate,
            lr=lr,
            bias_correction=bias_correction,
            betas=betas,
            eps=eps,
            adam_math_mode=adam_math_mode,
            weight_decay=weight_decay,
            grad_clip_scale=grad_clip_scale,
        )
