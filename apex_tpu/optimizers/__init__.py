"""Fused optimizers.

Reference parity: apex/optimizers (FusedAdam, FusedLAMB, FusedSGD,
FusedNovoGrad, FusedAdagrad, FusedMixedPrecisionLamb — all backed by
amp_C multi_tensor kernels) and apex/contrib/optimizers
(DistributedFusedAdam = ZeRO-2, DistributedFusedLAMB).

TPU design: every optimizer is an optax-compatible
``GradientTransformation`` whose update math matches the reference kernels.
The "fused" property holds by construction: the entire pytree update is one
XLA fusion inside the caller's jitted step (what multi_tensor_apply buys on
GPU with chunked launches). The flat-buffer Pallas path
(apex_tpu/optimizers/_flat.py) additionally collapses many small parameters
into one contiguous kernel for step-time wins on models with many leaves.
ZeRO sharding (DistributedFusedAdam) is expressed as reduce-scatter /
all-gather over the 'dp' mesh axis inside shard_map.
"""

from apex_tpu.optimizers.fused_adam import fused_adam, FusedAdam
from apex_tpu.optimizers.fused_adam_swa import (
    fused_adam_swa,
    swa_params,
    FusedAdamSWA,
)
from apex_tpu.optimizers.fused_lamb import fused_lamb, FusedLAMB, FusedMixedPrecisionLamb
from apex_tpu.optimizers.fused_sgd import fused_sgd, FusedSGD
from apex_tpu.optimizers.fused_novograd import fused_novograd, FusedNovoGrad
from apex_tpu.optimizers.fused_adagrad import fused_adagrad, FusedAdagrad
from apex_tpu.optimizers.larc import larc, LARC
from apex_tpu.optimizers.clip_grad import clip_grad_norm
from apex_tpu.optimizers.distributed_fused_adam import (
    choose_overlap_buckets,
    distributed_fused_adam,
    zero_prefetch_gather,
    zero_regroup_flat,
    zero_state_specs,
    DistributedFusedAdam,
)
from apex_tpu.optimizers.distributed_fused_lamb import (
    distributed_fused_lamb,
    DistributedFusedLAMB,
)

__all__ = [
    "fused_adam",
    "FusedAdam",
    "fused_adam_swa",
    "swa_params",
    "FusedAdamSWA",
    "fused_lamb",
    "FusedLAMB",
    "FusedMixedPrecisionLamb",
    "fused_sgd",
    "FusedSGD",
    "fused_novograd",
    "FusedNovoGrad",
    "fused_adagrad",
    "FusedAdagrad",
    "larc",
    "LARC",
    "clip_grad_norm",
    "choose_overlap_buckets",
    "distributed_fused_adam",
    "zero_prefetch_gather",
    "zero_regroup_flat",
    "zero_state_specs",
    "DistributedFusedAdam",
    "distributed_fused_lamb",
    "DistributedFusedLAMB",
]
