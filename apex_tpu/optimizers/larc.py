"""LARC — Layer-wise Adaptive Rate Clipping/Scaling.

Reference parity: apex.parallel.LARC (parallel/LARC.py:5) — wraps any
optimizer; before the inner step, each parameter's gradient is rescaled by
the local adaptive lr

    local_lr = trust_coefficient * ||p|| / (||g|| + weight_decay * ||p|| + eps)

In ``clip`` mode local_lr is capped at the base lr (scale factor
min(local_lr/lr, 1)); in scale mode the factor is local_lr/lr.

TPU design: an optax gradient transform chained *before* the inner
transform — identical composition semantics to the reference's
optimizer-wrapper, but pure.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class LARCState(NamedTuple):
    pass


def larc_scaling(
    lr: float,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """The grad-rescaling stage of LARC, as a standalone transform."""

    def init_fn(params):
        del params
        return LARCState()

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("larc requires params")

        def _leaf(g, p):
            gf = g.astype(jnp.float32)
            pf = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(pf * pf))
            g_norm = jnp.sqrt(jnp.sum(gf * gf))
            local_lr = (
                trust_coefficient * p_norm / (g_norm + weight_decay * p_norm + eps)
            )
            ok = (p_norm > 0) & (g_norm > 0)
            if clip:
                factor = jnp.where(ok, jnp.minimum(local_lr / lr, 1.0), 1.0)
            else:
                factor = jnp.where(ok, local_lr / lr, 1.0)
            return (gf * factor).astype(g.dtype)

        return jax.tree_util.tree_map(_leaf, grads, params), state

    return optax.GradientTransformation(init_fn, update_fn)


def larc(
    inner: optax.GradientTransformation,
    lr: float,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """LARC wrapper: rescale grads layer-wise, then run ``inner``."""
    return optax.chain(
        larc_scaling(lr, trust_coefficient, clip, eps, weight_decay), inner
    )


class LARC:
    """Class-style alias mirroring apex.parallel.LARC(optimizer, ...)."""

    def __new__(
        cls,
        optimizer: optax.GradientTransformation,
        lr: float = 1e-3,
        trust_coefficient: float = 0.02,
        clip: bool = True,
        eps: float = 1e-8,
        **_unused,
    ):
        return larc(optimizer, lr=lr, trust_coefficient=trust_coefficient, clip=clip, eps=eps)
