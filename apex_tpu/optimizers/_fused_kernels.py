"""Flat-buffer Pallas optimizer kernels.

Reference parity: amp_C.multi_tensor_adam (csrc/multi_tensor_adam.cu:13-14)
driven by the chunked multi_tensor_apply engine
(csrc/multi_tensor_apply.cuh:19-133) — one kernel launch updates every
parameter tensor. TPU design: the pytree is flattened ONCE into a padded
fp32 buffer (ops/multi_tensor.flatten_pytree) and a single Pallas kernel
walks it in CHUNK_SIZE blocks; the (8,128)-aligned padding removes all the
reference's per-chunk remainder handling.

The jnp twin (`_adam_flat_ref`) is bit-identical math used for the
impl="xla" path and CPU tests; `fused_adam(fuse="flat")` in fused_adam.py
plugs either into the optax interface. benchmarks/bench_optimizers.py
measures flat-vs-tree; current numbers are in BENCH.md (CPU: tree Adam
wins — flatten round-trip overhead; flat l2norm wins 1.7x on already-flat
buffers, which is why the ZeRO optimizers use it).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import resolve_impl
from apex_tpu.ops.multi_tensor import CHUNK_SIZE

_LANES = 128
_ROWS_PER_CHUNK = CHUNK_SIZE // _LANES  # 512 rows of 128 f32 lanes


def _adam_flat_kernel(
    sc_ref, g_ref, p_ref, m_ref, v_ref,
    upd_ref, m_out_ref, v_out_ref,
    *, lr, beta1, beta2, eps, weight_decay, adam_w_mode,
):
    """One CHUNK of the Adam update (ref multi_tensor_adam.cu:13-14 math:
    ADAM_MODE_0 = AdamW decoupled decay, ADAM_MODE_1 = L2 into the grad)."""
    bc1 = sc_ref[0, 0]  # 1 - beta1^t (bias correction, traced via step)
    bc2 = sc_ref[0, 1]
    g = g_ref[...]
    p = p_ref[...]
    if not adam_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode and weight_decay != 0.0:
        upd = upd + weight_decay * p
    upd_ref[...] = -lr * upd
    m_out_ref[...] = m
    v_out_ref[...] = v


def _adam_flat_ref(g, p, m, v, bc1, bc2, *, lr, beta1, beta2, eps,
                   weight_decay, adam_w_mode):
    """jnp twin of the kernel — identical math, XLA-fused."""
    if not adam_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p
    m = beta1 * m + (1.0 - beta1) * g
    v = beta2 * v + (1.0 - beta2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w_mode and weight_decay != 0.0:
        upd = upd + weight_decay * p
    return -lr * upd, m, v


def adam_flat(
    g_flat, p_flat, m_flat, v_flat, bc1, bc2,
    *, lr, beta1, beta2, eps, weight_decay, adam_w_mode,
    impl: str = "auto",
):
    """Adam over padded flat fp32 buffers; returns (update, m, v).

    All four buffers must share the same length, a multiple of CHUNK_SIZE
    (flatten_pytree guarantees this). ``bc1``/``bc2`` are the (traced)
    bias-correction denominators; everything else is static.
    """
    (n,) = g_flat.shape
    assert n % CHUNK_SIZE == 0, f"flat buffer ({n}) not CHUNK_SIZE-padded"
    use_pallas, interpret = resolve_impl(impl)
    if not use_pallas:
        return _adam_flat_ref(
            g_flat, p_flat, m_flat, v_flat, bc1, bc2,
            lr=lr, beta1=beta1, beta2=beta2, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
        )
    rows = n // _LANES
    view = lambda a: a.reshape(rows, _LANES)
    sc = jnp.stack([
        jnp.asarray(bc1, jnp.float32), jnp.asarray(bc2, jnp.float32)
    ]).reshape(1, 2)
    grid = (n // CHUNK_SIZE,)
    chunk_spec = pl.BlockSpec(
        (_ROWS_PER_CHUNK, _LANES), lambda i: (i, 0),
        memory_space=pltpu.VMEM,
    )
    kernel = functools.partial(
        _adam_flat_kernel, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
        weight_decay=weight_decay, adam_w_mode=adam_w_mode,
    )
    upd, m, v = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
            chunk_spec, chunk_spec, chunk_spec, chunk_spec,
        ],
        out_specs=(chunk_spec, chunk_spec, chunk_spec),
        interpret=interpret,
    )(sc, view(g_flat), view(p_flat), view(m_flat), view(v_flat))
    return upd.reshape(n), m.reshape(n), v.reshape(n)


def _l2norm_flat_kernel(x_ref, acc_ref):
    """Partial sum-of-squares per chunk, accumulated across the grid into
    one (1,1) SMEM cell (ref multi_tensor_l2norm_kernel.cu's two-stage
    block reduction collapsed into a sequential-grid accumulation)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0, 0] = 0.0

    x = x_ref[...]
    acc_ref[0, 0] += jnp.sum(x * x)


def sumsq_flat(x_flat, impl: str = "auto"):
    """Sum of squares of a flat buffer.

    Accepts any length: internally zero-padded to a CHUNK_SIZE multiple for
    the Pallas grid (zeros contribute nothing to the sum). This is the
    reduction ZeRO shards feed — a per-rank shard of a CHUNK-padded buffer
    (`padded_total / dp`) is generally NOT itself CHUNK-aligned.
    """
    (n,) = x_flat.shape
    use_pallas, interpret = resolve_impl(impl)
    xf = x_flat.astype(jnp.float32)
    if not use_pallas:
        return jnp.sum(xf * xf)
    if n % CHUNK_SIZE:
        xf = jnp.pad(xf, (0, CHUNK_SIZE - n % CHUNK_SIZE))
        (n,) = xf.shape
    rows = n // _LANES
    sq = pl.pallas_call(
        _l2norm_flat_kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        grid=(n // CHUNK_SIZE,),
        in_specs=[
            pl.BlockSpec(
                (_ROWS_PER_CHUNK, _LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        ),
        interpret=interpret,
    )(xf.reshape(rows, _LANES))
    return sq[0, 0]


def l2norm_flat(x_flat, impl: str = "auto"):
    """Global L2 norm of a flat buffer (padding zeros contribute 0).

    Measured 1.7x faster than the tree-based ``multi_tensor_l2norm`` on
    already-flat buffers even on CPU/XLA (BENCH.md) — the flat path is the
    default wherever the data already lives in one buffer (ZeRO shards in
    distributed_fused_lamb; fused_adam's flat engine).
    """
    return jnp.sqrt(sumsq_flat(x_flat, impl=impl))
