"""Fused gradient clipping.

Reference parity: apex.contrib.clip_grad.clip_grad_norm_
(contrib/clip_grad/clip_grad.py:16) — global-norm clip using
multi_tensor_l2norm + multi_tensor_scale.

Engine choice (measured, BENCH.md): the tree-based norm stays because the
input here is a pytree — the flat reduction only wins when the data already
lives in one buffer (flatten round-trips cost more than they save; see the
adam tree-vs-flat row). ZeRO optimizers, whose shards ARE flat, use
``_fused_kernels.sumsq_flat`` instead.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops.multi_tensor import multi_tensor_l2norm


def clip_grad_norm(
    grads: Any, max_norm: float, norm_type: float = 2.0
) -> Tuple[Any, jax.Array]:
    """Clip grads to global ``max_norm``; returns (clipped_grads, total_norm).

    Functional: returns new grads instead of mutating in place.
    """
    if norm_type == 2.0:
        total_norm = multi_tensor_l2norm(grads)
    elif norm_type == float("inf"):
        leaves = jax.tree_util.tree_leaves(grads)
        total_norm = jnp.max(
            jnp.stack([jnp.max(jnp.abs(x.astype(jnp.float32))) for x in leaves])
        )
    else:
        leaves = jax.tree_util.tree_leaves(grads)
        acc = sum(
            jnp.sum(jnp.abs(x.astype(jnp.float32)) ** norm_type) for x in leaves
        )
        total_norm = acc ** (1.0 / norm_type)
    coef = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * coef).astype(g.dtype), grads
    )
    return clipped, total_norm
