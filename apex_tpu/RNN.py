"""``apex.RNN`` import-surface alias (reference:
/root/reference/apex/RNN/__init__.py — the deprecated-but-shipped RNN
factories).  Implementations live in ``apex_tpu.rnn`` (lowercase, the
package's own naming); this alias keeps
``from apex.RNN import LSTM`` migrations working verbatim."""

from apex_tpu.rnn import models
from apex_tpu.rnn.models import GRU, LSTM, ReLU, Tanh, mLSTM

__all__ = ["models", "LSTM", "GRU", "ReLU", "Tanh", "mLSTM"]
