"""BERT model.

Reference parity: apex/transformer/testing/standalone_bert.py — bidirectional
(padding-mask) transformer with tokentype embeddings, an LM head (dense +
gelu + LN + tied-embedding logits) and a binary (NSP) head off a tanh pooler.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import Embedding
from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.parallel.layers import _tp_size
from apex_tpu.parallel.mappings import gather_from_sequence_parallel_region
from apex_tpu.transformer.config import TransformerConfig
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.layer import ParallelTransformer


def bert_extended_attention_mask(attention_mask):
    """(b, s) 1=keep → (b, 1, s, s) True=masked-out.

    Ref: bert_extended_attention_mask in standalone_bert.py — attention_mask
    is the padding indicator; the extended mask is the outer product inverted.
    """
    m = attention_mask.astype(bool)
    ext = m[:, None, :] & m[:, :, None]  # (b, s, s)
    return ~ext[:, None, :, :]


class Pooler(nn.Module):
    """Tanh pooler over the first token (ref: Pooler in
    standalone_transformer_lm.py)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden_states):  # (s, b, h)
        first = hidden_states[0]  # (b, h)
        d = nn.Dense(self.config.hidden_size, param_dtype=self.config.params_dtype)(
            first
        )
        return jnp.tanh(d.astype(jnp.float32)).astype(hidden_states.dtype)


class BertModel(nn.Module):
    """BERT with LM + optional binary head.

    Returns (lm_loss_or_logits, binary_logits) when ``add_binary_head``;
    vocab logits stay tp-sharded for vocab_parallel_cross_entropy.
    """

    config: TransformerConfig
    num_tokentypes: int = 2
    add_binary_head: bool = True
    pre_process: bool = True
    post_process: bool = True
    num_layers: Optional[int] = None

    def setup(self):
        cfg = self.config
        if self.pre_process or (
            self.post_process and cfg.share_embeddings_and_output_weights
        ):
            self.embedding = Embedding(
                config=cfg, num_tokentypes=self.num_tokentypes, name="embedding"
            )
        self.transformer = ParallelTransformer(
            config=cfg,
            num_layers=self.num_layers,
            post_layer_norm=self.post_process,
            attn_mask_type=AttnMaskType.padding,
            name="transformer",
        )
        if self.post_process:
            self.lm_dense = nn.Dense(
                cfg.hidden_size, param_dtype=cfg.params_dtype, name="lm_head_dense"
            )
            self.lm_norm_scale = self.param(
                "lm_head_norm_scale", nn.initializers.ones_init(), (cfg.hidden_size,)
            )
            self.lm_norm_bias = self.param(
                "lm_head_norm_bias", nn.initializers.zeros_init(), (cfg.hidden_size,)
            )
            if self.add_binary_head:
                self.pooler = Pooler(config=cfg, name="pooler")
                self.binary_head = nn.Dense(
                    2, param_dtype=cfg.params_dtype, name="binary_head"
                )

    def __call__(
        self,
        tokens,
        attention_mask=None,
        tokentype_ids=None,
        lm_labels=None,
        deterministic: bool = True,
    ):
        cfg = self.config
        # padding keeps the Pallas flash fast path: a (b, s) key-padding row
        # reaches the kernel directly (ops/attention.py key_padding_mask —
        # the reference fmha's cu_seqlens role). Semantics match the dense
        # extended mask: queries at padded positions attend uniformly but
        # their losses are masked out (Megatron masks lm_loss by loss_mask).
        # Attention dropout forces the unfused CoreAttention path, which
        # wants the dense (b,1,s,s) extended mask.
        ext_mask = None
        key_padding_mask = None
        if attention_mask is not None:
            if cfg.attention_dropout > 0.0 and not deterministic:
                ext_mask = bert_extended_attention_mask(attention_mask)
            else:
                key_padding_mask = attention_mask.astype(bool) == False  # noqa: E712
        if self.pre_process:
            if tokentype_ids is None and self.num_tokentypes > 0:
                tokentype_ids = jnp.zeros_like(tokens)  # segment-0 default
            h = self.embedding(
                tokens, tokentype_ids=tokentype_ids, deterministic=deterministic
            )
        else:
            h = tokens
        h = self.transformer(
            h, attention_mask=ext_mask, key_padding_mask=key_padding_mask,
            deterministic=deterministic,
        )
        if not self.post_process:
            return h

        if cfg.sequence_parallel and _tp_size(cfg.tensor_axis) > 1:
            # pooler/LM head need the full sequence (token 0 lives on rank 0).
            # to_model_parallel=False (backward = split): two heads consume
            # this tensor — the binary head's cotangent is replicated over tp
            # while the LM head's partial cotangent is psum'ed by attend()'s
            # copy_to vjp — so the summed cotangent here is replicated and a
            # reduce-scatter backward would double-count the binary path.
            h = gather_from_sequence_parallel_region(
                h, cfg.tensor_axis, to_model_parallel=False
            )

        binary_logits = None
        if self.add_binary_head:
            pooled = self.pooler(h)
            binary_logits = self.binary_head(pooled).astype(jnp.float32)

        lm = self.lm_dense(h)
        lm = jax.nn.gelu(lm.astype(jnp.float32), approximate=True)
        lm = layer_norm(
            lm,
            self.lm_norm_scale,
            self.lm_norm_bias,
            eps=cfg.layernorm_epsilon,
        ).astype(h.dtype)
        logits = self.embedding.word_embeddings.attend(lm)  # (s, b, v/tp)
        logits = jnp.transpose(logits, (1, 0, 2))  # (b, s, v/tp)
        if lm_labels is None:
            return logits, binary_logits
        losses = vocab_parallel_cross_entropy(
            logits, lm_labels, axis_name=cfg.tensor_axis
        )
        return losses, binary_logits
