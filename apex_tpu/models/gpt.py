"""GPT language model.

Reference parity: apex/transformer/testing/standalone_gpt.py (gpt_model
over TransformerLanguageModel, standalone_transformer_lm.py) — vocab-parallel
embedding + learned/rotary positions, causal ParallelTransformer, tied
embedding logits, vocab-parallel cross entropy. ``pre_process``/``post_process``
mirror the pipeline-stage flags of build_model (schedules/common.py:83-108).

Layout: tokens are (batch, seq); hidden states run (seq, batch, hidden)
through the stack (Megatron layout, so sequence-parallel mappings act on
dim 0); loss is per-token (batch, seq) fp32.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.parallel.layers import (
    ColumnParallelLinear,
    VocabParallelEmbedding,
    _tp_size,
)
from apex_tpu.parallel.mappings import (
    gather_from_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
)
from apex_tpu.transformer.config import TransformerConfig
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.layer import ParallelTransformer, rotary_embedding_for


class Embedding(nn.Module):
    """Word + learned-position (+tokentype) embeddings with dropout.

    Ref: Embedding in standalone_transformer_lm.py — VocabParallelEmbedding
    plus a replicated position table; with sequence parallelism the output is
    scattered along the sequence dim (mappings.py:213).
    """

    config: TransformerConfig
    num_tokentypes: int = 0

    def setup(self):
        cfg = self.config
        self.word_embeddings = VocabParallelEmbedding(
            num_embeddings=cfg.vocab_size,
            embedding_dim=cfg.hidden_size,
            axis_name=cfg.tensor_axis,
            params_dtype=cfg.params_dtype,
            # Megatron init_method_normal(init_method_std=0.02) — the
            # reference's testing/arguments.py default; N(0,1) blows up the
            # tied-logit scale (std ~ sqrt(hidden))
            embedding_init=nn.initializers.normal(stddev=0.02),
            name="word_embeddings",
        )
        if cfg.position_embedding_type == "learned":
            self.position_embeddings = self.param(
                "position_embeddings",
                nn.initializers.normal(stddev=0.02),
                (cfg.max_position_embeddings, cfg.hidden_size),
                cfg.params_dtype,
            )
        if self.num_tokentypes > 0:
            self.tokentype_embeddings = self.param(
                "tokentype_embeddings",
                nn.initializers.normal(stddev=0.02),
                (self.num_tokentypes, cfg.hidden_size),
                cfg.params_dtype,
            )
        # setup-based module: submodules must be declared here, not inline.
        # Dropout runs BEFORE the SP scatter (full-sequence mask, identical
        # on all tp ranks) but tokens are already cp-sharded — fold cp.
        from apex_tpu.transformer.layer import ShardAwareDropout

        cp_axes = (cfg.context_axis,) if cfg.context_parallel_mode else ()
        self.dropout = ShardAwareDropout(rate=cfg.hidden_dropout, axis_names=cp_axes)

    def __call__(self, tokens, position_ids=None, tokentype_ids=None,
                 deterministic: bool = True, decode_step: bool = False):
        # decode_step: a replicated single token — skip the SP scatter (one
        # token cannot be sequence-sharded; see transformer/layer.py's
        # plain-TP decode layout)
        cfg = self.config
        h = self.word_embeddings(tokens)  # (b, s, h)
        if cfg.position_embedding_type == "learned":
            if position_ids is None:
                position_ids = jnp.arange(tokens.shape[1])[None, :]
                if cfg.context_parallel_mode is not None:
                    # cp-sharded sequence: local chunk r holds global
                    # positions r*s_local.. — offset by the cp rank (same
                    # fix as the rotary-table slice in transformer/layer.py)
                    cp = _tp_size(cfg.context_axis)
                    if cp > 1:
                        rank = jax.lax.axis_index(cfg.context_axis)
                        position_ids = position_ids + rank * tokens.shape[1]
            h = h + jnp.take(self.position_embeddings, position_ids, axis=0)
        if tokentype_ids is not None:
            if self.num_tokentypes <= 0:
                raise ValueError(
                    "tokentype_ids passed but num_tokentypes == 0 "
                    "(ref: Megatron Embedding raises on this mismatch)"
                )
            h = h + jnp.take(self.tokentype_embeddings, tokentype_ids, axis=0)
        elif self.num_tokentypes > 0:
            raise ValueError(
                "num_tokentypes > 0 but no tokentype_ids passed — the "
                "tokentype table would silently train as dead weight"
            )
        h = jnp.transpose(h, (1, 0, 2))  # (s, b, h)
        h = h.astype(cfg.compute_dtype)
        if cfg.hidden_dropout > 0.0:
            h = self.dropout(h, deterministic=deterministic)
        if (cfg.sequence_parallel and _tp_size(cfg.tensor_axis) > 1
                and not decode_step):
            h = scatter_to_sequence_parallel_region(h, cfg.tensor_axis)
        return h


class GPTModel(nn.Module):
    """Causal LM over the parallel transformer stack.

    ``num_layers`` overrides the stage-local depth for pipeline chunks;
    when ``post_process`` and labels are given, returns per-token CE losses
    (ref: post_language_model_processing in standalone_gpt.py), else logits
    (vocab-sharded over tp) or, for intermediate stages, hidden states.
    """

    config: TransformerConfig
    pre_process: bool = True
    post_process: bool = True
    num_layers: Optional[int] = None

    def setup(self):
        cfg = self.config
        if self.pre_process or (
            self.post_process and cfg.share_embeddings_and_output_weights
        ):
            self.embedding = Embedding(config=cfg, name="embedding")
        if self.post_process and not cfg.share_embeddings_and_output_weights:
            # untied output head: vocab-parallel projection (ref: Megatron
            # untie_embeddings_and_output_weights path in parallel_lm_logits)
            self.output_layer = ColumnParallelLinear(
                output_size=cfg.vocab_size,
                use_bias=False,
                axis_name=cfg.tensor_axis,
                params_dtype=cfg.params_dtype,
                kernel_init=nn.initializers.normal(stddev=0.02),
                # the layer's own SP gather has a reduce-scatter backward —
                # half the comm of a manual gather + copy_to composition
                sequence_parallel_enabled=cfg.sequence_parallel,
                # fp32 logits for the vocab-parallel CE, like the tied path
                output_dtype=jnp.float32,
                name="output_layer",
            )
        self.transformer = ParallelTransformer(
            config=cfg,
            num_layers=self.num_layers,
            post_layer_norm=self.post_process,
            attn_mask_type=AttnMaskType.causal,
            name="transformer",
        )

    def __call__(
        self,
        tokens,
        position_ids=None,
        attention_mask=None,
        key_padding_mask=None,
        labels=None,
        loss_mask=None,
        deterministic: bool = True,
        cache_len=None,
        decode_step: bool = False,
    ):
        # key_padding_mask: (b, s) bool, True = padded-out key; stays on the
        # attention fast paths (flash kernel, ring/Ulysses CP — under cp>1
        # pass the LOCAL sequence shard, sharded exactly like tokens)
        cfg = self.config
        cache_active = cache_len is not None or decode_step
        if self.pre_process:
            h = self.embedding(tokens, position_ids,
                               deterministic=deterministic,
                               decode_step=decode_step)
        else:
            h = tokens  # already (s_local, b, h) hidden states from prev stage

        rotary = None
        if cfg.position_embedding_type == "rope":
            seq = h.shape[0]
            if cfg.sequence_parallel and _tp_size(cfg.tensor_axis) > 1:
                seq = seq * _tp_size(cfg.tensor_axis)
            if cfg.context_parallel_mode is not None:
                # cp-sharded sequence: build the GLOBAL table; attention
                # slices each rank's chunk (transformer/layer.py)
                seq = seq * _tp_size(cfg.context_axis)
            if cache_active:
                # KV-cache decoding: the full-length table; attention slices
                # each call's absolute positions (prefill [0, s), step
                # [cache_index, cache_index+1))
                seq = cache_len if cache_len is not None else (
                    cfg.max_position_embeddings
                )
            rotary = rotary_embedding_for(cfg, seq)

        h = self.transformer(
            h,
            attention_mask=attention_mask,
            key_padding_mask=key_padding_mask,
            rotary_pos_emb=rotary,
            deterministic=deterministic,
            **(
                {"cache_len": cache_len, "decode_step": decode_step}
                if cache_active
                else {}
            ),
        )
        if not self.post_process:
            return h

        tied = cfg.share_embeddings_and_output_weights
        # decode steps carry a replicated single token — nothing is
        # sequence-sharded, so the SP head gather must not run
        sp_gathered = (cfg.sequence_parallel and _tp_size(cfg.tensor_axis) > 1
                       and not decode_step)
        if tied:
            if sp_gathered:
                # to_model_parallel=True — attend(parallel_input=True) leaves
                # dh partial per tp rank and the gather backward is a single
                # reduce-scatter (the reference's
                # tensor_parallel_output_grad=True path)
                h = gather_from_sequence_parallel_region(
                    h, cfg.tensor_axis, to_model_parallel=True
                )
            logits = self.embedding.word_embeddings.attend(
                h, parallel_input=sp_gathered
            )  # (s, b, v/tp) fp32
        else:
            # the layer performs the SP gather itself (reduce-scatter
            # backward) and emits fp32 logits
            logits = self.output_layer(
                h,
                **({"sequence_parallel_override": False}
                   if decode_step else {}),
            )
        logits = jnp.transpose(logits, (1, 0, 2))  # (b, s, v/tp)
        if labels is None:
            return logits
        losses = vocab_parallel_cross_entropy(
            logits, labels, axis_name=cfg.tensor_axis
        )
        if loss_mask is not None:
            losses = losses * loss_mask
        return losses


def gpt_loss_fn(losses, loss_mask=None):
    """Mean loss over unmasked tokens (ref: loss_func in test_gpt_minimal.py)."""
    if loss_mask is None:
        return jnp.mean(losses)
    m = loss_mask.astype(jnp.float32)
    return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)
