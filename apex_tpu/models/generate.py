"""Autoregressive decoding for the causal LMs.

Extension beyond the reference (apex has no inference path). Two modes:

- ``use_cache=True`` (default): one prefill pass writes rotated K/V into
  per-layer "cache" variables (transformer/layer.py ParallelAttention),
  then each new token runs the model at sequence length 1 against the
  cache through the flash key-padding fast path — O(S) attention per
  token instead of O(S^2), the standard KV-cache decode.
- ``use_cache=False``: the model recomputes the full prefix each step
  (O(S^2) per token). Kept as the reference path the cache is tested
  against, and for models without cache support.

Parity: tests/test_hf_parity.py pins greedy continuations against HF
``generate(do_sample=False)`` on the same imported weights; cached and
uncached decode are asserted token-identical.
"""

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["generate", "beam_search", "sample_next_token"]


def _filter_logits(next_logits, top_k, top_p):
    """Standard nucleus/top-k truncation: logits outside the kept set are
    driven to -inf so categorical sampling never picks them.

    Hardened edges (pinned in tests/test_hf_parity.py):

    - ``top_k >= vocab`` is an exact no-op (HF clamps; the sort+compare
      below would also keep everything, but skipping it avoids paying a
      vocab-sized sort for a filter that cannot filter);
    - ``top_p >= 1.0`` is an exact no-op: the cumulative-sum comparison
      is float arithmetic, and near the boundary a rounding of
      ``csum`` to exactly 1.0 one slot early could truncate a genuinely
      nonzero-probability tail token — "keep the full mass" must not
      depend on summation order;
    - ``top_k < 1`` / ``top_p <= 0`` are caller errors, refused with a
      reason (a silent empty keep-set would make categorical sample
      from all -inf logits and return garbage token 0).
    """
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_k >= next_logits.shape[-1]:
            top_k = None  # keep everything: exact no-op
    if top_p is not None:
        if top_p <= 0.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_p >= 1.0:
            top_p = None  # full mass: exact no-op
    if top_k is not None:
        kth = jnp.sort(next_logits, axis=-1)[:, -top_k][:, None]
        next_logits = jnp.where(next_logits < kth, -jnp.inf, next_logits)
    if top_p is not None:
        sorted_desc = jnp.sort(next_logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p; the
        # shifted comparison always keeps the top token
        keep_sorted = jnp.roll(csum < top_p, 1, axis=-1).at[:, 0].set(True)
        kept = jnp.sum(keep_sorted, axis=-1)  # per-row cutoff count
        cutoff = jnp.take_along_axis(
            sorted_desc, (kept - 1)[:, None], axis=-1
        )
        next_logits = jnp.where(next_logits < cutoff, -jnp.inf, next_logits)
    return next_logits


def _select_next(next_logits, temperature, key, top_k=None, top_p=None):
    if temperature > 0.0:
        # temperature BEFORE truncation (HF warper order): the nucleus is
        # computed on the tempered distribution, so high temperatures keep
        # more tokens — filtering raw logits would diverge from HF whenever
        # temperature != 1
        next_logits = _filter_logits(next_logits / temperature, top_k, top_p)
        return jax.random.categorical(key, next_logits, axis=-1)
    return jnp.argmax(next_logits, axis=-1)


def sample_next_token(next_logits, temperature, key, top_k=None,
                      top_p=None):
    """Single-position sampling with a TRACED per-call temperature.

    The serving engine (``apex_tpu.serving``) batches requests with
    heterogeneous temperatures through ONE compiled decode step, so the
    temperature must be an ordinary traced scalar — ``_select_next``'s
    python-float branch (``if temperature > 0``) would burn a recompile
    per distinct value. Branchless instead: both the greedy argmax and
    the tempered/filtered categorical sample are computed, and
    ``jnp.where`` picks by the traced ``temperature > 0``. ``top_k`` /
    ``top_p`` stay STATIC (they shape the sort/cumsum); the HF warper
    order (temper BEFORE truncation) is preserved exactly as in
    :func:`_select_next`.

    ``next_logits`` is ``(v,)`` or ``(b, v)``; returns int token id(s)
    of matching batch rank.
    """
    squeeze = next_logits.ndim == 1
    logits = next_logits[None] if squeeze else next_logits
    logits = logits.astype(jnp.float32)
    # a zero (greedy) temperature must not divide by zero inside the
    # discarded sampling branch: NaN logits would propagate through
    # where() on some backends' grads — clamp the divisor only
    safe_t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    filtered = _filter_logits(logits / safe_t, top_k, top_p)
    sampled = jax.random.categorical(key, filtered, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    tok = jnp.where(jnp.asarray(temperature) > 0.0, sampled, greedy)
    return tok[0] if squeeze else tok


def _check_position_bound(model, s, max_new_tokens):
    max_pos = getattr(
        getattr(model, "config", None), "max_position_embeddings", None
    )
    # rope models may leave the field at its 0 default (no position table)
    if max_pos and s + max_new_tokens > max_pos:
        # out-of-range positions would be silently CLAMPED by the gather
        # (jnp.take clips), yielding garbage continuations — fail loudly
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model's max_position_embeddings ({max_pos})"
        )


def generate(
    model,
    variables,
    prompt_tokens,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    use_cache: bool = True,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
):
    """Continue ``prompt_tokens`` ((b, s) int32) by ``max_new_tokens``.

    ``temperature == 0``: greedy argmax. Otherwise softmax sampling at the
    given temperature using ``rng``, optionally truncated to the ``top_k``
    highest logits and/or the ``top_p`` probability nucleus (both are the
    HF-convention semantics). Returns (b, s + max_new_tokens).
    """
    b, s = prompt_tokens.shape
    total = s + max_new_tokens
    if max_new_tokens <= 0:
        return prompt_tokens
    _check_position_bound(model, s, max_new_tokens)
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused by greedy; keeps the scan uniform

    buf = jnp.zeros((b, total), prompt_tokens.dtype)
    buf = jax.lax.dynamic_update_slice(buf, prompt_tokens, (0, 0))

    if use_cache:
        # prefill: prompt logits + per-layer K/V cache sized for the run
        logits, state = model.apply(
            variables, prompt_tokens, cache_len=total, mutable=["cache"]
        )
        rng, sub = jax.random.split(rng)
        nxt = _select_next(
            logits[:, s - 1, :].astype(jnp.float32), temperature, sub,
            top_k, top_p,
        ).astype(buf.dtype)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, s))

        def step(carry, _):
            buf, cache, tok, cur, key = carry
            logits, updated = model.apply(
                {**variables, "cache": cache},
                tok[:, None],
                position_ids=cur[None, None],  # learned-position models
                # cache_len sizes the rope table; the config's
                # max_position_embeddings may legitimately be 0 for rope
                cache_len=total,
                decode_step=True,
                mutable=["cache"],
            )
            key, sub = jax.random.split(key)
            nxt = _select_next(
                logits[:, 0, :].astype(jnp.float32), temperature, sub,
                top_k, top_p,
            ).astype(buf.dtype)
            buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, cur + 1))
            return (buf, updated["cache"], nxt, cur + 1, key), None

        if max_new_tokens > 1:
            (buf, _, _, _, _), _ = jax.lax.scan(
                step,
                (buf, state["cache"], nxt, jnp.int32(s), rng),
                None,
                length=max_new_tokens - 1,
            )
        return buf

    def step(carry, _):
        buf, cur, key = carry
        logits = model.apply(variables, buf)  # (b, total, vocab)
        # the next token comes from position cur-1 (causal: positions >= cur
        # hold garbage but cannot influence it)
        next_logits = jax.lax.dynamic_slice_in_dim(
            logits, cur - 1, 1, axis=1
        )[:, 0, :].astype(jnp.float32)
        key, sub = jax.random.split(key)
        nxt = _select_next(
            next_logits, temperature, key=sub, top_k=top_k, top_p=top_p
        ).astype(buf.dtype)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, cur))
        return (buf, cur + 1, key), None

    (buf, _, _), _ = jax.lax.scan(
        step, (buf, jnp.int32(s), rng), None, length=max_new_tokens
    )
    return buf


def beam_search(
    model,
    variables,
    prompt_tokens,
    max_new_tokens: int,
    num_beams: int,
    length_penalty: float = 1.0,
):
    """Beam-search decoding over the KV cache.

    Standard fixed-width beam search: the prompt is prefilled once per
    batch row, the cache is expanded to ``b*num_beams`` rows, and every
    step scores all ``num_beams * vocab`` continuations, keeps the top
    ``num_beams``, and REORDERS the cache rows to follow their beams (the
    jnp.take on the cache pytree is the TPU analogue of HF's
    ``_reorder_cache``). Returns ``(tokens, scores)`` with tokens
    (b, num_beams, s + max_new_tokens) sorted best-first and scores the
    length-normalized sequence log-probs
    (sum logp / (s + max_new_tokens)^length_penalty — HF's BeamHypotheses
    convention of dividing by the FULL hypothesis length incl. prompt).

    No early stopping / EOS handling: the models here have no reserved
    tokens; generation always runs ``max_new_tokens`` steps.
    """
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    b, s = prompt_tokens.shape
    total = s + max_new_tokens
    if max_new_tokens > 0:
        _check_position_bound(model, s, max_new_tokens)
    if max_new_tokens <= 0:
        scores = jnp.zeros((b, num_beams), jnp.float32)
        return jnp.broadcast_to(
            prompt_tokens[:, None, :], (b, num_beams, s)
        ), scores
    k = num_beams

    # prefill once per row, then tile rows to beams
    logits, state = model.apply(
        variables, prompt_tokens, cache_len=total, mutable=["cache"]
    )
    logp0 = jax.nn.log_softmax(logits[:, s - 1, :].astype(jnp.float32), -1)
    vocab = logp0.shape[-1]
    first = jax.lax.top_k(logp0, k)  # (b, k) values/indices

    def tile_beams(x):
        # row r -> beams r*k .. r*k+k-1; scalar bookkeeping leaves
        # (cache_index) are shared by all beams and stay as they are
        return jnp.repeat(x, k, axis=0) if x.ndim else x

    cache = jax.tree_util.tree_map(tile_beams, state["cache"])
    buf = jnp.zeros((b * k, total), prompt_tokens.dtype)
    buf = jax.lax.dynamic_update_slice(buf, tile_beams(prompt_tokens), (0, 0))
    buf = buf.at[:, s].set(first[1].reshape(b * k))
    scores = first[0].reshape(b * k)  # cumulative log-prob per beam
    tok = first[1].reshape(b * k)

    def step(carry, _):
        buf, cache, tok, cur, scores = carry
        logits, upd = model.apply(
            {**variables, "cache": cache},
            tok[:, None],
            position_ids=cur[None, None],
            cache_len=total,
            decode_step=True,
            mutable=["cache"],
        )
        logp = jax.nn.log_softmax(logits[:, 0, :].astype(jnp.float32), -1)
        # (b, k*vocab) joint scores; top-k per batch row
        joint = (scores[:, None] + logp).reshape(b, k * vocab)
        best, flat_idx = jax.lax.top_k(joint, k)  # (b, k)
        src_beam = flat_idx // vocab              # which beam it extends
        nxt = (flat_idx % vocab).reshape(b * k)
        rows = (jnp.arange(b)[:, None] * k + src_beam).reshape(b * k)
        # follow the winning beams: reorder history, cache, and scores
        buf = jnp.take(buf, rows, axis=0)
        cache = jax.tree_util.tree_map(
            lambda x: jnp.take(x, rows, axis=0) if x.ndim else x,
            upd["cache"],
        )
        buf = jax.lax.dynamic_update_slice(
            buf, nxt[:, None].astype(buf.dtype), (0, cur + 1)
        )
        return (buf, cache, nxt.astype(tok.dtype), cur + 1,
                best.reshape(b * k)), None

    if max_new_tokens > 1:
        (buf, _, _, _, scores), _ = jax.lax.scan(
            step, (buf, cache, tok, jnp.int32(s), scores), None,
            length=max_new_tokens - 1,
        )
    # HF's BeamHypotheses normalizes by the FULL hypothesis length
    # (prompt + generated), not just the generated span — all beams share
    # one length here so ranking is unaffected, but the reported scores
    # match HF's convention only with the full length.
    norm = scores / (total ** length_penalty)
    # beams are already best-first per batch row (top_k sorts descending)
    return buf.reshape(b, k, total), norm.reshape(b, k)
