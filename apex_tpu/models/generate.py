"""Autoregressive decoding for the causal LMs.

Extension beyond the reference (apex has no inference path); kept
deliberately simple and jit-correct: a fixed-size token buffer is filled
one position per scan step and the model recomputes the full prefix each
step (O(S^2) per sequence — evaluation/demo grade, not a serving engine).
Causality makes the garbage beyond the current length invisible to the
logits that matter, so no masking bookkeeping is needed.

Parity: tests/test_hf_parity.py pins greedy continuations against HF
``generate(do_sample=False)`` on the same imported weights.
"""

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["generate"]


def generate(
    model,
    variables,
    prompt_tokens,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
):
    """Continue ``prompt_tokens`` ((b, s) int32) by ``max_new_tokens``.

    ``temperature == 0``: greedy argmax. Otherwise softmax sampling at the
    given temperature using ``rng``. Returns (b, s + max_new_tokens).
    """
    b, s = prompt_tokens.shape
    total = s + max_new_tokens
    max_pos = getattr(getattr(model, "config", None), "max_position_embeddings", None)
    if max_pos is not None and total > max_pos:
        # out-of-range positions would be silently CLAMPED by the gather
        # (jnp.take clips), yielding garbage continuations — fail loudly
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"model's max_position_embeddings ({max_pos})"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # unused by greedy; keeps the scan uniform

    buf = jnp.zeros((b, total), prompt_tokens.dtype)
    buf = jax.lax.dynamic_update_slice(buf, prompt_tokens, (0, 0))

    def step(carry, _):
        buf, cur, key = carry
        logits = model.apply(variables, buf)  # (b, total, vocab)
        # the next token comes from position cur-1 (causal: positions >= cur
        # hold garbage but cannot influence it)
        next_logits = jax.lax.dynamic_slice_in_dim(
            logits, cur - 1, 1, axis=1
        )[:, 0, :].astype(jnp.float32)
        key, sub = jax.random.split(key)
        if temperature > 0.0:
            nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(next_logits, axis=-1)
        nxt = nxt.astype(buf.dtype)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, cur))
        return (buf, cur + 1, key), None

    (buf, _, _), _ = jax.lax.scan(
        step, (buf, jnp.int32(s), rng), None, length=max_new_tokens
    )
    return buf
