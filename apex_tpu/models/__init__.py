"""Flagship models (ref: apex/transformer/testing/standalone_{gpt,bert}.py,
examples/imagenet) re-built TPU-native on the apex_tpu transformer stack."""

from apex_tpu.models.gpt import GPTModel, gpt_loss_fn
from apex_tpu.models.generate import generate
from apex_tpu.models.hf_import import (
    gpt2_from_hf,
    llama_from_hf,
    mistral_from_hf,
    params_to_hf_gpt2,
    params_to_hf_llama,
)
from apex_tpu.models.bert import BertModel
from apex_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
    cross_entropy_loss,
)

__all__ = [
    "GPTModel",
    "generate",
    "gpt2_from_hf",
    "llama_from_hf",
    "mistral_from_hf",
    "params_to_hf_gpt2",
    "params_to_hf_llama",
    "BertModel",
    "gpt_loss_fn",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "cross_entropy_loss",
]
