"""ResNet for ImageNet, TPU-native (NHWC, bf16-friendly).

Reference parity: the reference ships no ResNet source but its flagship
example trains torchvision ResNet-50 under amp O0-O3
(/root/reference/examples/imagenet/main_amp.py:157-172) and the L1 tier
compares RN50 convergence traces across opt levels
(/root/reference/tests/L1/common/run_test.sh:20-49). This module provides
the model those flows need, built the TPU way:

- NHWC layout (XLA's native conv layout on TPU; the reference's
  channels_last flag, main_amp.py:116-130, is the CUDA analogue);
- BatchNorm via :class:`apex_tpu.parallel.SyncBatchNorm` so the same model
  runs single-chip (``bn_axes=()``) or data-parallel with synchronized
  statistics (``bn_axes=('dp',)`` ≙ apex.parallel.convert_syncbn_model,
  parallel/__init__.py:21);
- compute dtype is a constructor arg; parameters always live fp32 and are
  cast per-call, so amp O2 (bf16 compute + fp32 master params) is the
  natural mode.
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.parallel.sync_batch_norm import SyncBatchNorm

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    features: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.features, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.features, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.features * 4, (1, 1))(y)
        # zero-init of the last BN scale (torchvision zero_init_residual /
        # the standard ImageNet recipe) helps early-training stability
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    features: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.features, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.features, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet over NHWC images.

    ``bn_axes``: mesh axes for synchronized BN statistics (() = local BN).
    ``dtype``: compute dtype (bf16 for amp O2/O3); params stay fp32.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    bn_axes: Sequence[str] = ()
    bn_momentum: float = 0.1
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        # BN statistics are always computed fp32 (SyncBatchNorm contract);
        # the keep_batchnorm_fp32 rule of amp O2 is therefore structural.
        norm = partial(
            SyncBatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            axis_names=tuple(self.bn_axes),
            dtype=self.dtype,
        )

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    features=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)


def cross_entropy_loss(logits, labels, label_smoothing: float = 0.0):
    """Softmax CE over class logits (main_amp.py uses nn.CrossEntropyLoss)."""
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    return jnp.mean(softmax_cross_entropy_loss(logits, labels, label_smoothing))
