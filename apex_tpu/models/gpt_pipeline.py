"""Pipelined-GPT building blocks: pre/stage/post functions for the
compiled pipeline schedule.

Reference parity: the model side of build_model + forward_step
(schedules/common.py:30,253) — the reference splits its GPT into
pre_process (embedding), per-stage transformer chunks, and post_process
(final LN + head + loss). One shared implementation here feeds the tests,
the driver dryrun, and the examples, including the two SP subtleties:

- the Embedding module already scatters its output to the SP region
  (models/gpt.py) — pre_fn must NOT scatter again;
- under SP each tp rank scores only its sequence shard, so the replicated
  post params (final norm + head) receive tp-PARTIAL grads; routing them
  through ``copy_to_tensor_model_parallel_region`` (identity forward,
  psum backward) completes them — the same mechanism Norm uses for its
  SP-sharded scale/bias (transformer/layer.py Norm).
"""

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import Embedding
from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.parallel.layers import _tp_size
from apex_tpu.parallel.mappings import copy_to_tensor_model_parallel_region
from apex_tpu.transformer import ParallelTransformer, TransformerConfig


class GPTPipelineParts(NamedTuple):
    embed: Any
    chunk: Any
    pre_fn: Callable
    stage_fn: Callable
    post_loss_fn: Callable
    init_post: Callable


def build_gpt_pipeline(cfg: TransformerConfig, pp: int) -> GPTPipelineParts:
    """Modules + pure functions for ``forward_backward_with_pre_post``.

    The stack is split as: Embedding (pre, replicated over pp) →
    ``num_layers/pp`` transformer layers per stage → final LayerNorm +
    untied vocab head + token-mean CE (post, replicated over pp).
    """
    if cfg.num_layers % pp != 0:
        raise ValueError(f"num_layers ({cfg.num_layers}) not divisible by pp ({pp})")
    embed = Embedding(config=cfg)
    chunk = ParallelTransformer(
        config=cfg, num_layers=cfg.num_layers // pp, post_layer_norm=False
    )

    def pre_fn(pre_params, tokens_mb):
        # Embedding handles the SP scatter internally (models/gpt.py)
        return embed.apply({"params": pre_params}, tokens_mb)

    def stage_fn(chunk_params, h):
        return chunk.apply({"params": chunk_params}, h)

    def post_loss_fn(post_params, y, labels_mb):
        tp = _tp_size(cfg.tensor_axis)
        sp = cfg.sequence_parallel and tp > 1
        scale = post_params["norm_scale"]
        bias = post_params["norm_bias"]
        head = post_params["head"]
        lab = labels_mb
        if sp:
            # replicated post params see tp-partial grads under SP:
            # identity-forward/psum-backward completes them
            scale = copy_to_tensor_model_parallel_region(scale, cfg.tensor_axis)
            bias = copy_to_tensor_model_parallel_region(bias, cfg.tensor_axis)
            head = copy_to_tensor_model_parallel_region(head, cfg.tensor_axis)
            r = jax.lax.axis_index(cfg.tensor_axis)
            lab = jax.lax.dynamic_slice_in_dim(
                labels_mb, r * y.shape[0], y.shape[0], axis=1
            )
        h = layer_norm(
            y, scale.astype(jnp.float32), bias.astype(jnp.float32)
        ).astype(y.dtype)
        logits = jnp.transpose(jnp.einsum("sbh,hv->sbv", h, head), (1, 0, 2))
        loss = jnp.mean(softmax_cross_entropy_loss(logits, lab))
        # under SP: local-mean / tp — the SPMD sum across tp ranks
        # differentiates to the global token mean
        return loss / tp if sp else loss

    def init_post(key):
        return {
            "norm_scale": jnp.ones((cfg.hidden_size,)),
            "norm_bias": jnp.zeros((cfg.hidden_size,)),
            "head": 0.05
            * jax.random.normal(key, (cfg.hidden_size, cfg.vocab_size)),
        }

    return GPTPipelineParts(embed, chunk, pre_fn, stage_fn, post_loss_fn, init_post)
