"""HuggingFace GPT-2 weight import.

Proves (and provides) functional interchangeability: a GPT-2 checkpoint in
the transformers format maps onto ``apex_tpu.models.GPTModel`` exactly —
same logits to fp32 tolerance (tests/test_hf_parity.py).  The reference's
Megatron-style GPT (testing/standalone_gpt.py) is architecture-identical to
GPT-2 (pre-LN, tanh-gelu, learned positions, tied embeddings); the only
differences are packing/layout conventions, handled here:

- HF ``c_attn`` packs [Q_all | K_all | V_all] over full hidden blocks;
  Megatron's fused ``query_key_value`` packs per head: [q_0 k_0 v_0 | q_1
  k_1 v_1 | ...] so the TP reshape (s, b, heads_local, 3*head_dim) works.
- HF Conv1D stores (in, out) kernels — the same orientation as our flax
  ``kernel``s, so no transposes beyond the qkv regroup.
"""

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np


def config_from_hf_gpt2(hf_config, **overrides):
    """TransformerConfig matching a transformers.GPT2Config."""
    from apex_tpu.transformer import TransformerConfig

    if getattr(hf_config, "activation_function", "gelu_new") not in (
        "gelu_new", "gelu_pytorch_tanh",
    ):
        raise ValueError(
            f"GPT2 activation {hf_config.activation_function!r} not the "
            "tanh-gelu this mapping assumes"
        )
    kw = dict(
        num_layers=hf_config.n_layer,
        hidden_size=hf_config.n_embd,
        num_attention_heads=hf_config.n_head,
        vocab_size=hf_config.vocab_size,
        max_position_embeddings=hf_config.n_positions,
        layernorm_epsilon=hf_config.layer_norm_epsilon,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        activation="gelu",  # _activate uses the tanh approximation == gelu_new
        position_embedding_type="learned",
        share_embeddings_and_output_weights=True,
        apply_query_key_layer_scaling=False,
        # checkpoint-parity default: the HF model computes fp32; override
        # with compute_dtype=jnp.bfloat16 for TPU-rate inference/training
        compute_dtype=jnp.float32,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _regroup_qkv(w_qkv: np.ndarray, heads: int) -> np.ndarray:
    """[Q|K|V] full-hidden blocks -> per-head [q k v] blocks.

    Works for both kernels (h, 3h) and biases (3h,): the leading dims are
    untouched, only the last axis is regrouped.
    """
    *lead, three_h = w_qkv.shape
    h = three_h // 3
    hn = h // heads
    q, k, v = np.split(w_qkv, 3, axis=-1)
    stack = np.stack(
        [x.reshape(*lead, heads, hn) for x in (q, k, v)], axis=-2
    )  # (*lead, heads, 3, hn)
    return stack.reshape(*lead, 3 * h)


def params_from_hf_gpt2(hf_model) -> Dict[str, Any]:
    """Map a transformers GPT2LMHeadModel/GPT2Model state onto GPTModel's
    param tree (tp=1 layout; shard with jax.device_put + NamedSharding for
    tp>1 — the per-head qkv packing already matches the TP split)."""
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    heads = hf_model.config.n_head

    def g(name):
        return sd[pfx + name]

    params: Dict[str, Any] = {
        "embedding": {
            "word_embeddings": {"embedding": jnp.asarray(g("wte.weight"))},
            "position_embeddings": jnp.asarray(g("wpe.weight")),
        },
        "transformer": {
            "final_layernorm": {
                "scale": jnp.asarray(g("ln_f.weight")),
                "bias": jnp.asarray(g("ln_f.bias")),
            },
        },
    }
    for i in range(hf_model.config.n_layer):
        L = f"h.{i}."
        params["transformer"][f"layer_{i}"] = {
            "input_layernorm": {
                "scale": jnp.asarray(g(L + "ln_1.weight")),
                "bias": jnp.asarray(g(L + "ln_1.bias")),
            },
            "post_attention_layernorm": {
                "scale": jnp.asarray(g(L + "ln_2.weight")),
                "bias": jnp.asarray(g(L + "ln_2.bias")),
            },
            "self_attention": {
                "query_key_value": {
                    "kernel": jnp.asarray(
                        _regroup_qkv(g(L + "attn.c_attn.weight"), heads)
                    ),
                    "bias": jnp.asarray(
                        _regroup_qkv(g(L + "attn.c_attn.bias"), heads)
                    ),
                },
                "dense": {
                    "kernel": jnp.asarray(g(L + "attn.c_proj.weight")),
                    "bias": jnp.asarray(g(L + "attn.c_proj.bias")),
                },
            },
            "mlp": {
                "dense_h_to_4h": {
                    "kernel": jnp.asarray(g(L + "mlp.c_fc.weight")),
                    "bias": jnp.asarray(g(L + "mlp.c_fc.bias")),
                },
                "dense_4h_to_h": {
                    "kernel": jnp.asarray(g(L + "mlp.c_proj.weight")),
                    "bias": jnp.asarray(g(L + "mlp.c_proj.bias")),
                },
            },
        }
    return params


def gpt2_from_hf(hf_model, **config_overrides) -> Tuple[Any, Dict[str, Any]]:
    """(GPTModel, params) functionally equal to the given HF GPT-2."""
    from apex_tpu.models import GPTModel

    cfg = config_from_hf_gpt2(hf_model.config, **config_overrides)
    return GPTModel(config=cfg), {"params": params_from_hf_gpt2(hf_model)}


def _ungroup_qkv(w_packed: np.ndarray, heads: int) -> np.ndarray:
    """Inverse of _regroup_qkv: per-head [q k v] blocks -> [Q|K|V]."""
    *lead, three_h = w_packed.shape
    h = three_h // 3
    hn = h // heads
    stack = w_packed.reshape(*lead, heads, 3, hn)
    parts = [stack[..., :, j, :].reshape(*lead, h) for j in range(3)]
    return np.concatenate(parts, axis=-1)


def params_to_hf_gpt2(params, hf_model) -> None:
    """Load a GPTModel param tree back INTO an HF GPT-2 (in place) — the
    inverse of ``params_from_hf_gpt2``; round-trip tested."""
    import torch

    p = params.get("params", params)
    heads = hf_model.config.n_head

    def arr(x):
        return torch.from_numpy(np.ascontiguousarray(np.asarray(x)))

    sd = {}
    wte = np.asarray(p["embedding"]["word_embeddings"]["embedding"])
    sd["transformer.wte.weight"] = arr(wte)
    sd["transformer.wpe.weight"] = arr(p["embedding"]["position_embeddings"])
    sd["lm_head.weight"] = arr(wte)  # tied
    sd["transformer.ln_f.weight"] = arr(p["transformer"]["final_layernorm"]["scale"])
    sd["transformer.ln_f.bias"] = arr(p["transformer"]["final_layernorm"]["bias"])
    for i in range(hf_model.config.n_layer):
        lp = p["transformer"][f"layer_{i}"]
        L = f"transformer.h.{i}."
        sd[L + "ln_1.weight"] = arr(lp["input_layernorm"]["scale"])
        sd[L + "ln_1.bias"] = arr(lp["input_layernorm"]["bias"])
        sd[L + "ln_2.weight"] = arr(lp["post_attention_layernorm"]["scale"])
        sd[L + "ln_2.bias"] = arr(lp["post_attention_layernorm"]["bias"])
        sa = lp["self_attention"]
        sd[L + "attn.c_attn.weight"] = arr(
            _ungroup_qkv(np.asarray(sa["query_key_value"]["kernel"]), heads)
        )
        sd[L + "attn.c_attn.bias"] = arr(
            _ungroup_qkv(np.asarray(sa["query_key_value"]["bias"]), heads)
        )
        sd[L + "attn.c_proj.weight"] = arr(sa["dense"]["kernel"])
        sd[L + "attn.c_proj.bias"] = arr(sa["dense"]["bias"])
        sd[L + "mlp.c_fc.weight"] = arr(lp["mlp"]["dense_h_to_4h"]["kernel"])
        sd[L + "mlp.c_fc.bias"] = arr(lp["mlp"]["dense_h_to_4h"]["bias"])
        sd[L + "mlp.c_proj.weight"] = arr(lp["mlp"]["dense_4h_to_h"]["kernel"])
        sd[L + "mlp.c_proj.bias"] = arr(lp["mlp"]["dense_4h_to_h"]["bias"])
    missing, unexpected = hf_model.load_state_dict(sd, strict=False)
    if unexpected:
        raise ValueError(f"unexpected keys in export: {unexpected}")


# ---------------------------------------------------------------------------
# Llama family
# ---------------------------------------------------------------------------


def config_from_hf_llama(hf_config, **overrides):
    """TransformerConfig matching a transformers.LlamaConfig.

    Llama == GPTModel with rmsnorm + rotate-half RoPE (same convention as
    ops/rope.py, so weights map with NO head permutation) + SwiGLU +
    bias-free linears + GQA + untied output head.
    """
    from apex_tpu.transformer import TransformerConfig

    kw = dict(
        num_layers=hf_config.num_hidden_layers,
        hidden_size=hf_config.hidden_size,
        num_attention_heads=hf_config.num_attention_heads,
        num_query_groups=hf_config.num_key_value_heads,
        # explicit head_dim (Mistral-Nemo style) may differ from hidden/heads
        kv_channels=getattr(hf_config, "head_dim", None),
        ffn_hidden_size=hf_config.intermediate_size,
        vocab_size=hf_config.vocab_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        layernorm_epsilon=hf_config.rms_norm_eps,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        normalization="rmsnorm",
        activation="swiglu",
        add_bias_linear=False,
        position_embedding_type="rope",
        rotary_base=getattr(hf_config, "rope_theta", 10000.0),
        share_embeddings_and_output_weights=bool(
            getattr(hf_config, "tie_word_embeddings", False)
        ),
        apply_query_key_layer_scaling=False,
        compute_dtype=jnp.float32,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def params_from_hf_llama(hf_model) -> Dict[str, Any]:
    """Map a transformers LlamaForCausalLM onto GPTModel's param tree.

    Packing transforms (torch Linear stores (out, in); ours store (in, out)):
    - k_proj/v_proj -> one fused ``key_value`` kernel packed per kv group as
      [k_g | v_g] (the (s,b,g,2*hn) split in ParallelAttention);
    - gate_proj/up_proj -> one ``dense_h_to_4h`` kernel packed [gate | up]
      (_activate's swiglu split);
    - rotate-half RoPE matches ops/rope.py directly — no qk permutation.
    """
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    cfg = hf_model.config
    heads, g = cfg.num_attention_heads, cfg.num_key_value_heads
    hn = getattr(cfg, "head_dim", None) or cfg.hidden_size // heads
    kw = sd["model.layers.0.self_attn.k_proj.weight"]
    if kw.shape[0] != g * hn:
        raise ValueError(
            f"k_proj out dim {kw.shape[0]} != kv_heads*head_dim {g}*{hn} — "
            "unexpected head layout for the llama/mistral mapping"
        )

    def g_(name):
        return sd["model." + name]

    def lin(w):  # (out, in) -> (in, out)
        return jnp.asarray(np.ascontiguousarray(w.T))

    params: Dict[str, Any] = {
        "embedding": {
            "word_embeddings": {"embedding": jnp.asarray(g_("embed_tokens.weight"))},
        },
        "transformer": {
            "final_layernorm": {"scale": jnp.asarray(g_("norm.weight"))},
        },
    }
    if not getattr(cfg, "tie_word_embeddings", False):
        params["output_layer"] = {"kernel": lin(sd["lm_head.weight"])}
    for i in range(cfg.num_hidden_layers):
        L = f"layers.{i}."
        wk = g_(L + "self_attn.k_proj.weight").T  # (h, g*hn)
        wv = g_(L + "self_attn.v_proj.weight").T
        kv = np.stack(
            [wk.reshape(-1, g, hn), wv.reshape(-1, g, hn)], axis=2
        ).reshape(-1, 2 * g * hn)  # per-group [k_g | v_g]
        params["transformer"][f"layer_{i}"] = {
            "input_layernorm": {
                "scale": jnp.asarray(g_(L + "input_layernorm.weight")),
            },
            "post_attention_layernorm": {
                "scale": jnp.asarray(g_(L + "post_attention_layernorm.weight")),
            },
            "self_attention": {
                "query": {"kernel": lin(g_(L + "self_attn.q_proj.weight"))},
                "key_value": {"kernel": jnp.asarray(np.ascontiguousarray(kv))},
                "dense": {"kernel": lin(g_(L + "self_attn.o_proj.weight"))},
            },
            "mlp": {
                "dense_h_to_4h": {
                    "kernel": jnp.concatenate(
                        [lin(g_(L + "mlp.gate_proj.weight")),
                         lin(g_(L + "mlp.up_proj.weight"))], axis=1
                    )
                },
                "dense_4h_to_h": {
                    "kernel": lin(g_(L + "mlp.down_proj.weight")),
                },
            },
        }
    return params


def llama_from_hf(hf_model, **config_overrides) -> Tuple[Any, Dict[str, Any]]:
    """(GPTModel, params) functionally equal to the given HF Llama — or
    Mistral: same weight schema, plus sliding-window attention when the HF
    config carries a ``sliding_window``."""
    from apex_tpu.models import GPTModel

    window = getattr(hf_model.config, "sliding_window", None)
    if window is not None:
        config_overrides.setdefault("attention_window", window)
    cfg = config_from_hf_llama(hf_model.config, **config_overrides)
    return GPTModel(config=cfg), {"params": params_from_hf_llama(hf_model)}


# same schema (mistral = llama weights + sliding window)
mistral_from_hf = llama_from_hf


def params_to_hf_llama(params, hf_model) -> None:
    """Load a GPTModel llama-style param tree back INTO ``hf_model``
    (in place) — the inverse of ``params_from_hf_llama``, so models trained
    here round-trip to the transformers ecosystem.

    ``params`` is the {'params': ...} variables dict or its inner tree.
    """
    import torch

    p = params.get("params", params)
    cfg = hf_model.config
    heads, g = cfg.num_attention_heads, cfg.num_key_value_heads
    hn = getattr(cfg, "head_dim", None) or cfg.hidden_size // heads
    ffn = cfg.intermediate_size

    def t(x):  # (in, out) kernel -> torch Linear (out, in)
        return torch.from_numpy(np.ascontiguousarray(np.asarray(x).T))

    sd = {}
    sd["model.embed_tokens.weight"] = torch.from_numpy(
        np.asarray(p["embedding"]["word_embeddings"]["embedding"])
    )
    sd["model.norm.weight"] = torch.from_numpy(
        np.asarray(p["transformer"]["final_layernorm"]["scale"])
    )
    if "output_layer" in p:
        sd["lm_head.weight"] = t(p["output_layer"]["kernel"])
    for i in range(cfg.num_hidden_layers):
        lp = p["transformer"][f"layer_{i}"]
        L = f"model.layers.{i}."
        sd[L + "input_layernorm.weight"] = torch.from_numpy(
            np.asarray(lp["input_layernorm"]["scale"])
        )
        sd[L + "post_attention_layernorm.weight"] = torch.from_numpy(
            np.asarray(lp["post_attention_layernorm"]["scale"])
        )
        sd[L + "self_attn.q_proj.weight"] = t(lp["self_attention"]["query"]["kernel"])
        kv = np.asarray(lp["self_attention"]["key_value"]["kernel"])
        kv = kv.reshape(-1, g, 2, hn)  # undo per-group [k_g | v_g]
        sd[L + "self_attn.k_proj.weight"] = t(kv[:, :, 0, :].reshape(-1, g * hn))
        sd[L + "self_attn.v_proj.weight"] = t(kv[:, :, 1, :].reshape(-1, g * hn))
        sd[L + "self_attn.o_proj.weight"] = t(lp["self_attention"]["dense"]["kernel"])
        h4 = np.asarray(lp["mlp"]["dense_h_to_4h"]["kernel"])  # (h, 2*ffn)
        sd[L + "mlp.gate_proj.weight"] = t(h4[:, :ffn])
        sd[L + "mlp.up_proj.weight"] = t(h4[:, ffn:])
        sd[L + "mlp.down_proj.weight"] = t(lp["mlp"]["dense_4h_to_h"]["kernel"])
    missing, unexpected = hf_model.load_state_dict(sd, strict=False)
    # rotary inv_freq buffers etc. may be "missing" (they are derived);
    # anything unexpected means the mapping drifted
    if unexpected:
        raise ValueError(f"unexpected keys in export: {unexpected}")
