"""The repo's documented allowlist: every intentional flagged construct.

This file is the single home of "yes, we mean it" for the static
auditors. EVERY entry carries its numerical/engineering reason — the
:class:`~apex_tpu.analysis.findings.AllowlistEntry` constructor rejects
bare entries — and lint-scope entries (``require_hit=True``) go stale
loudly when the construct they document disappears.

Organization: precision entries first (why each wide-dtype island in a
bf16 step is intentional), then collective-safety, then the compiled-HLO
comms entries, then the sharding/autofix entries, then the source-lint
entries, then the concurrency entries (every hand-proof the static
race/deadlock analyzer's findings rest on — the lock-free handshakes,
the deliberate blocking-under-lock sites, the audited teardown
handlers).
When the precision auditor flags a NEW site, the choice is binary: fix
the promotion, or add an entry HERE with the reason a reviewer can
check. See docs/analysis.md.
"""

from apex_tpu.analysis.findings import Allowlist, AllowlistEntry

__all__ = ["REPO_ALLOWLIST", "repo_allowlist"]

_PRECISION = [
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/ops/layer_norm.py",
        reason=(
            "norm statistics in f32: mean/variance of bf16 activations "
            "(~1e-3 squared terms) lose all significance in an 8-bit "
            "mantissa; the kernel reduces in f32 and casts back (the "
            "reference's AffineMixedDtypes contract)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/transformer/layer.py",
        reason=(
            "norm affine params cast to f32 for the f32 norm kernels, "
            "and their grad transposes back into low-precision masters "
            "when params_dtype is bf16 — the activation upcast that used "
            "to live in _activate was a REAL finding and was fixed, not "
            "allowlisted"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/ops/attention.py",
        reason=(
            "softmax statistics in f32: bf16 exp/sum over long rows "
            "overflows and loses the max-subtraction guard; scores and "
            "probabilities are f32, the context matmul returns to bf16 "
            "(flash-attention's accumulator contract)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/ops/softmax.py",
        reason=(
            "same softmax-statistics-in-f32 contract as ops/attention.py "
            "for the standalone fused softmax"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/parallel/layers.py",
        reason=(
            "master-weight casts: kernels/biases/embeddings are stored "
            "f32 (params_dtype) and cast to the compute dtype per use; "
            "the flagged bf16->f32 converts are the TRANSPOSES of those "
            "casts — gradients accumulating back into f32 masters, the "
            "whole point of O2 mixed precision"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/models/gpt.py",
        reason=(
            "embedding-output cast to compute dtype: its transpose "
            "accumulates embedding gradients in f32 — same master-weight "
            "contract as parallel/layers.py"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/models/bert.py",
        reason=(
            "BERT head/pooler params are f32 masters cast to compute "
            "dtype; flagged converts are the f32 gradient transposes"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/parallel/cross_entropy.py",
        reason=(
            "vocab-parallel CE computes logits stats (max, sum-exp, "
            "target logit) in f32: bf16 logsumexp over a 32k-vocab row "
            "is catastrophically lossy and the psum'ed partials must "
            "not saturate"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/parallel/ddp.py",
        reason=(
            "gradient allreduce in f32: summing N bf16 gradient replicas "
            "in bf16 loses low-order contributions exactly when N is "
            "large; the psum runs on f32 and casts back"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/parallel/ring_attention.py",
        reason=(
            "ring/blockwise attention carries f32 running max/sum/output "
            "accumulators across ring steps (the online-softmax "
            "recurrence is unstable in bf16)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/parallel/sync_batch_norm.py",
        reason=(
            "cross-replica batch-norm statistics in f32 (variance via "
            "E[x^2]-E[x]^2 cancels catastrophically in bf16)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/transformer/moe.py",
        reason=(
            "router math in f32: expert logits/softmax/aux-loss need "
            "exact tie-breaking and the load-balancing loss is a mean of "
            "tiny products; dispatched expert outputs re-enter bf16"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/transformer/utils.py",
        reason=(
            "grad-norm / param-norm sums of squares in f32 (sum of many "
            "small squares underflows bf16), and average_losses stacks "
            "scalars in f32"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/optimizers/",
        reason=(
            "master-weight f32 accumulations: fused/distributed "
            "optimizers keep moments and master params in f32 and "
            "unscale bf16/f16 grads into f32 before the update (O2 "
            "semantics; ref apex FusedAdam master path)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/amp/",
        reason=(
            "the amp machinery's own unscale/master casts: grads are "
            "promoted to f32 exactly once at the optimizer boundary "
            "(grad_scaler.unscale, cast_engine master params)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/ops/xentropy.py",
        reason=(
            "fused cross-entropy logsumexp statistics in f32 (same "
            "contract as parallel/cross_entropy.py)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/resilience/sentinel.py",
        reason=(
            "anomaly-sentinel EMA/variance state is f32 by construction; "
            "a bf16 loss entering the z-score math is promoted once per "
            "step (a scalar)"
        ),
    ),
    AllowlistEntry(
        rule="precision.promotion",
        match="apex_tpu/monitor/metrics.py",
        reason=(
            "MetricBag folds scalars in f32 (interval means of bf16 "
            "losses would quantize visibly); one scalar per metric per "
            "step"
        ),
    ),
]

_COLLECTIVE = [
    AllowlistEntry(
        rule="collective.dead-traffic",
        match="apex_tpu/amp/grad_scaler.py",
        reason=(
            "found_inf psum over a possibly-size-1 model-parallel axis "
            "is replication-ESTABLISHING, not traffic: XLA elides the "
            "size-1 reduce (zero bytes) but checked shard_map "
            "(check_rep/check_vma=True) relies on the psum to type the "
            "result replicated — gating it on axis size breaks "
            "out_specs inference on degenerate tp=1/pp=1 meshes "
            "(verified by repro)"
        ),
    ),
]

_COMMS = [
    # The HLO comms differ (analysis/hlo/comms_diff.py) cross-checks
    # XLA's emitted collectives against the xray ledger's trace-time
    # prediction. The known transpose-derived BACKWARD collectives — the
    # reversed mates of the TP gather/scatter mappings, sited by XLA at
    # the forward call sites in parallel/layers.py, models/gpt.py and
    # transformer/layer.py — are PREDICTED (the mappings' custom_vjp
    # pairs run their collectives through the ledger wrappers, PR-3) and
    # therefore match; they need no entries, and adding any would hide a
    # future regression that drops the custom_vjp pairing. What remains
    # is the one legitimate divergence XLA creates on its own:
    AllowlistEntry(
        rule="comms.folded",
        match="<step:*",
        reason=(
            "XLA legitimately emits FEWER reductions than traced: CSE "
            "folds byte-identical psums (the duplicated vocab-parallel "
            "CE stats over tp) and reassociation turns per-microbatch "
            "grad psums into one post-sum all-reduce — info-severity "
            "bookkeeping, suppressed here so the gate's record stream "
            "stays fully explained"
        ),
    ),
    AllowlistEntry(
        rule="comms.quantized",
        match="<step:*",
        reason=(
            "POSITIVE confirmation, not a defect: the differ verified "
            "8-bit-payload collectives (the parallel/compress.py "
            "quantized decomposition on the gpt-dp2tp2-int8 target) "
            "matched ledger predictions — recorded here so the gate's "
            "jsonl stays fully explained (every record allowlisted with "
            "a reason); the pattern's PRESENCE is separately pinned by "
            "tests/test_compress.py::TestLedgerPin, so suppressing it "
            "cannot hide a regression"
        ),
    ),
    AllowlistEntry(
        rule="comms.async",
        match="<step:*",
        reason=(
            "POSITIVE confirmation, not a defect: the differ verified "
            "that ledger-matched collectives were emitted as async "
            "-start/-done pairs (the overlap-aware schedules' proof "
            "loop: prefetched ZeRO param gathers, zero-bubble p2p "
            "edges) — recorded so the gate's jsonl stays fully "
            "explained. Backend-dependent by design: CPU XLA emits "
            "sync collectives, so the finding fires on TPU compiles "
            "only; the mechanism itself is pinned on synthetic async "
            "HLO by tests/test_analysis.py"
        ),
    ),
    # NO comms.vanished entry: nothing vanishes on the repo targets today
    # (CSE shortfalls are partial, so they land in comms.folded above),
    # and a whole predicted bucket disappearing — e.g. the dp grad
    # all-reduce going dead — is exactly the regression the differ
    # exists to catch. Allowlist matches on site, and vanished findings
    # all share the target's step site, so any entry here would mute
    # EVERY vanished bucket for the target, not one known case.
]

_SHARDING = [
    AllowlistEntry(
        rule="sharding.unverifiable",
        match="<hlo:*",
        reason=(
            "CPU jit compiles leave the entry ROOT without sharding "
            "annotations (GSPMD only stamps result shardings when a "
            "device assignment forces them), so output replication is "
            "honestly NOT audited on the CPU gate — recorded instead of "
            "silently skipped (degrade-loudly). The PARAM half of the "
            "audit still runs (entry parameters always carry shardings) "
            "and tests/test_autofix.py pins that the rule fires on the "
            "seeded naive target, so suppressing the info record cannot "
            "hide the auditor going blind"
        ),
    ),
    AllowlistEntry(
        rule="autofix.prescription",
        match="*",
        reason=(
            "a prescription is the FIX, not a defect: the defect it "
            "fixes (sharding.replicated-param, donation.missed, "
            "comms.reshard) is already on the stream under its own "
            "rule, and --fix exits nonzero itself when prescriptions "
            "remain unapplied or the apply is not idempotent — the "
            "info record exists so the jsonl carries the machine-"
            "applicable fix= payload"
        ),
    ),
]

_HBM = [
    AllowlistEntry(
        rule="memory.reconciled",
        match="<step:*",
        reason=(
            "POSITIVE confirmation, not a defect: the hlo-memory differ "
            "reconciled every resident component of the analytic ledger "
            "exactly against memory_analysis() (params + optimizer state "
            "digit-for-digit on the gpt targets) with temps inside the "
            "declared band — recorded so the gate's jsonl stays fully "
            "explained; the exact byte pins live in "
            "tests/test_memory_diff.py, so suppressing the info record "
            "cannot hide a regression"
        ),
    ),
    AllowlistEntry(
        rule="memory.overpredicted",
        match="<step:*",
        reason=(
            "model pessimism is information, not a defect: XLA aliasing "
            "or rematerializing bytes the ledger booked means the "
            "feasibility oracle over-refuses by the reported delta — "
            "worth reading, never worth failing the gate"
        ),
    ),
    AllowlistEntry(
        rule="memory.unverifiable",
        match="<step:*",
        reason=(
            "the bert, pipeline and autofix (gpt-zero-naive) targets "
            "carry no analytic ledger yet (StepTarget.hbm is None — "
            "their closed forms are ROADMAP follow-ups); the differ "
            "says so honestly instead of skipping. The gpt targets DO reconcile, and the examples' "
            "--xray-hbm treats unverifiable as NOT ok, so this cannot "
            "mask a platform that stops reporting memory_analysis()"
        ),
    ),
    # NO memory.unpredicted or memory.headroom entries: an argument
    # component the ledger cannot account for is a model bug to fix,
    # and a headroom breach is a capacity decision — neither is ever
    # explained away here.
]

_LINT = [
    AllowlistEntry(
        rule="lint.raw-collective",
        match="apex_tpu/monitor/xray/ledger.py",
        reason=(
            "the ledger's wrappers ARE the instrumented call sites — the "
            "one place raw lax collectives are allowed to live"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.compressed-collective",
        match="apex_tpu/parallel/compress.py",
        reason=(
            "the audited home: compress.py IS the one place quantize/"
            "dequant may compose with ledgered collectives — it records "
            "the true wire payloads (int8 + fp32 scales) in the ledger, "
            "owns the error-feedback residual semantics, and carries the "
            "poisoned-scale found_inf contract the unit tests pin"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.prefetch-gather",
        match="apex_tpu/optimizers/distributed_fused_adam.py",
        reason=(
            "the blessed home: zero_prefetch_gather IS the bucketed "
            "param-gather pipeline — its loop issues one ledgered "
            "all_gather per bucket by design, with overlap depth from "
            "choose_overlap_buckets (the ICI roofline) and an exact "
            "reconstruction transpose; both ZeRO optimizers route "
            "through it so the three invariants live once"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.hlo-text",
        match="apex_tpu/analysis/hlo/parser.py",
        reason=(
            "the parser is the single HLO-scraping home: module_text() "
            "is the one blessed .as_text() call; every other consumer "
            "hands the Lowered/Compiled object to the shared, "
            "nesting-safe parse functions"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.memory-api",
        match="apex_tpu/monitor/xray/hbm/",
        reason=(
            "the hbm package IS the blessed memory-API home: live.py's "
            "device_watermarks() is the one .memory_stats() call site "
            "and report.py's report_from_compiled() the one "
            ".memory_analysis() call site — every other consumer routes "
            "through them so None-vs-fake-zero has one convention"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.trace-file",
        match="apex_tpu/monitor/xray/timeline/",
        reason=(
            "the timeline package IS the blessed trace-event reader: the "
            "parser's suffix constants, glob messages, and format "
            "docstrings are the one place the trace-event filename "
            "marker may live (the lint.hlo-text/parser.py contract, "
            "applied to XProf's export)"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.trace-file",
        match="apex_tpu/analysis/lint.py",
        reason=(
            "the rule's own home: its docstring, detection literal, and "
            "finding message necessarily spell the format marker they "
            "police"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.trace-file",
        match="apex_tpu/monitor/xray/__init__.py",
        reason=(
            "the xray package index DOCUMENTS the format by name while "
            "routing readers to the timeline parser — documentation of "
            "where to go, not an ad-hoc reader"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.span-phases",
        match="apex_tpu/monitor/goodput/spans.py",
        reason=(
            "the span ledger's own implementation: span()/begin_span() "
            "forward their (runtime-validated) phase argument into "
            "Span, and Span.close forwards self.phase into emit_span — "
            "the one module where a non-literal phase is the mechanism, "
            "not a taxonomy leak; Span.__init__ raises on any string "
            "outside PHASES"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.signal-handlers",
        match="apex_tpu/utils/autoresume.py",
        reason=(
            "blessed home #1: AutoResume's preemption handler (flag + "
            "grace-budget arrival timestamp only, no IO) and the "
            "close()-time restoration of the previous disposition — the "
            "registration every other preemption consumer must route "
            "through"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.signal-handlers",
        match="apex_tpu/monitor/router.py",
        reason=(
            "blessed home #2: the router teardown's best-effort SIGTERM "
            "span-flush hook, which installs only over SIG_DFL so "
            "AutoResume's handler keeps precedence and re-raises so the "
            "process still dies by SIGTERM"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.process-exit",
        match="apex_tpu/resilience/health/responder.py",
        reason=(
            "the ONE deliberate hard-exit home: the incident "
            "responder's coordinated self-termination must use "
            "os._exit because a wedged main thread can run neither "
            "signal handlers nor atexit hooks — the responder performs "
            "the teardown (span flush, pending-save tombstone) itself "
            "from the watchdog thread and then ends the process with "
            "ExitCode.INCIDENT; sys.exit would raise into a thread "
            "that cannot unwind"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.silent-except",
        match="apex_tpu/monitor/router.py",
        reason=(
            "the PR-7 teardown blanket guards (_flush_all_routers): the "
            "atexit/SIGTERM flush runs when the process is already dying "
            "and the sinks ARE the reporting channel — a raising flush "
            "hook or sink close would mask the real exit path, and there "
            "is nowhere left to log a failure durably"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.silent-except",
        match="apex_tpu/monitor/watchdog.py",
        reason=(
            "ProfilerTrigger.close's abort-capture guard: stop_trace on "
            "an already-torn capture raises backend-dependently at end "
            "of run, and the PR-6 contract is losing-a-trace-must-not-"
            "lose-the-run — the abort happens during shutdown where a "
            "warning would be noise about a capture nobody will read"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.jit-donate",
        match="apex_tpu/resilience/replay/targets.py",
        reason=(
            "audited entrypoint: the GPT example's train_step is now "
            "BUILT here (the one shared home the replayer rebuilds "
            "bit-identical steps from); its donation is verified by the "
            "donation auditor (--audit-donation and the example test)"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.jit-donate",
        match="examples/llama/finetune_llama.py",
        reason=(
            "audited entrypoint: the llama train step's params+opt-state "
            "donation is verified by the donation auditor "
            "(--audit-donation and the example test)"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.jit-donate",
        match="apex_tpu/serving/engine.py",
        reason=(
            "audited entrypoint: the serving engine's AOT-compiled "
            "prefill/decode steps donate the block-allocated KV pool "
            "(the whole point of the donated pytree: steady-state "
            "serving reuses one HBM allocation in place); realized "
            "donation is pinned empirically by the serving selftest "
            "gate — the pre-tick pool buffer must be deleted"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.jit-donate",
        match="apex_tpu/analysis/donation.py",
        reason=(
            "the donation auditor itself constructs the donating jit in "
            "order to introspect XLA's realized aliasing"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.jit-donate",
        match="apex_tpu/analysis/passes.py",
        reason=(
            "lower_step is the auditors' shared AOT lowering recipe: it "
            "constructs the donating jit whose realized aliasing the "
            "donation auditor and the compiled-HLO passes introspect"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.trace-emit",
        match="apex_tpu/serving/trace/emit.py",
        reason=(
            "the ONE blessed kind=\"trace\" construction site: "
            "TraceEmitter._emit is where every span record is built, so "
            "span ids, parent links, attempt tags and the start/dur_s "
            "schema stay consistent across engine, fleet and handoff "
            "emitters — the lint.raw-collective/ledger.py contract, "
            "applied to the request x-ray"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.trace-emit",
        match="apex_tpu/serving/trace/slo.py",
        reason=(
            "the ONE blessed kind=\"slo\" construction site: "
            "SLOMonitor.poll emits the burn-rate record after draining "
            "its tap, so window/violations/burn_rate/alert fields are "
            "computed in one place with the documented rolling-window "
            "semantics"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.nondeterminism",
        match="apex_tpu/resilience/retry.py",
        reason=(
            "the retry jitter home: (rng or random).random() de-"
            "stampedes a FLEET of hosts retrying the same flaky "
            "filesystem — host-side sleep scheduling only, never step "
            "math; callers needing determinism inject rng= (the tests "
            "do) or pass jitter=0 (the single-writer save path does)"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.nondeterminism",
        match="apex_tpu/monitor/router.py",
        reason=(
            "the record-timestamp home: make_record's time.time() is "
            "the shared schema's 't' field — metadata every record "
            "carries for human/log correlation, joined on 'step' (never "
            "on 't') and never an input to any computation; the replay "
            "comparisons ignore it by construction"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.thread-create",
        match="apex_tpu/monitor/watchdog.py",
        reason=(
            "a blessed thread home: the watchdog monitor loop and the "
            "escalation ladder OWN thread lifecycle — named daemon "
            "threads, stop-event + join(timeout) on close, and the "
            "ProfilerTrigger _state_lock handshake for cross-thread "
            "capture requests; both Thread sites here are the "
            "inventoried concurrency roots the analyzer audits"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.thread-create",
        match="apex_tpu/resilience/health/responder.py",
        reason=(
            "a blessed thread home: the hard-exit escalation timer — a "
            "daemon Thread that os._exit()s if the cooperative drain "
            "wedges, i.e. the one thread that must NOT share lifecycle "
            "discipline with anything it might be escalating past; its "
            "root is inventoried and its reach audited by the "
            "handler-safety pass"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="lint.thread-create",
        match="apex_tpu/utils/checkpoint.py",
        reason=(
            "a blessed thread home: finalize_async's single background "
            "finalizer thread, whose handle the autoresume save "
            "handshake tracks (wait() joins it before the manifest "
            "commit) — the identity-swap protocol the concurrency "
            "allowlist entry on autoresume.py documents"
        ),
        require_hit=True,
    ),
]

# ----------------------------------------------------------------------
# concurrency: the static race/deadlock analyzer's documented hand-proofs
# (apex_tpu/analysis/concurrency). Every entry quotes the invariant the
# flagged construct rests on; require_hit=True because the analyzer sees
# the whole package every run — change the code and the entry goes stale,
# forcing the proof to be re-made.
# ----------------------------------------------------------------------

_CONCURRENCY = [
    AllowlistEntry(
        rule="concurrency.unguarded-write",
        match="apex_tpu/utils/autoresume.py",
        reason=(
            "the documented lock-free handshakes (autoresume module "
            "docstring): (1) the _pending identity-swap — save() "
            "installs a fresh dict, the background finalizer commits "
            "only `if self._pending is pending` and clears only `if "
            "self._pending is pending`, so a newer save wins by "
            "identity, never by field mutation; (2) the GIL-atomic flag "
            "stores _signaled/_signal_t/_requested/_sigterm_t/"
            "_abandoned_step — single machine-word rebinds written by "
            "the signal handler or the finalizer thread and only READ "
            "(never read-modify-written) elsewhere. Both are "
            "deliberately lock-free: the writer is a signal handler "
            "(may not take locks — see concurrency.handler-unsafe) or "
            "a finalizer that must never block the step loop"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.blocking-under-lock",
        match="apex_tpu/_native.py",
        reason=(
            "the compile-once guard: _load() holds _LOCK across the "
            "g++ subprocess + atomic rename ON PURPOSE — the lock's "
            "whole job is making every other thread wait for the ONE "
            "build instead of racing N compilers at the same .so; the "
            "per-pid temp + os.replace keeps an interrupted build from "
            "poisoning the mtime cache, and _LOCK nests nothing (leaf "
            "lock, no cycle possible)"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.blocking-under-lock",
        match="apex_tpu/monitor/router.py",
        reason=(
            "the sink fan-out IS the lock's purpose: MetricRouter._lock "
            "exists to serialize emit() against close() so a record "
            "never lands on a half-torn sink list; sink.emit under it "
            "is the invariant, not a bug. The lock is reentrant "
            "(RLock) and LEAF in the repo's order — no sink calls back "
            "into the router — so it can stall, never deadlock"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.blocking-under-lock",
        match="apex_tpu/resilience/remediation/",
        reason=(
            "the controller's one-way lock order: controller._lock -> "
            "router._lock (via _emit's router.event) and never the "
            "reverse — the router knows nothing about the controller, "
            "so the order cannot invert and the pair cannot cycle. The "
            "state.py makedirs/rename under the same lock is the "
            "persist-atomicity contract: the decision and its durable "
            "record must be one critical section, or a crash between "
            "them replays a restart budget it already spent"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.unbounded-wait",
        match="apex_tpu/resilience/chaos.py",
        reason=(
            "wedge() blocking forever is the FEATURE: the chaos drill's "
            "hung-collective stand-in must be indistinguishable from a "
            "real wedge (no timeout, nothing for except to catch) so "
            "the escalating watchdog — not the wedge — ends the job; "
            "timeout_s bounds it for unit tests only"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.unbounded-wait",
        match="apex_tpu/utils/autoresume.py",
        reason=(
            "the durability barrier: _commit's self._writer.wait() "
            "joins the single background finalizer before the manifest "
            "commit — unbounded BY CONTRACT because a checkpoint is "
            "either durable or the save did not happen; bounding it "
            "would invent a third state (manifest written, payload "
            "maybe not). The watchdog's deadline, not a local timeout, "
            "is the escape hatch for a wedged filesystem"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.handler-unsafe",
        match="apex_tpu/monitor/router.py",
        reason=(
            "the audited teardown: _flush_all_routers runs registered "
            "flush hooks (dynamic fn()) and router.close() from "
            "atexit/SIGTERM — each call is wrapped in except-and-drop "
            "(teardown must never raise), the router lock it takes is "
            "REENTRANT, and every flush path tolerates partial state; "
            "the hooks are registered only by the goodput span "
            "accountant, whose flush is lock-free"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.handler-unsafe",
        match="apex_tpu/utils/autoresume.py",
        reason=(
            "the coordinated handler chain: TerminationNotice's "
            "handler is flag-only (GIL-atomic stores, no locks) and "
            "then chains prev(signum, frame) — dynamic, but the chain "
            "is coordinated by construction: it skips the router "
            "teardown hook (checked by marker attribute, because that "
            "hook re-raises to DIE by the signal the notice exists to "
            "survive) and every other registrant in the repo is "
            "flag-style (lint.signal-handlers closes the set of homes)"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.unresolved",
        match="apex_tpu/",
        reason=(
            "the resolver's honest remainder: calls through variables, "
            "stored callbacks and injected fns that pure-AST resolution "
            "cannot follow from a thread root — surfaced as info so "
            "reviewers see exactly where the analyzer's reach ends, "
            "suppressed as a class because each is a visibility note, "
            "not a defect claim"
        ),
        require_hit=True,
    ),
    AllowlistEntry(
        rule="concurrency.shared-state",
        match="apex_tpu/",
        reason=(
            "the benign sharing inventory: single-writer-many-reader "
            "handshakes (GIL-atomic stores, legal by the same proof as "
            "the autoresume entry) and reads-only state — named "
            "patterns surfaced as info so the sharing stays deliberate "
            "and reviewable, suppressed as a class because neither "
            "pattern can lose an update"
        ),
        require_hit=True,
    ),
]

REPO_ALLOWLIST = Allowlist(
    _PRECISION + _COLLECTIVE + _COMMS + _SHARDING + _HBM + _LINT
    + _CONCURRENCY
)


def repo_allowlist() -> Allowlist:
    """A fresh copy of the repo allowlist (callers may extend)."""
    return Allowlist(list(REPO_ALLOWLIST.entries))
