"""Thread-root inventory + best-effort call graph (pass 1 of 4).

Every way host code leaves the main thread is a **concurrency root**:
``threading.Thread``/``Timer`` constructions, executor ``.submit``,
``signal.signal`` handlers, ``atexit.register`` hooks, and callback
escapes (an internal function reference handed to a deferred-execution
API — see model.py's DEFERRED_CALL_NAMES). Rooted files additionally
carry an implicit **main root** over their public surface, because
"the training loop calls ``beat()`` while ``_run`` polls" is exactly
the two-root interleaving the shared-state audit must see.

From each root this pass walks the resolved internal call graph. The
honesty contract of the whole x-ray lives here: any call the resolver
could NOT follow (a ``fn()`` on a local callable, an ambiguous
attribute like this repo's many ``emit``/``event`` methods, a restored
handler variable) is reported as ``concurrency.unresolved`` **info**
rather than silently dropped — the gate's jsonl stays an explicit
record of where the static story has holes, and each hole carries an
allowlist reason (see allowlist.py ``_CONCURRENCY``).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Optional, Set

from apex_tpu.analysis.findings import Finding, SEV_INFO
from apex_tpu.analysis.concurrency.model import Model, Root

#: max call-graph depth walked from a root (cycle-safe via visited set;
#: the cap only bounds pathological synthetic inputs)
_MAX_DEPTH = 64


def reachable(model: Model, root: Root) -> Set[str]:
    """Qualnames of every function reachable from ``root`` through
    resolved internal edges (the root targets themselves included)."""
    seen: Set[str] = set()
    work = [(t, 0) for t in root.targets if t in model.functions]
    while work:
        qual, depth = work.pop()
        if qual in seen or depth > _MAX_DEPTH:
            continue
        seen.add(qual)
        fi = model.functions.get(qual)
        if fi is None:
            continue
        for cs in fi.calls:
            if cs.kind == "internal" and cs.resolved in model.functions:
                work.append((cs.resolved, depth + 1))
    return seen


def must_hold(model: Model, root: Root) -> Dict[str, FrozenSet[str]]:
    """Per-function entry lock set that is held on EVERY path from
    ``root`` (intersection over call sites — the guard the shared-state
    audit checks writes against). Worklist fixpoint; monotone down."""
    entry: Dict[str, FrozenSet[str]] = {
        t: frozenset() for t in root.targets if t in model.functions}
    work = [t for t in entry]
    while work:
        qual = work.pop()
        fi = model.functions.get(qual)
        if fi is None:
            continue
        here = entry[qual]
        for cs in fi.calls:
            if cs.kind != "internal" or cs.resolved not in model.functions:
                continue
            new = here | cs.locks
            old = entry.get(cs.resolved)
            upd = new if old is None else (old & new)
            if old is None or upd != old:
                entry[cs.resolved] = upd
                work.append(cs.resolved)
    return entry


def concurrency_roots(model: Model,
                      kinds: Optional[Iterable[str]] = None) -> List[Root]:
    """The inventory, optionally filtered by kind; ``main`` roots last
    so per-root walks process real concurrency first."""
    roots = [r for r in model.roots
             if kinds is None or r.kind in kinds]
    return sorted(roots, key=lambda r: (r.kind == "main", r.label))


def unresolved_findings(model: Model) -> List[Finding]:
    """``concurrency.unresolved`` info for every dynamic call reachable
    from a NON-main root, plus every registration whose handler/target
    expression could not be resolved."""
    findings: List[Finding] = []
    seen_sites: Set[str] = set()
    for root in concurrency_roots(model):
        if root.kind == "main":
            continue
        for qual in sorted(reachable(model, root)):
            fi = model.functions[qual]
            for cs in fi.calls:
                if cs.kind != "dynamic":
                    continue
                site = f"{fi.rel}:{cs.lineno}"
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                findings.append(Finding(
                    rule="concurrency.unresolved",
                    message=(
                        f"call '{cs.text}(...)' reachable from "
                        f"{root.label} could not be statically resolved "
                        f"— the concurrency audit cannot follow it"
                    ),
                    site=site, severity=SEV_INFO,
                    target=root.label,
                    data={"callee": cs.text},
                ))
    for rel, lineno, text in model.unresolved_roots:
        site = f"{rel}:{lineno}"
        if site in seen_sites:
            continue
        seen_sites.add(site)
        findings.append(Finding(
            rule="concurrency.unresolved",
            message=(
                f"concurrency-root registration with unresolvable "
                f"target: {text}"
            ),
            site=site, severity=SEV_INFO,
            data={"callee": text},
        ))
    return sorted(findings, key=lambda f: f.site)
