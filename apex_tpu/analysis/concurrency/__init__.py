"""Concurrency x-ray: static race/deadlock analysis of the host runtime.

The threaded host side — watchdog poller + fire batch, incident
teardown, async checkpoint finalize, MetricRouter SIGTERM/atexit
teardown, remediation controller — has until now been hand-proved in
comments ("GIL-atomic identity-swap handshake", "the sink must never
take the controller lock"). This package puts the same gate discipline
behind those claims that the jaxpr/HLO passes put behind the compiled
step: pure AST (no execution, no jax import — the ``hlo/parser.py``
discipline), whole-package, wired into ``python -m apex_tpu.analysis``.

Four passes over one shared model (model.py):

- ``roots``     — thread/timer/executor/signal/atexit/callback root
  inventory + best-effort call graph; every edge the resolver cannot
  follow is ``concurrency.unresolved`` info, never silently dropped.
- ``shared``    — module globals and self-attributes written from ≥2
  roots without a common lock on every write path →
  ``concurrency.unguarded-write`` (error); benign patterns downgrade
  to named ``concurrency.shared-state`` info.
- ``lockgraph`` — lock-order cycles (``concurrency.lock-cycle``,
  error) and blocking calls — router fan-out, unbounded join/wait,
  file/subprocess I/O, imports — under a lock
  (``concurrency.blocking-under-lock`` /
  ``concurrency.unbounded-wait``, warnings).
- ``handlers``  — signal/atexit handler reach restricted to an
  async-signal-safe vocabulary (``concurrency.handler-unsafe``,
  error).

Findings flow through the same :class:`Finding`/Allowlist machinery as
every other pass; the repo's documented lock-free handshakes carry
``require_hit`` allowlist entries whose reasons ARE the hand-proofs —
when the code changes, the entry goes stale and the gate demands the
proof be re-made. Run standalone::

    from apex_tpu.analysis.concurrency import run_concurrency
    findings = run_concurrency()           # scans apex_tpu/
    findings = run_concurrency(files={...})  # synthetic (tests)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.concurrency.model import Model, build_model
from apex_tpu.analysis.concurrency import roots as _roots
from apex_tpu.analysis.concurrency import shared as _shared
from apex_tpu.analysis.concurrency import lockgraph as _lockgraph
from apex_tpu.analysis.concurrency import handlers as _handlers

#: the concurrency scan covers the library only: examples drive the
#: blessed entry points (AutoResume, monitor wiring) and own no threads
SCAN_DIRS = ("apex_tpu",)

#: pass registry, same shape as LINT_RULES / JAXPR_PASSES
CONCURRENCY_PASSES = {
    "roots": _roots.unresolved_findings,
    "shared": _shared.shared_state_findings,
    "lock-order": _lockgraph.lock_order_findings,
    "blocking": _lockgraph.blocking_findings,
    "handlers": _handlers.handler_findings,
}


def run_concurrency(
    files: Optional[Dict[str, str]] = None,
    root: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Build the model over ``files`` (default: scan ``apex_tpu/``) and
    run ``passes`` (default all), returning raw findings — apply an
    Allowlist afterwards, exactly like the lint/jaxpr passes."""
    if files is None:
        from apex_tpu.analysis.lint import collect_sources

        files = collect_sources(root=root, scan_dirs=SCAN_DIRS)
    model = build_model(files)
    findings: List[Finding] = []
    for name in (passes or CONCURRENCY_PASSES):
        findings.extend(CONCURRENCY_PASSES[name](model))
    return findings


__all__ = [
    "CONCURRENCY_PASSES", "Model", "build_model", "run_concurrency",
    "SCAN_DIRS",
]
