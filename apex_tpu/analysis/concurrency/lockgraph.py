"""Lock-order graph + blocking-under-lock (pass 3 of 4).

Two hazards, both **root-independent** (any function may be entered
from any thread; the hazards are structural properties of the lock
discipline, not of one interleaving):

- ``concurrency.lock-cycle`` (error): build the directed graph
  *lock A → lock B* for every acquisition of B while A may be held
  (locally, or inherited from any caller — a may-hold union fixpoint
  over the internal call graph). Any cycle is an ordering inversion
  two threads can deadlock on; a self-edge on a non-reentrant lock is
  the single-thread variant. Reentrant self-edges (RLock) are legal
  and skipped — that's the router's SIGTERM-reentrancy design.
- ``concurrency.blocking-under-lock`` (warning): a call that can block
  indefinitely — unbounded ``.join()``/``.wait()``, orbax
  ``wait_until_finished``, ``time.sleep``, file/subprocess I/O, a
  router/sink emit fan-out, an ``import`` statement (the interpreter
  import lock) — executed while any lock may be held. This is the
  PR-9 responder-stall shape: the lock's critical section inherits the
  latency (and, for the import lock, the deadlock potential) of the
  slow operation.

Plus ``concurrency.unbounded-wait`` (warning, lock-independent): a
``.wait()`` with no timeout on an unresolvable receiver, or ANY wait on
an inline-constructed ``threading.Event()`` — an event nobody else
holds a reference to, so nobody can ever ``set()`` it (the chaos
``wedge`` is exactly this, deliberately, and carries the allowlist
entry saying so).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set, Tuple

from apex_tpu.analysis.findings import Finding, SEV_ERROR, SEV_WARNING
from apex_tpu.analysis.concurrency.model import CallSite, Model

#: dotted external calls that can block indefinitely (or for I/O time)
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "os.makedirs", "os.replace", "os.rename",
    "os.remove", "os.unlink", "shutil.rmtree", "socket.create_connection",
})

#: attribute names that mark a router/metrics fan-out when the receiver
#: text names a router or sink — the emit path serializes arbitrary
#: sink I/O, so calling it under an unrelated lock extends that lock's
#: critical section by the slowest sink
_EMIT_ATTRS = frozenset({"emit", "event", "metrics"})


def _blocking_op(cs: CallSite) -> str:
    """Non-empty label when the call site can block; '' otherwise.
    Internal calls never match — their bodies are walked directly, so
    transitive blocking is found at the real blocking site with the
    caller's locks folded in by the may-hold propagation."""
    if cs.kind == "internal":
        return ""
    if cs.dotted in _BLOCKING_DOTTED:
        return cs.dotted
    if cs.dotted == "open" or cs.text == "open":
        return "open"
    if cs.attr == "join" and cs.nargs == 0:
        return f"{cs.text}() [unbounded join]"
    if cs.attr == "wait" and cs.nargs == 0:
        return f"{cs.text}() [unbounded wait]"
    if cs.attr == "wait_until_finished":
        return f"{cs.text}() [checkpoint wait]"
    if cs.attr in _EMIT_ATTRS and any(
            t in cs.recv_text.lower() for t in ("router", "sink")):
        return f"{cs.text}(...) [router fan-out]"
    return ""


def _may_hold_entry(model: Model) -> Dict[str, FrozenSet[str]]:
    """Union-over-callers fixpoint: the lock set that MAY be held at
    each function's entry, seeding every function as a potential thread
    entry point with nothing held."""
    entry: Dict[str, Set[str]] = {q: set() for q in model.functions}
    changed = True
    while changed:
        changed = False
        for qual, fi in model.functions.items():
            src = entry[qual]
            for cs in fi.calls:
                if cs.kind != "internal" or \
                        cs.resolved not in model.functions:
                    continue
                add = src | cs.locks
                tgt = entry[cs.resolved]
                if not add <= tgt:
                    tgt |= add
                    changed = True
    return {q: frozenset(s) for q, s in entry.items()}


def lock_order_findings(model: Model) -> List[Finding]:
    entry = _may_hold_entry(model)
    # lock digraph: held -> acquired, with one witness site per edge
    edges: Dict[Tuple[str, str], str] = {}
    findings: List[Finding] = []
    for qual in sorted(model.functions):
        fi = model.functions[qual]
        for lock_id, lineno, local_held in fi.acquires:
            held = entry[qual] | local_held
            site = f"{fi.rel}:{lineno}"
            for h in sorted(held):
                if h == lock_id:
                    if not model.locks[lock_id].reentrant:
                        findings.append(Finding(
                            rule="concurrency.lock-cycle",
                            message=(
                                f"re-acquisition of non-reentrant lock "
                                f"'{lock_id}' while it may already be "
                                f"held — single-thread self-deadlock"
                            ),
                            site=site, severity=SEV_ERROR,
                            target=lock_id,
                            data={"cycle": f"{lock_id} -> {lock_id}"},
                        ))
                    continue
                edges.setdefault((h, lock_id), site)

    findings.extend(_cycles(edges))
    return findings


def _cycles(edges: Dict[Tuple[str, str], str]) -> List[Finding]:
    """One finding per elementary cycle in the (tiny) lock digraph,
    canonicalized by rotating the cycle to start at its smallest lock
    id so the same cycle never reports twice."""
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    findings: List[Finding] = []

    def dfs(start: str, node: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                chain = " -> ".join(canon + (canon[0],))
                witness = edges.get((path[-1], start)) or \
                    edges.get((canon[-1], canon[0]), "")
                findings.append(Finding(
                    rule="concurrency.lock-cycle",
                    message=(
                        f"lock-order cycle {chain}: two threads taking "
                        f"these locks in opposite order deadlock"
                    ),
                    site=witness, severity=SEV_ERROR,
                    target=canon[0],
                    data={"cycle": chain},
                ))
            elif nxt not in on_path:
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return findings


def blocking_findings(model: Model) -> List[Finding]:
    entry = _may_hold_entry(model)
    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for qual in sorted(model.functions):
        fi = model.functions[qual]
        may = entry[qual]
        for cs in fi.calls:
            held = may | cs.locks
            site = f"{fi.rel}:{cs.lineno}"
            if held:
                op = _blocking_op(cs)
                if op and (site, op) not in seen:
                    seen.add((site, op))
                    findings.append(Finding(
                        rule="concurrency.blocking-under-lock",
                        message=(
                            f"{op} while holding "
                            f"{{{', '.join(sorted(held))}}} — the "
                            f"critical section inherits this call's "
                            f"worst-case latency"
                        ),
                        site=site, severity=SEV_WARNING,
                        target=sorted(held)[0],
                        data={"op": op,
                              "locks": ",".join(sorted(held))},
                    ))
            # unbounded wait: unsettable inline Event, or a no-timeout
            # wait on an unresolved receiver (lock-independent)
            if cs.attr == "wait" and cs.kind != "internal" and (
                    cs.inline_event or cs.nargs == 0):
                key = (site, "unbounded-wait")
                if key in seen:
                    continue
                seen.add(key)
                why = ("wait on an inline-constructed threading.Event() "
                       "that nothing can ever set()"
                       if cs.inline_event else
                       "wait() with no timeout")
                findings.append(Finding(
                    rule="concurrency.unbounded-wait",
                    message=f"{cs.text}(...): {why}",
                    site=site, severity=SEV_WARNING,
                    data={"op": ("Event.wait" if cs.inline_event
                                 else "wait")},
                ))
        for imp in fi.imports_under_lock:
            held = may | imp.locks
            if not held:
                continue
            site = f"{fi.rel}:{imp.lineno}"
            if (site, "import") in seen:
                continue
            seen.add((site, "import"))
            findings.append(Finding(
                rule="concurrency.blocking-under-lock",
                message=(
                    f"import of '{imp.module}' while holding "
                    f"{{{', '.join(sorted(held))}}} — first import "
                    f"runs arbitrary module code under BOTH this lock "
                    f"and the interpreter import lock"
                ),
                site=site, severity=SEV_WARNING,
                target=sorted(held)[0],
                data={"op": f"import {imp.module}",
                      "locks": ",".join(sorted(held))},
            ))
    return findings
