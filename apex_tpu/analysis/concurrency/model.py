"""AST extraction shared by the concurrency passes — no execution.

The concurrency x-ray works the way ``hlo/parser.py`` works on compiler
text: parse once, build a typed model, let every pass query it. This
module turns the scanned source set into that model:

- a **function table** keyed by qualname (``rel.py::Class.method``,
  ``rel.py::fn``, nested ``rel.py::fn.<locals>.inner``, module-level
  code as ``rel.py::<module>``), each function carrying its call sites,
  lock acquisitions, shared-state writes/reads, and the lock set held
  at every one of them;
- a **lock table**: every ``threading.Lock/RLock/Condition/Semaphore``
  construction site, identified *statically* — ``rel.py::Class.attr``
  for ``self.X = threading.Lock()``, ``rel.py::NAME`` for module
  globals (one id per definition site, the standard per-class
  approximation: instances share the identity);
- a **root inventory**: every way host code starts running off the main
  thread — ``threading.Thread(target=...)`` / ``Timer``, executor
  ``.submit``, ``signal.signal`` handlers, ``atexit.register`` hooks,
  plus *callback escapes* (an internal function reference handed to a
  deferred-execution call such as ``finalize_async(...)``,
  ``register_*`` listeners, or an internal constructor that stores
  callbacks — the responder's escalation callables, the checkpoint
  finalize closure). Rooted files additionally get one implicit
  **main root** covering their public surface, so "called from the
  training loop while the thread runs" counts as a second root.

Call resolution is best-effort and honest about its limits:
``self.m()`` resolves within the class, bare names through the nested
scope then the module then cross-module ``from apex_tpu... import``
edges, attribute calls only when the method name is unique across the
scan (this repo's ``emit``/``event``/``close`` are deliberately NOT —
see roots.py, which reports every unresolved edge as
``concurrency.unresolved`` info instead of silently dropping it).
Dotted calls into known stdlib/jax modules classify as ``external``.

Everything here is pure AST — importable with no jax, no threads, no
side effects — so the gate cost is parse time (<2s for the package).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

#: threading constructors that define a lock identity when assigned
LOCK_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
})
REENTRANT_CTORS = frozenset({"RLock"})

#: attribute calls that mutate their receiver in place — a write to the
#: receiver's state for the shared-state audit (deque.append & co.)
MUTATING_ATTR_CALLS = frozenset({
    "append", "appendleft", "add", "clear", "pop", "popleft", "update",
    "extend", "remove", "discard", "insert", "setdefault", "set",
})

#: call names whose function-reference arguments run LATER on another
#: thread (the callback-escape set): the checkpoint writer's
#: ``finalize_async``, executor ``submit``, ``add_done_callback``, and
#: any ``register*`` listener API. Internal class constructors are also
#: scanned (a constructor that stores a callable is deferring it —
#: the responder handing ``self._dump``/``self._terminate`` into
#: ``StallWatchdog(escalations=...)``).
DEFERRED_CALL_NAMES = frozenset({
    "finalize_async", "submit", "add_done_callback", "call_later",
    "call_soon", "call_soon_threadsafe",
})

#: keyword names that mark a callable argument handed to an internal
#: CONSTRUCTOR as deferred (stored for later invocation) — plain
#: internal calls are synchronous and never create roots from their
#: arguments (``retry_with_backoff(fn)`` runs fn on the caller's thread)
CALLBACK_KWARGS = frozenset({
    "target", "callback", "escalations", "exit_fn", "hooks", "func",
})

#: method names too universal for the unique-name attribute-resolution
#: fallback: ``self._f.flush()`` must NOT resolve to the one ``flush``
#: method in the scan (it's a file object's). These resolve as
#: ``dynamic`` instead and surface as ``concurrency.unresolved`` info.
_COMMON_METHODS = frozenset({
    "flush", "close", "write", "read", "get", "set", "put", "pop",
    "append", "add", "update", "clear", "copy", "keys", "values",
    "items", "join", "start", "stop", "run", "send", "recv", "open",
    "wait", "emit", "event", "acquire", "release", "submit", "result",
    "cancel", "done", "encode", "decode", "strip", "split", "format",
    "save", "load", "reset", "name", "next", "step", "state",
})

#: stdlib / third-party top-level modules whose dotted calls classify as
#: ``external`` (never ``dynamic``) — their blocking behaviour is table-
#: driven in lockgraph.py, their signal-safety in handlers.py
_KNOWN_EXTERNAL_MODULES = frozenset({
    "os", "sys", "time", "signal", "atexit", "threading", "logging",
    "json", "math", "re", "io", "itertools", "functools", "contextlib",
    "collections", "dataclasses", "subprocess", "shutil", "tempfile",
    "socket", "ctypes", "struct", "random", "warnings", "traceback",
    "inspect", "types", "typing", "pathlib", "glob", "errno", "uuid",
    "hashlib", "copy", "numpy", "np", "jax", "jnp", "lax", "orbax",
    "optax", "flax", "gc", "pickle", "queue", "weakref", "enum",
    "argparse", "textwrap", "difflib", "unicodedata", "string",
    "heapq", "bisect", "operator", "abc", "platform", "importlib",
    "statistics",
})

_SAFE_BUILTINS = frozenset({
    "len", "str", "int", "float", "bool", "repr", "id", "type", "abs",
    "min", "max", "sum", "round", "sorted", "list", "dict", "set",
    "tuple", "frozenset", "range", "enumerate", "zip", "map", "filter",
    "isinstance", "issubclass", "getattr", "setattr", "hasattr",
    "callable", "iter", "next", "vars", "format", "any", "all",
    "divmod", "ord", "chr", "reversed", "bytes", "hash", "print",
    "super", "object", "delattr", "globals", "locals", "dir", "slice",
    "memoryview", "bytearray", "staticmethod", "classmethod",
    "property", "exec", "eval", "compile", "open", "input",
    "ValueError", "TypeError", "KeyError", "RuntimeError", "OSError",
    "Exception", "BaseException", "StopIteration", "AttributeError",
    "IndexError", "NotImplementedError", "KeyboardInterrupt",
    "FileNotFoundError", "ZeroDivisionError", "OverflowError",
})


@dataclasses.dataclass(frozen=True)
class LockDef:
    """A statically-identified lock: one id per construction site."""
    id: str                      # "rel.py::Class.attr" | "rel.py::NAME"
    reentrant: bool
    site: str                    # "rel.py:NN"


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""
    text: str                    # rendered callee, e.g. "self.router.event"
    lineno: int
    locks: FrozenSet[str]        # lock ids locally held at the call
    kind: str                    # "internal" | "external" | "dynamic"
    resolved: Optional[str] = None   # qualname when kind == "internal"
    attr: Optional[str] = None       # terminal attribute name, if any
    recv_text: str = ""              # receiver expression text, if any
    dotted: Optional[str] = None     # normalized "mod.fn" for externals
    nargs: int = 0                   # positional + keyword arg count
    inline_event: bool = False       # receiver is `threading.Event()`


@dataclasses.dataclass
class StateWrite:
    state: str                   # "rel.py::Class.attr" | "rel.py::NAME"
    lineno: int
    locks: FrozenSet[str]
    in_init: bool                # own-class ctor store (happens-before)


@dataclasses.dataclass
class StateRead:
    state: str
    lineno: int


@dataclasses.dataclass
class ImportUnder:
    lineno: int
    locks: FrozenSet[str]
    module: str


@dataclasses.dataclass
class FuncInfo:
    qualname: str
    rel: str
    name: str
    lineno: int
    cls: Optional[str] = None    # immediate class name for methods
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    #: (lock id, lineno, locks already held locally at the acquisition)
    acquires: List[Tuple[str, int, FrozenSet[str]]] = (
        dataclasses.field(default_factory=list))
    writes: List[StateWrite] = dataclasses.field(default_factory=list)
    reads: List[StateRead] = dataclasses.field(default_factory=list)
    imports_under_lock: List[ImportUnder] = (
        dataclasses.field(default_factory=list))


@dataclasses.dataclass(frozen=True)
class Root:
    """One concurrency root: an entry point onto a non-main context —
    or the implicit main root of a rooted file (kind ``main``)."""
    kind: str                    # thread|timer|executor|signal|atexit|
    #                              callback|main
    site: str                    # "rel.py:NN" ("rel.py" for main)
    targets: Tuple[str, ...]     # resolved entry qualnames (may be ())
    label: str                   # stable display id, e.g. "thread:f.py:10"


@dataclasses.dataclass
class Model:
    files: Dict[str, str]
    functions: Dict[str, FuncInfo]
    locks: Dict[str, LockDef]
    roots: List[Root]
    #: method name -> sorted qualnames across the scan (for unique-name
    #: attribute resolution; ambiguous names resolve to nothing)
    method_index: Dict[str, List[str]]
    #: registration sites whose handler expression could not be resolved
    #: (e.g. restoring a saved handler variable) — reported by roots.py
    unresolved_roots: List[Tuple[str, int, str]]  # (rel, lineno, text)

    def rooted_files(self) -> List[str]:
        rels = {r.site.split(":")[0] for r in self.roots
                if r.kind != "main"}
        return sorted(rels)


def _dotted_text(node: ast.AST) -> str:
    """Best-effort render of a callee/receiver expression."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted_text(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted_text(node.func)}(...)"
    if isinstance(node, ast.Subscript):
        return f"{_dotted_text(node.value)}[...]"
    if isinstance(node, ast.Constant):
        return repr(node.value)
    return "<expr>"


def _module_to_rel(dotted: str, files: Dict[str, str]) -> Optional[str]:
    """``apex_tpu.monitor.router`` -> ``apex_tpu/monitor/router.py`` when
    that file is in the scan set (or its package ``__init__.py``)."""
    base = dotted.replace(".", "/")
    for cand in (base + ".py", base + "/__init__.py"):
        if cand in files:
            return cand
    return None


class _Scope:
    """Per-file name environment built in pass 1."""

    def __init__(self, rel: str):
        self.rel = rel
        #: alias -> real top-level module dotted path ("_signal"->"signal")
        self.module_aliases: Dict[str, str] = {}
        #: from-imported name -> ("func", qualname) | ("class", rel, cls)
        #:                      | ("ext", dotted)
        self.imported: Dict[str, Tuple] = {}
        #: class name -> {method name -> qualname}
        self.classes: Dict[str, Dict[str, str]] = {}
        #: module-level function name -> qualname
        self.module_funcs: Dict[str, str] = {}
        #: module-level assigned names (globals the shared audit tracks)
        self.module_globals: Set[str] = set()


class ModelBuilder:
    def __init__(self, files: Dict[str, str]):
        self.files = files
        self.functions: Dict[str, FuncInfo] = {}
        self.locks: Dict[str, LockDef] = {}
        self.roots: List[Root] = []
        self.method_index: Dict[str, Set[str]] = {}
        self.unresolved_roots: List[Tuple[str, int, str]] = []
        self.scopes: Dict[str, _Scope] = {}
        self.trees: Dict[str, ast.Module] = {}
        #: qualname -> (scope, class name or None, parent func qualname,
        #:              ast node or None for <module>, local name set)
        self._fmeta: Dict[str, Tuple] = {}

    # ---------------------------------------------------------------- pass 1

    def collect(self) -> None:
        for rel in sorted(self.files):
            try:
                tree = ast.parse(self.files[rel])
            except SyntaxError:
                continue        # lint owns the unparseable-file finding
            self.trees[rel] = tree
            scope = _Scope(rel)
            self.scopes[rel] = scope
            self._collect_imports(rel, tree, scope)
            self._collect_defs(rel, tree, scope)
            self._collect_locks(rel, tree, scope)
        # resolve cross-module from-imports now every file is indexed
        for rel, scope in self.scopes.items():
            for name, entry in list(scope.imported.items()):
                if entry[0] != "pending":
                    continue
                mod_rel, leaf = entry[1], entry[2]
                other = self.scopes.get(mod_rel)
                if other is None:
                    scope.imported[name] = ("ext", leaf)
                elif leaf in other.module_funcs:
                    scope.imported[name] = (
                        "func", other.module_funcs[leaf])
                elif leaf in other.classes:
                    scope.imported[name] = ("class", mod_rel, leaf)
                else:
                    scope.imported[name] = ("ext", leaf)

    def _collect_imports(self, rel, tree, scope) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    top = a.name.split(".")[0]
                    scope.module_aliases[a.asname or top] = (
                        a.name if a.asname else top)
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod_rel = _module_to_rel(node.module, self.files)
                for a in node.names:
                    bound = a.asname or a.name
                    if mod_rel is not None:
                        scope.imported[bound] = (
                            "pending", mod_rel, a.name)
                    else:
                        scope.imported[bound] = (
                            "ext", f"{node.module}.{a.name}")

    def _collect_defs(self, rel, tree, scope) -> None:
        def walk_nested(node, parent_qual, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{parent_qual}.<locals>.{child.name}"
                    # closures inherit the enclosing method's class:
                    # `self` inside them is the same instance
                    self._register_func(rel, q, child, cls, parent_qual,
                                        scope)
                    walk_nested(child, q, cls)

        for child in ast.iter_child_nodes(tree):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{rel}::{child.name}"
                scope.module_funcs[child.name] = qual
                self._register_func(rel, qual, child, None, None, scope)
                walk_nested(child, qual, None)
            elif isinstance(child, ast.ClassDef):
                methods: Dict[str, str] = {}
                scope.classes[child.name] = methods
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        q = f"{rel}::{child.name}.{sub.name}"
                        methods[sub.name] = q
                        self._register_func(rel, q, sub, child.name,
                                            None, scope)
                        self.method_index.setdefault(
                            sub.name, set()).add(q)
                        walk_nested(sub, q, child.name)
        # module-level pseudo-function for top-level statements
        mod_q = f"{rel}::<module>"
        self.functions[mod_q] = FuncInfo(
            qualname=mod_q, rel=rel, name="<module>", lineno=1)
        self._fmeta[mod_q] = (scope, None, None, tree, set())
        # module-level assigned names (the globals the shared audit
        # tracks)
        for child in ast.iter_child_nodes(tree):
            for tgt in _assign_targets(child):
                if isinstance(tgt, ast.Name):
                    scope.module_globals.add(tgt.id)

    def _register_func(self, rel, qual, node, cls, parent, scope) -> None:
        if qual in self.functions:
            return
        self.functions[qual] = FuncInfo(
            qualname=qual, rel=rel, name=node.name,
            lineno=node.lineno, cls=cls)
        locals_: Set[str] = set()
        args = node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            locals_.add(a.arg)
        if args.vararg:
            locals_.add(args.vararg.arg)
        if args.kwarg:
            locals_.add(args.kwarg.arg)
        declared_global: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Global):
                declared_global.update(sub.names)
            elif isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                locals_.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                locals_.add(sub.name)
        locals_ -= declared_global
        self._fmeta[qual] = (scope, cls, parent, node, locals_)

    def _collect_locks(self, rel, tree, scope) -> None:
        """Every ``<target> = threading.Lock()``-shaped assignment, at any
        nesting depth, defines a lock id."""
        class_stack: List[str] = []

        def visit(node):
            if isinstance(node, ast.ClassDef):
                class_stack.append(node.name)
                for c in ast.iter_child_nodes(node):
                    visit(c)
                class_stack.pop()
                return
            if isinstance(node, ast.Assign):
                ctor = _lock_ctor_name(node.value, scope)
                if ctor:
                    for tgt in node.targets:
                        lock_id = self._lock_target_id(
                            rel, tgt, class_stack)
                        if lock_id:
                            self.locks.setdefault(lock_id, LockDef(
                                id=lock_id,
                                reentrant=ctor in REENTRANT_CTORS,
                                site=f"{rel}:{node.lineno}"))
            for c in ast.iter_child_nodes(node):
                visit(c)

        visit(tree)

    def _lock_target_id(self, rel, tgt, class_stack) -> Optional[str]:
        if isinstance(tgt, ast.Attribute) and isinstance(
                tgt.value, ast.Name) and tgt.value.id == "self":
            if class_stack:
                return f"{rel}::{class_stack[-1]}.{tgt.attr}"
            return None
        if isinstance(tgt, ast.Name):
            if class_stack:
                return f"{rel}::{class_stack[-1]}.{tgt.id}"
            return f"{rel}::{tgt.id}"
        return None

    # ---------------------------------------------------------------- pass 2

    def extract(self) -> None:
        for qual in sorted(self.functions):
            scope, cls, parent, node, locals_ = self._fmeta[qual]
            fi = self.functions[qual]
            walker = _BodyWalker(self, fi, scope, cls, parent, locals_)
            if node is None:
                continue
            if isinstance(node, ast.Module):
                walker.walk_module(node)
            else:
                walker.walk_func(node)
        self._add_main_roots()
        # drop duplicate roots (a Thread ctor matched both the special
        # case and a callback kwarg scan)
        seen: Set[Tuple] = set()
        uniq: List[Root] = []
        for r in sorted(self.roots,
                        key=lambda r: (r.site, r.kind, r.targets)):
            key = (r.site, r.targets)
            if key in seen:
                continue
            seen.add(key)
            uniq.append(r)
        self.roots = uniq

    def _add_main_roots(self) -> None:
        """Every file that OWNS a root also has a main-thread surface:
        its public module functions and public/lifecycle methods run on
        the caller's thread while the root runs concurrently."""
        rooted = {r.site.split(":")[0] for r in self.roots}
        lifecycle = {"__init__", "__call__", "__enter__", "__exit__"}
        for rel in sorted(rooted):
            scope = self.scopes.get(rel)
            if scope is None:
                continue
            targets: List[str] = [f"{rel}::<module>"]
            for name, q in sorted(scope.module_funcs.items()):
                if not name.startswith("_"):
                    targets.append(q)
            for cname, methods in sorted(scope.classes.items()):
                for mname, q in sorted(methods.items()):
                    if not mname.startswith("_") or mname in lifecycle:
                        targets.append(q)
            self.roots.append(Root(
                kind="main", site=rel, targets=tuple(targets),
                label=f"main:{rel}"))

    # ------------------------------------------------------------- finalize

    def build(self) -> Model:
        self.collect()
        self.extract()
        return Model(
            files=self.files,
            functions=self.functions,
            locks=self.locks,
            roots=self.roots,
            method_index={k: sorted(v)
                          for k, v in self.method_index.items()},
            unresolved_roots=self.unresolved_roots,
        )


def _assign_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    return []


def _lock_ctor_name(value: ast.AST, scope: _Scope) -> Optional[str]:
    """``threading.Lock()`` / aliased / ``from threading import RLock``
    constructor name, else None."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        mod = scope.module_aliases.get(fn.value.id, fn.value.id)
        if mod == "threading" and fn.attr in LOCK_CTORS:
            return fn.attr
    if isinstance(fn, ast.Name):
        entry = scope.imported.get(fn.id)
        if entry and entry[0] == "ext" and entry[1] in {
                f"threading.{c}" for c in LOCK_CTORS}:
            return entry[1].split(".")[-1]
    return None


class _BodyWalker:
    """Walks one function body tracking the locally-held lock set."""

    def __init__(self, builder: ModelBuilder, fi: FuncInfo, scope: _Scope,
                 cls: Optional[str], parent: Optional[str],
                 locals_: Set[str]):
        self.b = builder
        self.fi = fi
        self.scope = scope
        self.cls = cls
        self.parent = parent
        self.locals = locals_
        self.in_init = (fi.name == "__init__")

    # -- entry points ------------------------------------------------------

    def walk_func(self, node) -> None:
        self._block(node.body, frozenset())

    def walk_module(self, tree: ast.Module) -> None:
        body = [s for s in tree.body
                if not isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef))]
        self._block(body, frozenset())

    # -- statement walk ----------------------------------------------------

    def _block(self, stmts: Sequence[ast.stmt],
               held: FrozenSet[str]) -> None:
        for stmt in stmts:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # walked as its own function
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            if held:
                mod = (stmt.module if isinstance(stmt, ast.ImportFrom)
                       else stmt.names[0].name) or ""
                self.fi.imports_under_lock.append(
                    ImportUnder(stmt.lineno, held, mod))
            return
        if isinstance(stmt, ast.With):
            new_held = set(held)
            for item in stmt.items:
                lock_id = self._lock_expr_id(item.context_expr)
                if lock_id:
                    self.fi.acquires.append(
                        (lock_id, item.context_expr.lineno,
                         frozenset(new_held)))
                    new_held.add(lock_id)
                else:
                    self._expr(item.context_expr, held)
            self._block(stmt.body, frozenset(new_held))
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            for tgt in _assign_targets(stmt):
                self._store(tgt, stmt.lineno, held,
                            aug=isinstance(stmt, ast.AugAssign))
            if getattr(stmt, "value", None) is not None:
                self._expr(stmt.value, held)
            return
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, held)
            return
        if isinstance(stmt, (ast.Return, ast.Raise)):
            val = stmt.value if isinstance(stmt, ast.Return) else stmt.exc
            if val is not None:
                self._expr(val, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, held)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._block(stmt.body, held)
            self._block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, held)
            for h in stmt.handlers:
                self._block(h.body, held)
            self._block(stmt.orelse, held)
            self._block(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.Delete, ast.Assert)):
            for v in ast.iter_child_nodes(stmt):
                if isinstance(v, ast.expr):
                    self._expr(v, held)
            return
        # anything else: walk child expressions conservatively
        for v in ast.iter_child_nodes(stmt):
            if isinstance(v, ast.expr):
                self._expr(v, held)
            elif isinstance(v, ast.stmt):
                self._stmt(v, held)

    # -- state access ------------------------------------------------------

    def _state_id(self, node: ast.AST) -> Optional[str]:
        """Shared-state identity for an attribute/global reference."""
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self" \
                and self.cls:
            return f"{self.fi.rel}::{self.cls}.{node.attr}"
        if isinstance(node, ast.Name):
            if node.id in self.locals:
                return None
            if node.id in self.scope.module_globals:
                return f"{self.fi.rel}::{node.id}"
        return None

    def _store(self, tgt: ast.AST, lineno: int, held: FrozenSet[str],
               aug: bool = False) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._store(el, lineno, held, aug=aug)
            return
        base = tgt
        if isinstance(tgt, ast.Subscript):
            base = tgt.value
            self._expr(tgt.slice, held)
        state = self._state_id(base)
        if state is None:
            return
        if state in self.b.locks:
            return                       # lock construction, not state
        self.fi.writes.append(StateWrite(
            state=state, lineno=lineno, locks=held,
            # construction happens-before: a plain ``self.x = ...`` in
            # __init__ precedes any thread start, and module-level
            # initializers run under the import lock. Aug/subscript
            # stores in __init__ still count (they read-modify-write
            # possibly shared containers).
            in_init=((self.in_init and not aug
                      and not isinstance(tgt, ast.Subscript)
                      and isinstance(base, ast.Attribute))
                     or self.fi.name == "<module>"),
        ))
        if aug:
            self.fi.reads.append(StateRead(state, lineno))

    # -- lock expressions --------------------------------------------------

    def _lock_expr_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name):
            if expr.value.id == "self" and self.cls:
                lid = f"{self.fi.rel}::{self.cls}.{expr.attr}"
                if lid in self.b.locks:
                    return lid
            mod = self.scope.module_aliases.get(expr.value.id)
            if mod:
                mod_rel = _module_to_rel(mod, self.b.files)
                if mod_rel:
                    lid = f"{mod_rel}::{expr.attr}"
                    if lid in self.b.locks:
                        return lid
        if isinstance(expr, ast.Name):
            lid = f"{self.fi.rel}::{expr.id}"
            if lid in self.b.locks:
                return lid
        return None

    # -- expression walk ---------------------------------------------------

    def _expr(self, expr: ast.AST, held: FrozenSet[str]) -> None:
        for node in _walk_exprs(expr):
            if isinstance(node, ast.Call):
                self._call(node, held)
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                state = self._state_id(node)
                if state and state not in self.b.locks:
                    self.fi.reads.append(StateRead(state, node.lineno))
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                state = self._state_id(node)
                if state and state not in self.b.locks:
                    self.fi.reads.append(StateRead(state, node.lineno))

    # -- call handling -----------------------------------------------------

    def _call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        fn = node.func
        text = _dotted_text(fn)
        nargs = len(node.args) + len(node.keywords)
        site = CallSite(text=text, lineno=node.lineno, locks=held,
                        kind="dynamic", nargs=nargs)

        # `.acquire()` on a recognized lock: approximate as "held for
        # the rest of the function" is unsound across blocks; we record
        # the acquisition edge (for the lock graph) without extending
        # the held set — the repo idiom is `with lock:` throughout.
        if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
            lid = self._lock_expr_id(fn.value)
            if lid:
                self.fi.acquires.append((lid, node.lineno, held))
                return

        # in-place mutation of shared state via method call
        # (deque.append, set.add, Event.set, dict.update, ...)
        if isinstance(fn, ast.Attribute) and \
                fn.attr in MUTATING_ATTR_CALLS:
            state = self._state_id(fn.value)
            if state and state not in self.b.locks:
                self.fi.writes.append(StateWrite(
                    state=state, lineno=node.lineno, locks=held,
                    in_init=(self.in_init
                             and isinstance(fn.value, ast.Attribute)),
                ))

        self._resolve(fn, node, site)
        self.fi.calls.append(site)
        self._detect_roots(fn, node, site)

    def _resolve(self, fn: ast.AST, node: ast.Call,
                 site: CallSite) -> None:
        scope = self.scope
        if isinstance(fn, ast.Name):
            name = fn.id
            q = self._lookup_bare(name)
            if q:
                site.kind, site.resolved = "internal", q
                return
            entry = scope.imported.get(name)
            if entry:
                if entry[0] == "func":
                    site.kind, site.resolved = "internal", entry[1]
                    return
                if entry[0] == "class":
                    ctor = f"{entry[1]}::{entry[2]}.__init__"
                    site.kind = "internal"
                    site.resolved = (ctor if ctor in self.b.functions
                                     else None)
                    site.recv_text = f"{entry[1]}::{entry[2]}"
                    if site.resolved is None:
                        site.kind = "external"
                        site.dotted = f"{entry[1]}::{entry[2]}"
                    return
                site.kind = "external"
                site.dotted = entry[1]
                return
            if name in scope.classes:
                ctor = f"{self.fi.rel}::{name}.__init__"
                if ctor in self.b.functions:
                    site.kind, site.resolved = "internal", ctor
                    site.recv_text = f"{self.fi.rel}::{name}"
                else:
                    site.kind, site.dotted = "external", name
                return
            if name in _SAFE_BUILTINS or name == "open":
                site.kind, site.dotted = "external", name
                return
            if name in self.locals:
                site.kind = "dynamic"    # fn()/cb() on a local callable
                return
            site.kind, site.dotted = "external", name
            return
        if isinstance(fn, ast.Attribute):
            site.attr = fn.attr
            site.recv_text = _dotted_text(fn.value)
            site.inline_event = _is_inline_event(fn.value, scope)
            # self.m() -> own class method
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                    and self.cls:
                methods = scope.classes.get(self.cls, {})
                if fn.attr in methods:
                    site.kind, site.resolved = "internal", methods[fn.attr]
                    return
                site.kind = "dynamic"
                return
            # mod.f() through a module alias
            if isinstance(fn.value, ast.Name):
                mod = scope.module_aliases.get(fn.value.id)
                if mod:
                    mod_rel = _module_to_rel(mod, self.b.files)
                    if mod_rel:
                        other = self.b.scopes.get(mod_rel)
                        if other and fn.attr in other.module_funcs:
                            site.kind = "internal"
                            site.resolved = other.module_funcs[fn.attr]
                            return
                    site.kind = "external"
                    site.dotted = f"{mod.split('.')[0]}.{fn.attr}"
                    return
                entry = scope.imported.get(fn.value.id)
                if entry and entry[0] == "ext":
                    site.kind = "external"
                    site.dotted = f"{entry[1]}.{fn.attr}"
                    return
            # deep external chains: os.path.join, jax.profiler.start_trace
            root_name = _expr_root_name(fn.value)
            if root_name and self.scope.module_aliases.get(
                    root_name, root_name) in _KNOWN_EXTERNAL_MODULES \
                    and not _mentions_self(fn.value):
                site.kind = "external"
                site.dotted = f"{_dotted_text(fn.value)}.{fn.attr}"
                return
            # unique-method-name fallback across the scan — but never
            # for universal method names (file .flush(), dict .get()):
            # those belong to objects outside the scan far more often
            # than to the one in-scan definition
            if fn.attr not in _COMMON_METHODS:
                cands = self.b.method_index.get(fn.attr, set())
                if len(cands) == 1:
                    site.kind = "internal"
                    site.resolved = next(iter(cands))
                    return
            site.kind = "dynamic"
            return
        site.kind = "dynamic"

    def _lookup_bare(self, name: str) -> Optional[str]:
        """Nested-scope chain: own/enclosing nested defs, then module
        functions."""
        q = self.fi.qualname
        while q:
            cand = f"{q}.<locals>.{name}"
            if cand in self.b.functions:
                return cand
            meta = self.b._fmeta.get(q)
            q = meta[2] if meta else None
        return self.scope.module_funcs.get(name)

    # -- root detection ----------------------------------------------------

    def _detect_roots(self, fn: ast.AST, node: ast.Call,
                      site: CallSite) -> None:
        rel, lineno = self.fi.rel, node.lineno
        loc = f"{rel}:{lineno}"
        # threading.Thread(target=...) / threading.Timer(interval, fn)
        ctor = self._threading_ctor(fn)
        if ctor in ("Thread", "Timer"):
            kind = "thread" if ctor == "Thread" else "timer"
            tgt = None
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    tgt = self._func_ref(kw.value)
            if ctor == "Timer" and tgt is None and len(node.args) >= 2:
                tgt = self._func_ref(node.args[1])
            if tgt:
                self.b.roots.append(Root(
                    kind=kind, site=loc, targets=(tgt,),
                    label=f"{kind}:{loc}"))
            else:
                self.b.unresolved_roots.append(
                    (rel, lineno, f"{ctor} target {_args_text(node)}"))
            return
        # signal.signal(sig, handler) / atexit.register(fn)
        mod_call = self._stdlib_call(fn)
        if mod_call == "signal.signal" and len(node.args) >= 2:
            handler = node.args[1]
            if _is_sig_constant(handler):
                return                   # SIG_DFL / SIG_IGN restore
            tgt = self._func_ref(handler)
            if tgt:
                self.b.roots.append(Root(
                    kind="signal", site=loc, targets=(tgt,),
                    label=f"signal:{loc}"))
            else:
                self.b.unresolved_roots.append(
                    (rel, lineno, f"signal handler {_dotted_text(handler)}"))
            return
        if mod_call == "atexit.register" and node.args:
            tgt = self._func_ref(node.args[0])
            if tgt:
                self.b.roots.append(Root(
                    kind="atexit", site=loc, targets=(tgt,),
                    label=f"atexit:{loc}"))
            else:
                self.b.unresolved_roots.append(
                    (rel, lineno,
                     f"atexit hook {_dotted_text(node.args[0])}"))
            return
        # generic callback escapes: deferred-call names, register* APIs,
        # internal constructors, known callback kwargs
        terminal = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        is_ctor = (site.kind == "internal" and site.resolved
                   and site.resolved.endswith(".__init__"))
        deferred = (terminal in DEFERRED_CALL_NAMES
                    or "register" in terminal)
        if not deferred and not is_ctor:
            return          # plain calls run their args synchronously
        kind = "executor" if terminal == "submit" else "callback"
        for val, kw_name in _arg_exprs(node):
            if not deferred and not _callbackish_kwarg(kw_name):
                continue    # ctors: only callback-shaped keywords defer
            for ref in _callable_refs(val):
                tgt = self._func_ref(ref)
                if tgt:
                    self.b.roots.append(Root(
                        kind=kind, site=loc, targets=(tgt,),
                        label=f"{kind}:{loc}"))

    def _threading_ctor(self, fn: ast.AST) -> Optional[str]:
        if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name):
            mod = self.scope.module_aliases.get(fn.value.id, fn.value.id)
            if mod == "threading":
                return fn.attr
        if isinstance(fn, ast.Name):
            entry = self.scope.imported.get(fn.id)
            if entry and entry[0] == "ext" and \
                    entry[1] in ("threading.Thread", "threading.Timer"):
                return entry[1].split(".")[-1]
        return None

    def _stdlib_call(self, fn: ast.AST) -> Optional[str]:
        if isinstance(fn, ast.Attribute) and isinstance(
                fn.value, ast.Name):
            mod = self.scope.module_aliases.get(fn.value.id, fn.value.id)
            if mod in ("signal", "atexit"):
                return f"{mod}.{fn.attr}"
        if isinstance(fn, ast.Name):
            entry = self.scope.imported.get(fn.id)
            if entry and entry[0] == "ext" and entry[1] in (
                    "signal.signal", "atexit.register"):
                return entry[1]
        return None

    def _func_ref(self, expr: ast.AST) -> Optional[str]:
        """Resolve a function REFERENCE (not call) to a qualname."""
        if isinstance(expr, ast.Name):
            q = self._lookup_bare(expr.id)
            if q:
                return q
            entry = self.scope.imported.get(expr.id)
            if entry and entry[0] == "func":
                return entry[1]
            return None
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name):
            if expr.value.id == "self" and self.cls:
                return self.scope.classes.get(self.cls, {}).get(expr.attr)
            # c._on_event where the method name is unique in the scan
            if expr.attr not in _COMMON_METHODS:
                cands = self.b.method_index.get(expr.attr, set())
                if len(cands) == 1:
                    return next(iter(cands))
        return None


def _callbackish_kwarg(name: Optional[str]) -> bool:
    """Constructor keywords that plausibly store a callable for later."""
    if not name:
        return False
    return (name in CALLBACK_KWARGS or name.startswith("on_")
            or "hook" in name or "callback" in name
            or "escalation" in name or name.endswith("_fn"))


def _is_inline_event(expr: ast.AST, scope: _Scope) -> bool:
    """``threading.Event().wait(...)`` — an event nobody else can set."""
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        mod = scope.module_aliases.get(fn.value.id, fn.value.id)
        return mod == "threading" and fn.attr == "Event"
    if isinstance(fn, ast.Name):
        entry = scope.imported.get(fn.id)
        return bool(entry and entry[0] == "ext"
                    and entry[1] == "threading.Event")
    return False


def _is_sig_constant(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Attribute)
            and expr.attr in ("SIG_DFL", "SIG_IGN"))


def _expr_root_name(expr: ast.AST) -> Optional[str]:
    while isinstance(expr, (ast.Attribute, ast.Subscript, ast.Call)):
        expr = getattr(expr, "value", None) or getattr(expr, "func", None)
        if expr is None:
            return None
    return expr.id if isinstance(expr, ast.Name) else None


def _mentions_self(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Name) and n.id == "self"
               for n in ast.walk(expr))


def _arg_exprs(node: ast.Call):
    for a in node.args:
        yield a, None
    for kw in node.keywords:
        yield kw.value, kw.arg


def _callable_refs(expr: ast.AST):
    """Name/self-attribute references inside an argument expression —
    including through ``functools.partial(...)``, tuples, and lists."""
    if isinstance(expr, (ast.Name, ast.Attribute)):
        yield expr
        return
    if isinstance(expr, (ast.Tuple, ast.List)):
        for el in expr.elts:
            yield from _callable_refs(el)
        return
    if isinstance(expr, ast.Call):
        for a in expr.args:
            yield from _callable_refs(a)
        for kw in expr.keywords:
            yield from _callable_refs(kw.value)


def _walk_exprs(expr: ast.AST):
    """All expression nodes, NOT descending into nested lambdas/
    comprehension function scopes (close enough for host code)."""
    yield expr
    for child in ast.iter_child_nodes(expr):
        if isinstance(child, (ast.Lambda,)):
            continue
        if isinstance(child, ast.expr):
            yield from _walk_exprs(child)
        elif isinstance(child, (ast.keyword, ast.comprehension)):
            for sub in ast.iter_child_nodes(child):
                if isinstance(sub, ast.expr):
                    yield from _walk_exprs(sub)


def _args_text(node: ast.Call) -> str:
    parts = [_dotted_text(a) for a in node.args]
    parts += [f"{kw.arg}={_dotted_text(kw.value)}" for kw in node.keywords]
    return "(" + ", ".join(parts) + ")"


def build_model(files: Dict[str, str]) -> Model:
    """Parse ``files`` (repo-relative path -> source) into a Model."""
    return ModelBuilder(files).build()
