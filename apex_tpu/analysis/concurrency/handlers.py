"""Handler-safety (pass 4 of 4): signal handlers and atexit hooks.

A signal handler preempts whatever the main thread was doing — possibly
mid-critical-section — and an atexit hook runs during interpreter
teardown while daemon threads still hold locks. Both are therefore
restricted to an **async-signal-safe vocabulary**: flag stores,
timestamping (``time.monotonic``/``time.time``), ``os._exit``/
``os.kill``/``os.getpid``, and handler re-registration
(``signal.signal``). Anything that can re-enter a lock another thread
holds — an explicit acquisition, a blocking call, logging (which takes
the logging module lock), or a call the resolver cannot follow at all —
is ``concurrency.handler-unsafe`` (error).

The repo's two registrants are exactly the interesting cases: the
autoresume flag-only handler *chains the previous handler* (a dynamic
call — safe only because the chain is coordinated to flag-style
handlers, which is the allowlist entry's documented reason), and the
router teardown flushes sinks under its own RLock (safe only because
that lock is reentrant and every flush path tolerates partial state —
again, the entry quotes the proof). Change either body and the
``require_hit`` entry goes stale, forcing the proof to be re-made.
"""

from __future__ import annotations

from typing import List, Set, Tuple

from apex_tpu.analysis.findings import Finding, SEV_ERROR
from apex_tpu.analysis.concurrency.model import CallSite, Model
from apex_tpu.analysis.concurrency import roots as roots_mod
from apex_tpu.analysis.concurrency.lockgraph import _blocking_op

#: external dotted calls a handler may make
_SAFE_DOTTED = frozenset({
    "time.monotonic", "time.time", "time.perf_counter",
    "time.monotonic_ns", "time.time_ns",
    "os._exit", "os.kill", "os.getpid",
    "signal.signal", "signal.getsignal", "signal.Signals",
    "sys.stderr.write", "sys.stdout.write",
})

#: benign receiver methods (pure reads / GIL-atomic container ops)
_SAFE_ATTRS = frozenset({
    "get", "items", "keys", "values", "copy", "append", "add",
    "discard", "pop", "popleft", "clear", "set", "is_set", "monotonic",
    "startswith", "endswith", "strip", "split", "join", "format",
    "getsignal", "signal",
})


def _violation(cs: CallSite) -> Tuple[str, str]:
    """(cause, detail) when the call is outside the safe vocabulary;
    ("", "") when it is fine. Internal calls are fine here — their
    bodies are walked by the same reach."""
    if cs.kind == "internal":
        return "", ""
    op = _blocking_op(cs)
    if op:
        return "blocking", op
    if cs.attr and cs.attr in _SAFE_ATTRS:
        return "", ""    # benign receiver method, resolvable or not
    if cs.kind == "dynamic":
        return "dynamic-call", f"{cs.text}(...)"
    if cs.dotted in _SAFE_DOTTED or cs.text in _SAFE_DOTTED:
        return "", ""
    if cs.dotted and (cs.dotted.split(".")[0] in ("logging",)
                      or cs.dotted.startswith("logger.")
                      or cs.recv_text == "logger"):
        return "unsafe-call", f"{cs.dotted} (logging takes a module lock)"
    if cs.attr and cs.attr in _SAFE_ATTRS:
        return "", ""
    if cs.dotted and "." not in cs.dotted:
        return "", ""                    # bare builtins (len, sorted, ...)
    return "unsafe-call", cs.dotted or cs.text


def handler_findings(model: Model) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str]] = set()
    for root in roots_mod.concurrency_roots(model, kinds=("signal",
                                                          "atexit")):
        for qual in sorted(roots_mod.reachable(model, root)):
            fi = model.functions[qual]
            for lock_id, lineno, _held in fi.acquires:
                key = (root.label, f"{fi.rel}:{lineno}", "lock")
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    rule="concurrency.handler-unsafe",
                    message=(
                        f"{root.kind} handler reach acquires lock "
                        f"'{lock_id}' — deadlocks if the interrupted "
                        f"thread holds it"
                    ),
                    site=f"{fi.rel}:{lineno}", severity=SEV_ERROR,
                    target=root.label,
                    data={"handler": root.targets[0] if root.targets
                          else "", "cause": "lock", "detail": lock_id},
                ))
            for cs in fi.calls:
                cause, detail = _violation(cs)
                if not cause:
                    continue
                key = (root.label, f"{fi.rel}:{cs.lineno}", cause)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    rule="concurrency.handler-unsafe",
                    message=(
                        f"{root.kind} handler reach: {detail} is "
                        f"outside the async-signal-safe vocabulary "
                        f"({cause})"
                    ),
                    site=f"{fi.rel}:{cs.lineno}", severity=SEV_ERROR,
                    target=root.label,
                    data={"handler": root.targets[0] if root.targets
                          else "", "cause": cause, "detail": detail},
                ))
    return findings
