"""Shared-state audit (pass 2 of 4): unguarded cross-root writes.

For every module global and ``self.*`` attribute in a rooted file, join
the root inventory against the write/read sites the model extracted:

- written from **≥2 distinct roots** with no single lock common to
  every write path → ``concurrency.unguarded-write`` (error). The
  guard check is the *must-hold* set: a lock counts only when it is
  held on every static path from the root to the write (intersection
  semantics — a lock taken on one branch proves nothing).
- one writing root with other roots reading → info, pattern named
  ``single-writer-many-reader`` (a GIL-atomic store handshake — legal,
  but it must be *deliberate*, so it surfaces for an allowlist reason);
- no writer outside construction, ≥2 reading roots → info,
  ``reads-only``.

``__init__``'s own-attribute stores are exempt (construction
happens-before the thread exists); everything else — aug-assigns,
subscript stores, in-place mutators like ``deque.append`` — counts.
The repo's two documented lock-free handshakes (autoresume's
``_pending`` identity swap, the remediation controller's GIL-atomic
deque) show up here as errors and carry ``require_hit`` allowlist
entries quoting exactly those hand-proofs — change the code, the entry
goes stale, the gate asks for a fresh proof.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from apex_tpu.analysis.findings import Finding, SEV_ERROR, SEV_INFO
from apex_tpu.analysis.concurrency.model import Model
from apex_tpu.analysis.concurrency import roots as roots_mod


def shared_state_findings(model: Model) -> List[Finding]:
    # state id -> root label -> list of (site, effective lock frozenset)
    writes: Dict[str, Dict[str, List[Tuple[str, frozenset]]]] = {}
    reads: Dict[str, Set[str]] = {}

    for root in roots_mod.concurrency_roots(model):
        # every file's implicit main root is the SAME thread — two
        # main-surface writers cannot race each other, so they collapse
        # into one logical root for the distinctness count
        label = "main" if root.kind == "main" else root.label
        entry = roots_mod.must_hold(model, root)
        for qual in roots_mod.reachable(model, root):
            fi = model.functions[qual]
            held_entry = entry.get(qual, frozenset())
            for w in fi.writes:
                if w.in_init:
                    continue
                eff = held_entry | w.locks
                writes.setdefault(w.state, {}).setdefault(
                    label, []).append(
                        (f"{fi.rel}:{w.lineno}", eff))
            for r in fi.reads:
                reads.setdefault(r.state, set()).add(label)

    findings: List[Finding] = []
    for state in sorted(set(writes) | set(reads)):
        by_root = writes.get(state, {})
        writer_roots = sorted(by_root)
        reader_roots = reads.get(state, set())
        if len(writer_roots) >= 2:
            all_sites = sorted(
                (site, locks)
                for sites in by_root.values() for site, locks in sites)
            common = None
            for _, locks in all_sites:
                common = locks if common is None else (common & locks)
            if common:
                continue            # every write path shares a lock
            first_site = all_sites[0][0]
            findings.append(Finding(
                rule="concurrency.unguarded-write",
                message=(
                    f"shared state '{state}' is written from "
                    f"{len(writer_roots)} concurrency roots with no "
                    f"common lock on every write path"
                ),
                site=first_site, severity=SEV_ERROR, target=state,
                data={"state": state,
                      "roots": ",".join(writer_roots),
                      "writes": len(all_sites)},
            ))
        elif len(writer_roots) == 1 and (reader_roots - set(writer_roots)):
            sites = by_root[writer_roots[0]]
            findings.append(Finding(
                rule="concurrency.shared-state",
                message=(
                    f"'{state}': single-writer-many-reader — written "
                    f"only from {writer_roots[0]}, read from "
                    f"{len(reader_roots - set(writer_roots))} other "
                    f"root(s); relies on GIL-atomic stores"
                ),
                site=sorted(s for s, _ in sites)[0],
                severity=SEV_INFO, target=state,
                data={"state": state,
                      "pattern": "single-writer-many-reader",
                      "writer": writer_roots[0]},
            ))
        elif not writer_roots and len(reader_roots) >= 2:
            findings.append(Finding(
                rule="concurrency.shared-state",
                message=(
                    f"'{state}': reads-only — no post-construction "
                    f"writer, read from {len(reader_roots)} roots"
                ),
                site=state.split("::")[0], severity=SEV_INFO,
                target=state,
                data={"state": state, "pattern": "reads-only"},
            ))
    return findings
