"""Host-sync detector: callbacks and host transfers inside the step.

A compiled train step should touch the host exactly once per log
interval (the MetricBag contract, monitor/metrics.py) — anything else
serializes the device against Python. The offenders hide well because
they are *correct*: ``jax.debug.print`` left over from a debugging
session, a ``pure_callback`` smuggled in by a library, an
``io_callback`` logger — each one stalls the XLA pipeline for a host
round-trip (~73 ms through the relay, utils/benchmarking.py) every
single step, which swamps small-step training without changing any
output. This pass finds them in the traced jaxpr before a step runs:

- ``host-sync.callback`` — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` (what ``jax.debug.print`` lowers to) and the legacy
  host_callback primitives.
- ``host-sync.transfer`` — explicit ``device_put`` equations whose
  destination is a host memory space (the memories API): an in-step
  device->host transfer.

Debug taps that are MEANT to ship (none today) would get a documented
allowlist entry; everything else is a finding.
"""

from typing import Iterable

from apex_tpu.analysis.findings import Finding, SEV_ERROR
from apex_tpu.analysis.passes import eqn_site, jaxpr_pass

__all__ = ["host_sync_pass"]

#: primitives that call back into Python (one host round-trip per step,
#: per occurrence), with the user-facing API name for the message
_CALLBACK_PRIMS = {
    "pure_callback": "jax.pure_callback",
    "io_callback": "jax.experimental.io_callback",
    "debug_callback": "jax.debug.print/jax.debug.callback",
    "outside_call": "jax.experimental.host_callback (legacy)",
    "host_callback": "jax.experimental.host_callback (legacy)",
}


def _targets_host(eqn) -> bool:
    """True when a device_put equation's destination is host memory."""
    for key in ("devices", "srcs", "memory_kind", "sharding"):
        val = eqn.params.get(key)
        if val is not None and "host" in repr(val).lower():
            return True
    return False


@jaxpr_pass("host-sync")
def host_sync_pass(ctx) -> Iterable[Finding]:
    for eqn in ctx.iter_eqns():
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS:
            yield ctx.finding(
                "host-sync.callback",
                f"{_CALLBACK_PRIMS[name]} inside the compiled step: one "
                f"host round-trip EVERY step (the bag/router path exists "
                f"so this crossing is paid once per interval)",
                site=eqn_site(eqn), severity=SEV_ERROR,
                data={"primitive": name},
            )
        elif name == "device_put" and _targets_host(eqn):
            yield ctx.finding(
                "host-sync.transfer",
                "device_put to host memory inside the compiled step: an "
                "in-step device->host transfer serializes the device "
                "against host RAM",
                site=eqn_site(eqn), severity=SEV_ERROR,
                data={"primitive": name},
            )
