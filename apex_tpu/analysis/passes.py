"""Jaxpr-pass framework: trace a step function, walk it, audit it.

The trace-time half of ``apex_tpu.analysis``. A *pass* receives a
:class:`StepContext` — the closed jaxpr of a step function obtained via
``jax.make_jaxpr`` (abstract tracing: CPU-safe, no execution, args may be
``ShapeDtypeStruct``) plus the ambient mesh and donation intent — and
yields :class:`~apex_tpu.analysis.findings.Finding` records. Passes
register into :data:`JAXPR_PASSES` with :func:`jaxpr_pass`, the same
shape as the AST rule registry in ``lint.py``:

    @jaxpr_pass("precision")
    def precision_pass(ctx):
        for eqn in ctx.iter_eqns():
            ...
            yield Finding(rule="precision.promotion", ...)

Walking covers the WHOLE program: :func:`iter_eqns` recurses into every
sub-jaxpr an equation carries (pjit/shard_map bodies, scan/while bodies,
cond branches, custom_vjp fwd/bwd, remat) — a promotion inside a
rematerialized scan body two levels down is still found. Sites resolve
through the equation's source-info traceback to the first frame that is
neither jax-internal nor one of our thin wrapper modules (the xray
ledger, pipeline p2p), so a flagged collective points at the schedule
that issued it, not at the wrapper that recorded it.

Run everything over a :class:`StepTarget` with :func:`run_passes`; the
CLI (``python -m apex_tpu.analysis``) does exactly that for the in-repo
GPT/BERT step builders (``targets.py``).
"""

import dataclasses
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.analysis.findings import Allowlist, Finding, merge_findings

__all__ = [
    "JAXPR_PASSES",
    "jaxpr_pass",
    "StepContext",
    "StepTarget",
    "iter_eqns",
    "eqn_site",
    "lower_step",
    "run_passes",
]

#: registered jaxpr passes, name -> pass fn(StepContext) -> Iterable[Finding]
JAXPR_PASSES: Dict[str, Callable] = {}

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: wrapper modules whose frames are NOT the interesting call site: the
#: instrumented collective wrappers and the p2p edge helpers — findings
#: should name the schedule/layer that called them
_WRAPPER_FRAGMENTS = (
    os.path.join("monitor", "xray", "ledger.py"),
    os.path.join("parallel", "pipeline", "p2p.py"),
)


def jaxpr_pass(name: str):
    """Register a pass under ``name`` (decorator)."""

    def register(fn):
        JAXPR_PASSES[name] = fn
        return fn

    return register


def _relsite(path: str, line: int) -> str:
    """Normalize an absolute source path to a repo-relative site string."""
    path = path.replace(os.sep, "/")
    for anchor in ("/apex_tpu/", "/examples/", "/tests/", "/benchmarks/"):
        idx = path.rfind(anchor)
        if idx >= 0:
            return f"{path[idx + 1:]}:{line}"
    root = _REPO_ROOT.replace(os.sep, "/")
    if path.startswith(root + "/"):
        return f"{path[len(root) + 1:]}:{line}"
    return f"{path}:{line}"


def eqn_site(eqn, skip_wrappers: bool = True) -> str:
    """Repo-relative ``file.py:line`` of the user code that produced an
    equation, or ``"<unknown>"`` when source info is unavailable.

    Note one honest quirk: equations synthesized by transposition
    (backward-pass converts, reversed scan edges) inherit the FORWARD
    equation's source info, so a backward promotion points at the forward
    cast it transposes — the right line to look at anyway.
    """
    try:
        from jax._src import source_info_util


        frames = list(source_info_util.user_frames(eqn.source_info))
    except Exception:
        return "<unknown>"
    chosen = None
    for fr in frames:
        chosen = fr
        if skip_wrappers and any(
            frag in fr.file_name for frag in _WRAPPER_FRAGMENTS
        ):
            continue
        break
    if chosen is None:
        return "<unknown>"
    return _relsite(chosen.file_name, chosen.start_line)


def _subjaxprs(eqn) -> Iterator[Any]:
    """Every jaxpr nested in an equation's params (pjit/scan/cond/shard_map
    bodies, custom_vjp rules, remat) — duck-typed on ``.eqns``."""
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            j = getattr(v, "jaxpr", v)  # ClosedJaxpr -> Jaxpr
            if hasattr(j, "eqns"):
                yield j


def iter_eqns(jaxpr) -> Iterator[Any]:
    """Depth-first over every equation of ``jaxpr`` (Jaxpr or ClosedJaxpr)
    including all nested sub-jaxprs."""
    j = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in j.eqns:
        yield eqn
        for sub in _subjaxprs(eqn):
            yield from iter_eqns(sub)


def lower_step(fn, args, donate_argnums=None):
    """The auditors' ONE AOT lowering recipe (donation, the HLO comms
    differ, the sharding auditor all read products of this — keep them
    agreeing):

    - a DECLARED donation intent always builds a fresh
      ``jax.jit(fn, donate_argnums=..., keep_unused=True)``, even over a
      prejitted ``fn`` — keep_unused makes HLO parameters map 1:1 onto
      flat input leaves, which the donation auditor's indexing needs;
    - otherwise a prejitted ``fn`` lowers as-is (its own donation marks
      are the thing under audit), and a plain function gets
      ``keep_unused=True`` with no donation.
    """
    if donate_argnums:
        return jax.jit(
            fn, donate_argnums=tuple(donate_argnums), keep_unused=True
        ).lower(*args)
    if hasattr(fn, "lower"):  # only jit stages carry .lower
        return fn.lower(*args)
    return jax.jit(fn, keep_unused=True).lower(*args)


@dataclasses.dataclass
class StepTarget:
    """A step function prepared for auditing: what the CLI and tests hand
    to :func:`run_passes`.

    ``args`` may be concrete arrays or ``ShapeDtypeStruct``s; nothing is
    executed. ``donate_argnums`` is the donation INTENT the donation
    auditor verifies against XLA's realized aliasing (None disables that
    pass for the target — e.g. an inference step with nothing to donate).
    """

    name: str
    fn: Callable
    args: Tuple = ()
    mesh: Optional[jax.sharding.Mesh] = None
    donate_argnums: Optional[Tuple[int, ...]] = None
    #: dtypes considered "low precision" for the precision auditor; a
    #: promotion OUT of these to f32/f64 is flagged
    low_dtypes: Tuple = (jnp.bfloat16, jnp.float16)
    #: the analytic HBM prediction (an ``xray.hbm.model.HbmBreakdown``)
    #: the ``hlo-memory`` differ reconciles against XLA's
    #: ``memory_analysis()``; None disables exact reconciliation for the
    #: target (the pass reports ``memory.unverifiable`` instead)
    hbm: Optional[Any] = None
    #: per-target floors for the sharding/donation auditors; None uses
    #: each auditor's 1 MiB default. The tiny CLI targets sit far below
    #: that on purpose — the seeded autofix target lowers the floors so
    #: its deliberately replicated flat opt-state buffers are flagged
    sharding_min_bytes: Optional[int] = None
    donation_min_bytes: Optional[int] = None
    #: autofix hooks (analysis/autofix): ``builder(mesh, **overrides)``
    #: rebuilds this target with injected specs/donations ("specs are
    #: data"); ``build_overrides`` records what this instance was built
    #: with. ``spec_slots`` maps an argnum to the builder kwarg naming
    #: that argument's PartitionSpec; ``donate_slot`` names the builder
    #: kwarg taking the donate tuple. A target with no builder is not
    #: auto-fixable — the applier prints prescriptions instead.
    builder: Optional[Callable] = None
    build_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    spec_slots: Dict[int, str] = dataclasses.field(default_factory=dict)
    donate_slot: Optional[str] = None


class StepContext:
    """What a pass sees: the target plus its lazily-traced jaxpr."""

    def __init__(self, target: StepTarget):
        self.target = target
        self._jaxpr = None
        self._aot = None
        self._hlo_module = None

    @property
    def name(self) -> str:
        return self.target.name

    @property
    def fn(self):
        return self.target.fn

    @property
    def args(self):
        return self.target.args

    @property
    def mesh(self):
        return self.target.mesh

    @property
    def donate_argnums(self):
        return self.target.donate_argnums

    @property
    def low_dtypes(self):
        return tuple(jnp.dtype(d) for d in self.target.low_dtypes)

    @property
    def jaxpr(self):
        """The closed jaxpr of the step, traced once and cached. Tracing
        is abstract (``jax.make_jaxpr``) — no devices are touched, which
        is what makes the auditors CPU-safe pre-flight checks."""
        if self._jaxpr is None:
            fn = self.fn
            # a jit-wrapped step (only jit stages carry .lower) is
            # unwrapped one level so the walk starts at the program, not
            # at a single opaque pjit equation (the predict_comms
            # pattern); shard_map wrappers must stay on — they carry the
            # mesh context the body needs
            if hasattr(fn, "lower"):
                fn = getattr(fn, "__wrapped__", fn)
            self._jaxpr = jax.make_jaxpr(fn)(*self.args)
        return self._jaxpr

    def aot(self):
        """``(lowered, compiled)`` of the step, built once and shared by
        every pass that reads compile products (donation, the HLO comms
        differ, the sharding auditor) — the compile is the only
        non-tracing cost in the whole gate, so it is paid once per
        target. Lowering follows :func:`lower_step` exactly (declared
        donation intent wins, ``keep_unused=True`` for 1:1 leaf↔param
        mapping) so every consumer reads the same module."""
        if self._aot is None:
            lowered = lower_step(self.fn, self.args, self.donate_argnums)
            self._aot = (lowered, lowered.compile())
        return self._aot

    def hlo_module(self):
        """The parsed optimized-HLO module of :meth:`aot`'s executable,
        parsed once and shared by every compile-product pass (donation's
        realized aliases, the comms differ, the sharding auditor) — on a
        real model ``.as_text()`` serializes tens of MB, so text + parse
        are paid once per target, like the compile itself. Raises
        ``ValueError`` on unparseable HLO; callers downgrade that to
        their own unverifiable outcome."""
        if self._hlo_module is None:
            from apex_tpu.analysis.hlo import parser as hlo_parser

            _, compiled = self.aot()
            self._hlo_module = hlo_parser.parse_hlo_module(
                hlo_parser.module_text(compiled)
            )
        return self._hlo_module

    def iter_eqns(self) -> Iterator[Any]:
        return iter_eqns(self.jaxpr)

    def finding(self, rule: str, message: str, **kw) -> Finding:
        kw.setdefault("target", self.name)
        return Finding(rule=rule, message=message, **kw)


def run_passes(
    target: StepTarget,
    passes: Optional[Sequence[str]] = None,
    allowlist: Optional[Allowlist] = None,
) -> List[Finding]:
    """Run ``passes`` (default: all registered) over one target and return
    the merged raw findings; apply an allowlist afterwards via
    ``allowlist.apply`` (kept separate so the CLI can pool findings from
    several targets before the stale-entry check)."""
    names = list(passes) if passes is not None else sorted(JAXPR_PASSES)
    unknown = [n for n in names if n not in JAXPR_PASSES]
    if unknown:
        raise KeyError(
            f"unknown jaxpr pass(es) {unknown}; registered: "
            f"{sorted(JAXPR_PASSES)}"
        )
    ctx = StepContext(target)
    findings: List[Finding] = []
    for name in names:
        findings.extend(JAXPR_PASSES[name](ctx))
    merged = merge_findings(findings)
    if allowlist is not None:
        return allowlist.apply(merged, check_stale=False).findings
    return merged


# importing the pass modules registers them; keep at the bottom so the
# registry and decorators above exist first
from apex_tpu.analysis import precision as _precision  # noqa: E402,F401
from apex_tpu.analysis import donation as _donation  # noqa: E402,F401
from apex_tpu.analysis import collectives as _collectives  # noqa: E402,F401
from apex_tpu.analysis import host_sync as _host_sync  # noqa: E402,F401
from apex_tpu.analysis.hlo import comms_diff as _comms_diff  # noqa: E402,F401
from apex_tpu.analysis.hlo import sharding_audit as _sharding_audit  # noqa: E402,F401
from apex_tpu.analysis.hlo import memory_diff as _memory_diff  # noqa: E402,F401
