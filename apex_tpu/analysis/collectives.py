"""Collective-safety validator: axes, permutations, pipeline edges.

Every collective apex_tpu issues goes through the xray ledger wrappers
(tier-1 lint), so the traced step's collective equations ARE the
library's communication program. This pass checks that program against
the ambient mesh and against the pipeline edge grammar
(``parallel/pipeline/p2p.py``), statically:

- ``collective.unknown-axis`` — the collective names a mesh axis the
  ambient mesh does not carry. Inside one ``shard_map`` this is caught
  at trace time by jax itself; across refactors (a step traced under
  yesterday's mesh, run under today's) the jaxpr is the only place the
  mismatch is visible before devices are involved.
- ``collective.dead-traffic`` — a collective over a size-1 mesh axis.
  XLA elides it, so it is not a correctness bug, but it IS a sign the
  call site should be gated (the reduce is dead code that re-appears as
  real traffic the day the axis grows) — warning severity.
- ``collective.non-permutation`` — a ``ppermute`` whose edge list is not
  a partial permutation: duplicate sources, duplicate destinations,
  self-edges, or out-of-range ranks. jax does not validate this at trace
  time (verified: a duplicate-source perm traces fine) and XLA's
  behavior on it is undefined-to-hostile.
- ``collective.mismatched-edge`` — the static deadlock check for
  pipeline schedules. A linear chain shift (the p2p
  ``forward_edges``/``backward_edges`` grammar) with a missing interior
  link means some stage's input edge never fires while downstream
  stages still expect the stream: microbatches silently stop flowing at
  the gap (the SPMD analogue of a hung send/recv pair). Full rings and
  the single last->first wrap edge are valid by construction; edge sets
  that are not chain-shaped at all get only the permutation check.
"""

from typing import Iterable, List, Optional, Sequence, Tuple

from apex_tpu.analysis.findings import Finding, SEV_ERROR, SEV_WARNING
from apex_tpu.analysis.passes import eqn_site, jaxpr_pass

__all__ = ["collective_pass", "check_perm", "chain_gaps"]

#: jaxpr primitives that move bytes over a named mesh axis, with the
#: params key holding the axis name(s). pmean lowers to psum+div and
#: pmin to pmax of the negation, so the traced set is smaller than the
#: API set.
_COLLECTIVE_AXIS_KEYS = {
    "psum": "axes",
    "pmax": "axes",
    "pmin": "axes",
    "all_gather": "axis_name",
    "reduce_scatter": "axis_name",
    "all_to_all": "axis_name",
    "ppermute": "axis_name",
}


def _axes_of(eqn) -> Tuple:
    key = _COLLECTIVE_AXIS_KEYS[eqn.primitive.name]
    axes = eqn.params.get(key, ())
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    # positional (vmap) axes appear as ints; only named mesh axes are
    # auditable against a mesh
    return tuple(a for a in axes if isinstance(a, str))


def check_perm(
    perm: Sequence[Tuple[int, int]], axis_size: Optional[int]
) -> List[str]:
    """Problems making ``perm`` not a partial permutation (empty = ok)."""
    problems = []
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
    dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
    if dup_src:
        problems.append(f"duplicate source rank(s) {dup_src}")
    if dup_dst:
        problems.append(f"duplicate destination rank(s) {dup_dst}")
    selfed = sorted({s for s, d in perm if s == d})
    if selfed:
        problems.append(f"self-edge(s) at rank(s) {selfed}")
    if axis_size is not None:
        oob = sorted({r for r in srcs + dsts if not 0 <= r < axis_size})
        if oob:
            problems.append(
                f"rank(s) {oob} outside the axis (size {axis_size})"
            )
    return problems


def chain_gaps(
    perm: Sequence[Tuple[int, int]], axis_size: int
) -> Optional[List[int]]:
    """Interior gaps of a linear pipeline chain, or None when ``perm`` is
    not a linear chain (ring, wrap edge, arbitrary shuffle — no chain
    semantics to check).

    A linear chain is a uniform +-1 shift with NO wrap edge: the
    ``p2p.forward_edges``/``backward_edges`` grammar. A gap is a stage
    strictly inside the chain's span whose outgoing edge is missing —
    everything past it waits on data that never crosses the gap.
    """
    if not perm or axis_size < 3:
        return None
    for sig in (1, -1):
        if all(d - s == sig for s, d in perm):
            srcs = sorted(s for s, _ in perm)
            return [
                r for r in range(srcs[0] + 1, srcs[-1])
                if r not in set(srcs)
            ]
    return None


@jaxpr_pass("collective")
def collective_pass(ctx) -> Iterable[Finding]:
    mesh = ctx.mesh
    axis_names = tuple(mesh.axis_names) if mesh is not None else None
    for eqn in ctx.iter_eqns():
        name = eqn.primitive.name
        if name not in _COLLECTIVE_AXIS_KEYS:
            continue
        site = eqn_site(eqn)
        axes = _axes_of(eqn)
        axis_size = None
        for ax in axes:
            if axis_names is not None and ax not in axis_names:
                yield ctx.finding(
                    "collective.unknown-axis",
                    f"'{name}' over axis {ax!r} which the ambient mesh "
                    f"{axis_names} does not carry",
                    site=site, severity=SEV_ERROR,
                    data={"op": name, "axis": ax},
                )
                continue
            if mesh is not None:
                size = int(mesh.shape[ax])
                axis_size = size if len(axes) == 1 else axis_size
                if size == 1:
                    yield ctx.finding(
                        "collective.dead-traffic",
                        f"'{name}' over size-1 axis {ax!r} is dead traffic "
                        f"— XLA elides it today; gate the call site so it "
                        f"does not become real bytes when the axis grows",
                        site=site, severity=SEV_WARNING,
                        data={"op": name, "axis": ax},
                    )
        if name != "ppermute":
            continue
        perm = tuple(tuple(e) for e in eqn.params.get("perm", ()))
        ax = axes[0] if axes else "?"
        problems = check_perm(perm, axis_size)
        if problems:
            yield ctx.finding(
                "collective.non-permutation",
                f"ppermute over axis {ax!r} with invalid edges "
                f"{list(perm)}: " + "; ".join(problems),
                site=site, severity=SEV_ERROR,
                data={"axis": ax, "perm": str(list(perm))},
            )
            continue
        if axis_size is not None:
            gaps = chain_gaps(perm, axis_size)
            if gaps:
                yield ctx.finding(
                    "collective.mismatched-edge",
                    f"pipeline chain over axis {ax!r} has no edge out of "
                    f"stage(s) {gaps}: downstream stages' recv edges fire "
                    f"but the stream never crosses the gap (static "
                    f"deadlock) — edges {list(perm)}",
                    site=site, severity=SEV_ERROR,
                    data={"axis": ax, "gaps": str(gaps),
                          "perm": str(list(perm))},
                )
