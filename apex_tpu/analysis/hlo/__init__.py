"""Compiled-HLO static analysis: read what XLA actually emitted.

The trace-time passes (precision/collective/host-sync) and the xray
ledger see what the program ASKS for; this subpackage audits what the
compiler DID. One shared, nesting-safe HLO text parser
(:mod:`~apex_tpu.analysis.hlo.parser` — the single ``.as_text()``
scraping home, lint-enforced), a ``replica_groups`` -> mesh-axis
attribution layer (:mod:`~apex_tpu.analysis.hlo.attribution`), the
ghost-collective differ (:mod:`~apex_tpu.analysis.hlo.comms_diff`,
emitted vs ledger-predicted traffic), and the entry-sharding auditor
(:mod:`~apex_tpu.analysis.hlo.sharding_audit`, >=1MiB replicated
buffers on a parallel mesh). The two audits register as jaxpr passes
(``hlo-comms`` / ``hlo-sharding``) so ``run_passes`` and the
``python -m apex_tpu.analysis`` gate pick them up with everything else.

Lazy attribute access (PEP 562), same contract as the parent package:
importing ``apex_tpu.analysis.hlo`` must not initialize jax (the parser
and attribution are jax-free; the audits import jax on use).
"""

_EXPORTS = {
    # parser (jax-free)
    "HloModule": "parser",
    "HloCollective": "parser",
    "HloParam": "parser",
    "HloShape": "parser",
    "HloSharding": "parser",
    "COLLECTIVE_KINDS": "parser",
    "parse_hlo_module": "parser",
    "module_text": "parser",
    "realized_aliases": "parser",
    "mlir_marked_aliases": "parser",
    "mlir_main_signature": "parser",
    "balanced": "parser",
    # attribution (numpy only)
    "mesh_axis_partitions": "attribution",
    "classify_replica_groups": "attribution",
    "classify_source_target_pairs": "attribution",
    "canon_axis_key": "attribution",
    "AXIS_NONE": "attribution",
    "AXIS_UNKNOWN": "attribution",
    # audits
    "audit_comms": "comms_diff",
    "OP_CLASS": "comms_diff",
    "audit_entry_shardings": "sharding_audit",
}

__all__ = sorted(_EXPORTS) + [
    "parser", "attribution", "comms_diff", "sharding_audit",
]

_SUBMODULES = frozenset(__all__) - frozenset(_EXPORTS)


def __getattr__(name):
    import importlib

    if name in _EXPORTS:
        mod = importlib.import_module(
            f"apex_tpu.analysis.hlo.{_EXPORTS[name]}"
        )
        return getattr(mod, name)
    if name in _SUBMODULES:
        return importlib.import_module(f"apex_tpu.analysis.hlo.{name}")
    raise AttributeError(
        f"module 'apex_tpu.analysis.hlo' has no attribute {name!r}"
    )


def __dir__():
    return sorted(set(globals()) | set(__all__))
