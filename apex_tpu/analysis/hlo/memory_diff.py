"""Compiled-memory differ: XLA's ``memory_analysis()`` vs the HBM ledger.

The analytic ledger (``monitor/xray/hbm/model.py``) predicts a step's
per-device peak from closed-form arithmetic — what memory the config
SHOULD pin. XLA's ``compiled.memory_analysis()`` says what the compiled
program actually books. This pass reconciles the two over the shared
AOT compile (``StepContext.aot()`` — one ``.lower().compile()`` serves
the donation auditor and all three HLO passes):

- ``memory.unpredicted``  (error) — bytes the model cannot account
  for: an argument component whose measured bytes differ from the
  prediction (params and optimizer state must match EXACTLY — their
  layout is deterministic), entry-parameter bytes the parser cannot
  attribute to any predicted component, or temporaries beyond the
  declared band (``temp_band`` x the predicted transient bytes). The
  finding carries largest-buffer attribution from the HLO parser's
  entry-parameter shapes (XLA does not expose individual temp buffers,
  so the resident table is the anchor the forensics get).
- ``memory.headroom``     (warning) — the predicted (or measured) peak
  lands within ``headroom_fraction`` of device capacity: the config
  compiles today and OOMs on the first shape regression. Skipped when
  no capacity is known (CPU reports none — None is never faked).
- ``memory.overpredicted``(info) — model pessimism: the measured peak
  is below the prediction (XLA rematerialized or aliased what the
  ledger booked). Not a defect; the delta bounds how much the
  feasibility oracle over-refuses.
- ``memory.reconciled``   (info) — positive confirmation: every
  resident component matched exactly and the temps sat inside the
  band, with the full component table in the finding data — the gate's
  jsonl carries the proof, not just the absence of errors.
- ``memory.unverifiable`` (info) — the backend reports no memory
  analysis, the HLO could not be parsed, or the target carries no
  analytic ledger (``StepTarget.hbm``); callers promising verification
  (the examples' ``--xray-hbm``) must treat this as NOT ok.

Component-to-buffer attribution rides the jax ``op_name`` labels the
parser extracts per entry parameter: a label root of ``params`` books
to the ledger's ``weights`` component, ``opt_state`` to
``optimizer_state``, ``scaler_state`` to ``scaler_state``; every other
root (tokens, labels, ...) books to ``batch_data``.
"""

from typing import Dict, List, Optional, Tuple

from apex_tpu.analysis.findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING
from apex_tpu.analysis.passes import jaxpr_pass

__all__ = [
    "COMPONENT_ROOTS",
    "audit_memory",
    "largest_buffers",
    "hlo_memory_pass",
]

#: ledger component name -> the entry-parameter label roots it books;
#: roots claimed by no component fall through to ``batch_data``
COMPONENT_ROOTS: Dict[str, Tuple[str, ...]] = {
    "weights": ("params",),
    "optimizer_state": ("opt_state",),
    "scaler_state": ("scaler_state",),
}

#: measured temps may exceed the predicted transient bytes by this
#: factor before the differ calls them unpredicted — the declared band
#: (fusion scratch, reduction workspaces and dtype-widening temps ride
#: on top of the stash/grads the ledger books analytically)
DEFAULT_TEMP_BAND = 4.0

#: warn when the peak lands within this fraction of capacity
DEFAULT_HEADROOM_FRACTION = 0.1


def _label_root(param) -> str:
    """The first path element of a parameter's jax ``op_name`` label
    (``opt_state.exp_avg['params']...`` -> ``opt_state``)."""
    label = (param.label or param.name or "").replace("\\'", "'")
    for sep in ("[", ".", "/"):
        idx = label.find(sep)
        if idx >= 0:
            label = label[:idx]
    return label.lstrip("%")


def largest_buffers(module, n: int = 5) -> List[dict]:
    """The ``n`` largest entry-parameter buffers, largest first — the
    attribution table the OOM incident bundle carries."""
    rows = [
        {
            "name": (p.label or p.name).replace("\\'", "'")[:120],
            "bytes": int(p.nbytes),
        }
        for p in module.entry_params
    ]
    rows.sort(key=lambda r: r["bytes"], reverse=True)
    return rows[:n]


def _measured_components(module, predicted) -> Tuple[Dict[str, int], int]:
    """(component name -> measured bytes, unattributed bytes): entry
    parameters grouped through :data:`COMPONENT_ROOTS`."""
    root_of = {}
    for comp, roots in COMPONENT_ROOTS.items():
        if predicted.component(comp) is not None:
            for r in roots:
                root_of[r] = comp
    has_data = predicted.component("batch_data") is not None
    measured: Dict[str, int] = {}
    unattributed = 0
    for p in module.entry_params:
        root = _label_root(p)
        comp = root_of.get(root)
        if comp is None and has_data:
            comp = "batch_data"
        if comp is None:
            unattributed += p.nbytes
            continue
        measured[comp] = measured.get(comp, 0) + p.nbytes
    return measured, unattributed


def audit_memory(
    fn,
    *args,
    donate_argnums=None,
    target: str = "",
    compiled=None,
    module=None,
    predicted=None,
    capacity_bytes: Optional[int] = None,
    headroom_fraction: float = DEFAULT_HEADROOM_FRACTION,
    temp_band: float = DEFAULT_TEMP_BAND,
) -> List[Finding]:
    """Reconcile the analytic breakdown ``predicted`` (an
    ``hbm.model.HbmBreakdown``) against the compiled program's memory
    analysis. ``compiled``/``module`` reuse a shared AOT compile and
    HLO parse when given; ``capacity_bytes`` overrides the device's
    reported limit for virtual-topology rehearsals."""
    from apex_tpu.monitor.xray.hbm.report import report_from_compiled

    site0 = f"<step:{target or getattr(fn, '__name__', 'fn')}>"

    if compiled is None:
        from apex_tpu.analysis.passes import lower_step

        compiled = lower_step(fn, args, donate_argnums).compile()
    report = report_from_compiled(compiled)
    if report is None:
        return [Finding(
            rule="memory.unverifiable",
            message=(
                "backend reports no memory_analysis() for the compiled "
                "step — HBM NOT verified on this platform"
            ),
            site=site0, severity=SEV_INFO, target=target,
        )]

    findings: List[Finding] = []
    capacity = capacity_bytes or report.device_memory_bytes
    if predicted is not None and capacity is None:
        capacity = predicted.capacity_bytes

    if predicted is None:
        findings.append(Finding(
            rule="memory.unverifiable",
            message=(
                "target carries no analytic HBM ledger (StepTarget.hbm) "
                "— measured breakdown attached, prediction NOT verified"
            ),
            site=site0, severity=SEV_INFO, target=target,
            data={"measured": report.fields()},
        ))
    elif module is None or not module.entry_params:
        findings.append(Finding(
            rule="memory.unverifiable",
            message=(
                "optimized HLO could not be parsed into entry parameters "
                "— component attribution NOT verified"
            ),
            site=site0, severity=SEV_INFO, target=target,
        ))
    else:
        measured, unattributed = _measured_components(module, predicted)
        table = {}
        ok = True
        for comp in sorted(
            set(measured) | {c.name for c in predicted.components
                             if not c.transient}
        ):
            pred_c = predicted.component(comp)
            if pred_c is None or pred_c.transient:
                continue
            got = measured.get(comp, 0)
            want = pred_c.bytes
            table[comp] = {"predicted": want, "measured": got}
            if got != want:
                ok = False
                findings.append(Finding(
                    rule="memory.unpredicted",
                    message=(
                        f"component {comp!r}: predicted {want} bytes but "
                        f"the compiled program books {got} "
                        f"(delta {got - want:+d}) — the ledger's layout "
                        f"arithmetic disagrees with XLA"
                    ),
                    site=site0, severity=SEV_ERROR, target=target,
                    data={
                        "component": comp, "predicted": want,
                        "measured": got,
                        "largest_buffers": largest_buffers(module),
                    },
                ))
        if unattributed:
            ok = False
            findings.append(Finding(
                rule="memory.unpredicted",
                message=(
                    f"{unattributed} argument bytes attribute to no "
                    f"predicted component — the model cannot account "
                    f"for them"
                ),
                site=site0, severity=SEV_ERROR, target=target,
                data={
                    "unattributed_bytes": unattributed,
                    "largest_buffers": largest_buffers(module),
                },
            ))
        entry_total = sum(p.nbytes for p in module.entry_params)
        if entry_total != report.argument_bytes:
            ok = False
            findings.append(Finding(
                rule="memory.unpredicted",
                message=(
                    f"entry parameters sum to {entry_total} bytes but "
                    f"memory_analysis books {report.argument_bytes} "
                    f"argument bytes — the parser is missing buffers"
                ),
                site=site0, severity=SEV_ERROR, target=target,
                data={
                    "entry_param_bytes": entry_total,
                    "argument_bytes": report.argument_bytes,
                },
            ))
        transient = max(1, predicted.transient_bytes)
        temp_ratio = report.temp_bytes / transient
        if temp_ratio > temp_band:
            ok = False
            findings.append(Finding(
                rule="memory.unpredicted",
                message=(
                    f"{report.temp_bytes} temp bytes exceed the declared "
                    f"band ({temp_band:.1f}x the {predicted.transient_bytes}"
                    f" predicted transient bytes, ratio "
                    f"{temp_ratio:.2f}) — an unmodeled live-range "
                    f"dominates the step"
                ),
                site=site0, severity=SEV_ERROR, target=target,
                data={
                    "temp_bytes": report.temp_bytes,
                    "predicted_transient_bytes": predicted.transient_bytes,
                    "temp_band": temp_band,
                    "largest_buffers": largest_buffers(module),
                },
            ))
        if ok:
            findings.append(Finding(
                rule="memory.reconciled",
                message=(
                    f"resident components reconciled exactly "
                    f"({len(table)} components, {entry_total} argument "
                    f"bytes) and temps within the band "
                    f"(ratio {temp_ratio:.2f} <= {temp_band:.1f})"
                ),
                site=site0, severity=SEV_INFO, target=target,
                data={
                    "components": table,
                    "temp_bytes": report.temp_bytes,
                    "temp_ratio": round(temp_ratio, 4),
                    "predicted_peak_bytes": predicted.peak_bytes,
                    "measured_total_bytes": report.total_bytes,
                },
            ))
        if predicted.peak_bytes > report.total_bytes:
            findings.append(Finding(
                rule="memory.overpredicted",
                message=(
                    f"predicted peak {predicted.peak_bytes} exceeds the "
                    f"measured total {report.total_bytes} by "
                    f"{predicted.peak_bytes - report.total_bytes} bytes — "
                    f"model pessimism (XLA aliased or rematerialized "
                    f"booked bytes)"
                ),
                site=site0, severity=SEV_INFO, target=target,
                data={
                    "predicted_peak_bytes": predicted.peak_bytes,
                    "measured_total_bytes": report.total_bytes,
                },
            ))

    if capacity:
        peak = max(
            report.total_bytes,
            0 if predicted is None else predicted.peak_bytes,
        )
        budget = (1.0 - headroom_fraction) * capacity
        if peak > budget:
            findings.append(Finding(
                rule="memory.headroom",
                message=(
                    f"peak {peak} bytes lands within "
                    f"{headroom_fraction:.0%} of the {capacity}-byte "
                    f"capacity — the config fits today and OOMs on the "
                    f"first regression"
                ),
                site=site0, severity=SEV_WARNING, target=target,
                data={
                    "peak_bytes": peak,
                    "capacity_bytes": capacity,
                    "headroom_fraction": headroom_fraction,
                },
            ))
    return findings


@jaxpr_pass("hlo-memory")
def hlo_memory_pass(ctx) -> List[Finding]:
    """The registered-pass wrapper: reuses the target's shared AOT
    compile and parsed module, and reads the analytic prediction off
    ``StepTarget.hbm`` (None -> ``memory.unverifiable`` info)."""
    t = ctx.target
    _, compiled = ctx.aot()
    try:
        module = ctx.hlo_module()
    except ValueError:
        module = None
    return audit_memory(
        t.fn, *t.args,
        donate_argnums=t.donate_argnums,
        target=ctx.name,
        compiled=compiled,
        module=module,
        predicted=getattr(t, "hbm", None),
    )
