"""``replica_groups`` -> mesh-axis attribution.

An HLO collective carries no axis names — only its ``replica_groups``
partition of partition ids. But for a given mesh every subset of mesh
axes induces exactly one partition (the groups that vary along those
axes and agree on all others), so the mapping can be inverted: build
the partition for every subset of >1-sized axes and look the observed
groups up. A group set matching no subset is ``"unknown"`` — XLA
invented communication along a shape the program's mesh does not
express (the classic symptom of a bad resharding).

Partition ids: XLA's ``use_global_device_ids`` groups index the
device assignment, which jax builds in ``mesh.devices`` flattened
(row-major) order — attribution therefore works on POSITIONS in the
flattened mesh, never on ``Device.id`` (the two coincide on the common
contiguous meshes but not on sub-meshes or reordered topologies).

Size-1 axes are dropped everywhere: a collective over them moves no
bytes (the ledger elides them; XLA emits singleton groups), and a
composite like ``("pp","cp","tp")`` on a pp=cp=1 mesh canonicalizes to
``"tp"`` so both sides of the differ bucket identically.
"""

import itertools
from typing import Dict, FrozenSet, Sequence, Tuple

import numpy as np

__all__ = [
    "AXIS_NONE",
    "AXIS_UNKNOWN",
    "mesh_axis_partitions",
    "classify_replica_groups",
    "classify_source_target_pairs",
    "canon_axis_key",
]

#: singleton groups: no traffic (a collective over a size-1 axis)
AXIS_NONE = "none"
#: a group set matching no subset of the mesh's axes
AXIS_UNKNOWN = "unknown"

GroupKey = FrozenSet[FrozenSet[int]]


def _live_axes(mesh) -> Tuple[str, ...]:
    shape = dict(mesh.shape)
    return tuple(n for n in mesh.axis_names if shape[n] > 1)


def mesh_axis_partitions(mesh) -> Dict[GroupKey, str]:
    """``{replica-group partition: axis label}`` for every non-empty
    subset of the mesh's >1-sized axes. Labels join subset names in
    mesh order (``"dp,tp"``). Degenerate subsets that induce the same
    partition keep the smallest label (fewest axes)."""
    shape = dict(mesh.shape)
    names = list(mesh.axis_names)
    sizes = [shape[n] for n in names]
    ids = np.arange(int(np.prod(sizes, dtype=np.int64))).reshape(sizes)
    live = _live_axes(mesh)
    out: Dict[GroupKey, str] = {}
    for r in range(1, len(live) + 1):
        for subset in itertools.combinations(live, r):
            axes = [names.index(n) for n in subset]
            rest = [i for i in range(len(names)) if i not in axes]
            group_size = int(np.prod([sizes[i] for i in axes], dtype=np.int64))
            arr = ids.transpose(rest + axes).reshape(-1, group_size)
            key: GroupKey = frozenset(
                frozenset(int(x) for x in row) for row in arr
            )
            # setdefault: smaller subsets come first, so a partition
            # reachable with fewer axes keeps the shorter label
            out.setdefault(key, ",".join(subset))
    return out


def classify_replica_groups(
    mesh, replica_groups: Sequence[Sequence[int]],
    partitions: Dict[GroupKey, str] = None,
) -> str:
    """The mesh-axis label of one collective's ``replica_groups``:
    an axis-subset label (``"tp"``, ``"dp,tp"``), :data:`AXIS_NONE`
    for singleton groups (no traffic), or :data:`AXIS_UNKNOWN`."""
    if not replica_groups:
        # implicit "everyone": the full-mesh subset (or no traffic on a
        # single-device mesh)
        live = _live_axes(mesh)
        return ",".join(live) if live else AXIS_NONE
    if len(replica_groups[0]) <= 1:
        return AXIS_NONE
    if partitions is None:
        partitions = mesh_axis_partitions(mesh)
    key: GroupKey = frozenset(
        frozenset(int(x) for x in g) for g in replica_groups
    )
    return partitions.get(key, AXIS_UNKNOWN)


def classify_source_target_pairs(
    mesh, pairs: Sequence[Sequence[int]],
    partitions: Dict[GroupKey, str] = None,
) -> str:
    """The mesh-axis label of a collective-permute's
    ``source_target_pairs`` (permutes print pairs, not replica_groups).

    A permute belongs to axis subset S when every (src, dst) edge stays
    inside one group of S's partition — i.e. the endpoints differ only
    along S. The SMALLEST such subset wins (a pp-edge permute also fits
    inside the dp,pp partition; "pp" is the informative answer).
    Returns :data:`AXIS_NONE` for an empty pair list (ships nothing)
    and :data:`AXIS_UNKNOWN` when no subset contains every edge."""
    if not pairs:
        return AXIS_NONE
    if partitions is None:
        partitions = mesh_axis_partitions(mesh)
    # smallest subsets first: fewest axes, then mesh order via the label
    for key, label in sorted(
        partitions.items(), key=lambda kv: (kv[1].count(",") + 1, kv[1])
    ):
        if all(
            any(int(s) in g and int(d) in g for g in key)
            for s, d in pairs
        ):
            return label
    return AXIS_UNKNOWN


def canon_axis_key(mesh, axis_key: str) -> str:
    """Canonicalize a ledger axis key (names joined in CALL order, e.g.
    ``"pp,cp,tp"``) onto the attribution labels: drop size-1 axes, order
    by mesh axis order. Names the mesh does not know are kept (sorted
    last) so a mismatch stays visible instead of aliasing to a real
    axis."""
    names = [n for n in axis_key.split(",") if n]
    shape = dict(mesh.shape)
    known = [n for n in mesh.axis_names if n in names and shape[n] > 1]
    foreign = sorted(n for n in names if n not in shape)
    out = known + foreign
    return ",".join(out) if out else AXIS_NONE
