"""Ghost-collective differ: XLA's emitted collectives vs the ledger.

The xray ledger (monitor/xray/ledger.py) predicts a step's collective
traffic at TRACE time — what the program asked for. XLA is free to ask
for more (resharding all-gathers at sharding boundaries, the implicit
weight-update replication of arXiv:2004.13336) or less (CSE folds
duplicate reductions, dead traffic is deleted), and the compiled-HLO
collective layer is where the real comms cost is decided
(arXiv:2506.17615). This pass compiles the step once, parses the
optimized HLO (hlo/parser.py), attributes every collective's
``replica_groups`` to mesh axes (hlo/attribution.py), and diffs the two
sides:

- ``comms.unpredicted`` (error) — XLA emitted traffic the ledger never
  saw: a resharding leak, an uninstrumented collective, or a
  transpose-synthesized backward op whose forward was not custom_vjp
  paired (the ledger docstring's disclaimed blind spot — now loud).
- ``comms.reshard``     (error) — unpredicted traffic with no user
  source frame (or a ``sharding_constraint`` scope): inserted by the
  SPMD partitioner at a jit/shard_map boundary, reported with the
  non-replicated entry shardings that induced it.
- ``comms.vanished``    (warning) — a predicted traffic bucket with NO
  emitted counterpart: the program asks for collectives XLA deletes
  wholesale — dead traffic to remove at source.
- ``comms.folded``      (info) — a bucket where XLA emitted FEWER ops
  than predicted but not zero: CSE/combining legitimately dedupes
  identical reductions (the CE-stats psum pair in the GPT target), so
  a partial shortfall is bookkeeping, not a defect.
- ``comms.unverifiable``(info) — the HLO could not be parsed or no mesh
  is available for attribution; callers promising verification (the
  examples' ``--audit-comms``) must treat this as NOT ok.
- ``comms.quantized``   (info) — positive confirmation that 8-bit-payload
  collectives (the ``parallel/compress.py`` quantized decomposition)
  matched ledger predictions: the int8 pattern was VERIFIED as emitted,
  not allowlisted away. XLA legalizes a split-dim ``all_to_all`` into
  tuple form (one operand per participant); same-shaped operands of one
  all-to-all instruction are folded back into the single logical payload
  the ledger predicted before matching.
- ``comms.async``       (info) — positive confirmation that ledger-matched
  collectives were emitted as async ``-start``/``-done`` pairs: the
  compiler actually split them so its latency-hiding scheduler can
  overlap their wire time with compute — the emitted-HLO leg of the
  overlap proof loop (the prefetched ZeRO param gathers and the
  zero-bubble p2p edges are the callers that cite this), with
  predicted==emitted bytes carried in the finding data. Backend-honest:
  CPU XLA emits sync collectives, so the finding appears only where the
  backend's scheduler can overlap (TPU compiles); its absence on the
  CPU gate is expected, not a failure.

Matching currency is (op-class, mesh axis, OPERAND element count) —
elements, not bytes, because backends legalize dtypes without changing
element counts (CPU XLA widens bf16 collectives to f32; matching bytes
would break the CPU gate). Byte totals of both sides are carried in the
finding data for the reports. A vmap-batched collective (the examples'
microbatch loops under ``xray.scaled(n)``) emits ONE op moving ``n``
predicted payloads, so after exact matching, leftover HLO ops may
consume ``k = elements_hlo / elements_pred`` predictions of a matching
bucket. Collectives inside while/scan bodies appear once in text
however many times the loop runs — the same trace-once convention the
ledger's ``scaled()`` regions use, so the two sides agree per traced
occurrence.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.analysis.findings import (
    Finding,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
)
from apex_tpu.analysis.hlo import attribution
from apex_tpu.analysis.hlo import parser as hlo_parser
from apex_tpu.analysis.passes import _relsite, jaxpr_pass

__all__ = ["OP_CLASS", "audit_comms", "hlo_comms_pass"]

#: ledger op -> optimized-HLO opcode class
OP_CLASS = {
    "psum": "all-reduce",
    "pmean": "all-reduce",
    "pmax": "all-reduce",
    "pmin": "all-reduce",
    "all_gather": "all-gather",
    "psum_scatter": "reduce-scatter",
    "ppermute": "collective-permute",
    "all_to_all": "all-to-all",
}

BucketKey = Tuple[str, str, int]  # (op class, axis label, operand elements)


@dataclasses.dataclass
class _Unit:
    """One matchable emitted payload: one operand of one collective."""

    kind: str
    axis: str
    elements: int
    nbytes: int
    dtype: str
    dims: Tuple[int, ...]
    instr: hlo_parser.HloCollective

    @property
    def key(self) -> BucketKey:
        return (self.kind, self.axis, self.elements)


def _aot_compile(fn, args, donate_argnums):
    """The auditors' shared compile recipe — :func:`lower_step`, so a
    standalone ``audit_comms`` call reads the exact module the donation
    auditor and the CLI's ``ctx.aot()`` would."""
    from apex_tpu.analysis.passes import lower_step

    return lower_step(fn, args, donate_argnums).compile()


def _predicted_buckets(fn, args, mesh) -> Dict[BucketKey, int]:
    from apex_tpu.monitor.xray import ledger as xlax

    led = xlax.predict_comms(fn, *args)
    pred: Dict[BucketKey, int] = {}
    for e in led.entries:
        axis = attribution.canon_axis_key(mesh, e.axis)
        if axis == attribution.AXIS_NONE:
            continue
        elements = int(np.prod(e.shape, dtype=np.int64)) if e.shape else 1
        key = (OP_CLASS.get(e.op, e.op), axis, elements)
        pred[key] = pred.get(key, 0) + e.count
    return pred


def _emitted_units(module: hlo_parser.HloModule, mesh) -> List[_Unit]:
    partitions = attribution.mesh_axis_partitions(mesh)
    units: List[_Unit] = []
    for c in module.collectives:
        if c.kind == "collective-permute":
            # permutes print source_target_pairs, not replica_groups
            axis = attribution.classify_source_target_pairs(
                mesh, c.source_target_pairs, partitions
            )
        else:
            axis = attribution.classify_replica_groups(
                mesh, c.replica_groups, partitions
            )
        if axis == attribution.AXIS_NONE:
            continue  # singleton groups / empty perm: zero bytes, the
            # ledger elides these too
        if c.kind == "all-to-all" and len(c.operands) > 1:
            # XLA legalizes a split-dim all_to_all into TUPLE form: one
            # operand per participant, together ONE logical payload (the
            # quantized-collective decomposition in parallel/compress.py
            # traces one (n, chunk) payload and lands here as n (1, chunk)
            # operands). Fold operands of identical shape back into one
            # unit whose leading dim is the operand count, so the bucket
            # keyed on the ledger's full-payload element count matches;
            # distinct shapes (a combiner merging unrelated all-to-alls)
            # stay separate logical payloads.
            by_shape: Dict[Tuple[str, Tuple[int, ...]], List] = {}
            for op in c.operands:
                by_shape.setdefault(
                    (op.shape.dtype, op.shape.dims), []
                ).append(op)
            for (dtype, dims), ops in sorted(by_shape.items()):
                units.append(_Unit(
                    kind=c.kind, axis=axis,
                    elements=sum(op.elements for op in ops),
                    nbytes=sum(op.nbytes for op in ops),
                    dtype=dtype, dims=(len(ops),) + tuple(dims), instr=c,
                ))
            continue
        for op in c.operands:
            units.append(_Unit(
                kind=c.kind, axis=axis, elements=op.elements,
                nbytes=op.nbytes, dtype=op.shape.dtype,
                dims=op.shape.dims, instr=c,
            ))
    return units


def _is_ledger_sited(instr: hlo_parser.HloCollective) -> bool:
    return instr.source_file.replace("\\", "/").endswith(
        "monitor/xray/ledger.py"
    )


def _site(instr: hlo_parser.HloCollective, target: str) -> str:
    if instr.source_file:
        return _relsite(instr.source_file, instr.source_line)
    return f"<hlo:{target or 'step'}>"


def _entry_sharding_summary(
    module: hlo_parser.HloModule, limit: int = 8
) -> List[str]:
    """The non-replicated entry shardings — the boundary state that
    induces partitioner resharding — as compact strings."""
    out = []
    for p in module.entry_params:
        if p.sharding is not None and not p.sharding.fully_replicated:
            out.append(f"{p.label or p.name}: {p.sharding.raw}")
            if len(out) >= limit:
                break
    return out


def _reshard_suggestion(module: hlo_parser.HloModule, u) -> str:
    """Name the entry-param spec whose absence most plausibly caused a
    partitioner-inserted reshard: the largest fully-replicated entry
    param whose element count the moved payload divides into (the
    all-gather/all-reduce XLA inserts to materialize a replica moves
    the buffer, or a tile of it). The autofix derivation leg consumes
    this; ``--audit-comms`` users see it without ``--fix``."""
    candidates = []
    for p in module.entry_params:
        if p.sharding is None or not p.sharding.fully_replicated:
            continue
        n = int(np.prod(p.shape, dtype=np.int64)) if p.shape else 1
        if n >= u.elements and (u.elements == 0 or n % max(u.elements, 1) == 0):
            candidates.append((n, p))
    if not candidates:
        return ""
    _, p = max(candidates, key=lambda c: c[0])
    return (
        f"suggest annotating entry param {p.label or p.name} "
        f"({p.shape}) with NamedSharding(mesh, PartitionSpec({u.axis!r})) "
        f"(in_shardings= or with_sharding_constraint) so the partitioner "
        f"stops materializing a replica"
    )


def audit_comms(
    fn,
    *args,
    mesh,
    donate_argnums: Optional[Tuple[int, ...]] = None,
    target: str = "",
    compiled=None,
    module=None,
) -> List[Finding]:
    """Diff ``fn``'s optimized-HLO collectives against the ledger's
    trace-time prediction; see the module docstring for the rules.

    ``fn``/``args`` follow :func:`~apex_tpu.analysis.donation.audit_donation`:
    a plain step function or a jitted one, args may be
    ``ShapeDtypeStruct``s. ``compiled`` short-circuits the (seconds)
    compile when the caller already has the executable; ``module``
    additionally short-circuits the text + parse (the CLI's shared
    ``ctx.hlo_module()`` — on a real model the HLO text is tens of MB).
    """
    site0 = f"<step:{target or getattr(fn, '__name__', 'fn')}>"
    if mesh is None:
        return [Finding(
            rule="comms.unverifiable",
            message=(
                "no mesh available — replica_groups cannot be attributed "
                "to axes, comms NOT verified"
            ),
            site=site0, severity=SEV_INFO, target=target,
        )]
    if module is None:
        if compiled is None:
            compiled = _aot_compile(fn, args, donate_argnums)
        try:
            module = hlo_parser.parse_hlo_module(
                hlo_parser.module_text(compiled)
            )
        except ValueError as e:
            return [Finding(
                rule="comms.unverifiable",
                message=(
                    f"optimized HLO could not be parsed ({e}) — comms "
                    f"NOT verified (parser out of date for this XLA?)"
                ),
                site=site0, severity=SEV_INFO, target=target,
            )]
    if not module.entry_name:
        return [Finding(
            rule="comms.unverifiable",
            message=(
                "optimized HLO has no recognizable entry computation — "
                "comms NOT verified (parser out of date for this XLA?)"
            ),
            site=site0, severity=SEV_INFO, target=target,
        )]

    pred = _predicted_buckets(fn, args, mesh)
    units = _emitted_units(module, mesh)
    emitted_keys = {u.key for u in units}

    findings: List[Finding] = []
    remaining = dict(pred)
    consumed_any: Dict[BucketKey, bool] = {k: False for k in pred}

    # stage 1 — exact bucket matches; ledger-sited instructions consume
    # predictions first so any excess is reported at the site that is
    # NOT the wrapper (the transpose/reshard site a human must look at)
    matched: List[_Unit] = []
    leftovers: List[_Unit] = []
    for u in sorted(
        units,
        key=lambda u: (not _is_ledger_sited(u.instr), u.instr.line),
    ):
        if remaining.get(u.key, 0) > 0:
            remaining[u.key] -= 1
            consumed_any[u.key] = True
            matched.append(u)
        else:
            leftovers.append(u)

    # stage 2 — batched reconcile: a vmapped microbatch loop batches n
    # traced collectives into ONE op moving an n-stack of the predicted
    # payload, so its operand dims factor as (batch..., payload...).
    # Only leading-dim splits are candidates — element divisibility
    # alone would let a GENUINE unpredicted op (a reshard leak whose
    # size coincidentally equals k*e of some bucket) be consumed as
    # batching, masking exactly the error class the gate exists for.
    unmatched: List[_Unit] = []
    for u in leftovers:
        candidates = []
        for j in range(1, len(u.dims) + 1):
            k = int(np.prod(u.dims[:j], dtype=np.int64))
            e = int(np.prod(u.dims[j:], dtype=np.int64))
            if k > 1 and remaining.get((u.kind, u.axis, e), 0) >= k:
                candidates.append((e, k))
        if candidates:
            # smallest payload = largest batch factor: vmap batches the
            # WHOLE microbatch loop, so the right bucket is the one this
            # op covers k=n_micro times over — a larger-e candidate is a
            # coincidental split (seen: a (4,1,32) CE-stats op is 4x32,
            # not 2x64 of an unrelated layernorm bucket)
            e, k = min(candidates)
            key = (u.kind, u.axis, e)
            remaining[key] -= k
            consumed_any[key] = True
            matched.append(u)
        else:
            unmatched.append(u)

    # stage 3 — emitted-but-never-predicted: the gate's raison d'etre
    for u in unmatched:
        instr = u.instr
        is_reshard = (
            not instr.source_file or "sharding_constraint" in instr.op_name
        )
        is_transpose = "transpose(" in instr.op_name
        data = {
            "op": u.kind, "axis": u.axis, "elements": u.elements,
            "hlo_bytes": u.nbytes, "hlo_dtype": u.dtype,
            "groups": len(instr.replica_groups),
            "group_size": (
                instr.group_size or int(np.prod(
                    [s for _, s in mesh.shape.items()], dtype=np.int64))
            ),
        }
        if instr.kind == "collective-permute":
            data["pairs"] = len(instr.source_target_pairs)
        if instr.channel_id is not None:
            data["channel_id"] = instr.channel_id
        if is_reshard:
            shardings = _entry_sharding_summary(module)
            suggestion = _reshard_suggestion(module, u)
            findings.append(Finding(
                rule="comms.reshard",
                message=(
                    f"partitioner-inserted {u.kind} over {u.axis!r} "
                    f"({u.elements} el, {u.nbytes} B {u.dtype}) with no "
                    f"ledger prediction: XLA reshards at a jit/shard_map "
                    f"boundary; non-replicated entry shardings: "
                    f"{'; '.join(shardings) or '(none annotated)'}"
                    f"{'; ' + suggestion if suggestion else ''}"
                ),
                site=_site(instr, target), severity=SEV_ERROR,
                target=target,
                data=dict(data, entry_shardings=shardings,
                          suggestion=suggestion),
            ))
        else:
            why = (
                "transpose-synthesized backward collective the ledger "
                "cannot see (no custom_vjp pairing on the forward)"
                if is_transpose else
                "resharding leak or uninstrumented collective"
            )
            findings.append(Finding(
                rule="comms.unpredicted",
                message=(
                    f"XLA emitted {u.kind} over {u.axis!r} "
                    f"({u.elements} el, {u.nbytes} B {u.dtype}) that "
                    f"matches no ledger prediction — {why}"
                ),
                site=_site(instr, target), severity=SEV_ERROR,
                target=target, data=dict(data, transpose=is_transpose),
            ))

    # stage 4 — predicted-but-not-emitted
    for key, n in sorted(remaining.items(), key=str):
        if n <= 0:
            continue
        cls, axis, elements = key
        if consumed_any.get(key) or key in emitted_keys:
            findings.append(Finding(
                rule="comms.folded",
                message=(
                    f"{n} predicted {cls} over {axis!r} ({elements} el) "
                    f"beyond what XLA emitted — CSE/combining folded "
                    f"duplicate reductions (bookkeeping, not a defect)"
                ),
                site=site0, severity=SEV_INFO, target=target, count=n,
                data={"op": cls, "axis": axis, "elements": elements},
            ))
        else:
            findings.append(Finding(
                rule="comms.vanished",
                message=(
                    f"{n} predicted {cls} over {axis!r} ({elements} el) "
                    f"never appear in the optimized HLO — dead traffic "
                    f"the program should stop asking for"
                ),
                site=site0, severity=SEV_WARNING, target=target, count=n,
                data={"op": cls, "axis": axis, "elements": elements},
            ))

    # stage 5 — POSITIVE confirmation of the quantized-collective pattern
    # (parallel/compress.py): 8-bit-payload collectives that matched a
    # ledger prediction are reported per axis, so "the int8 pattern was
    # verified as emitted" is a record in the stream rather than the
    # absence of an error. Info severity: confirmation, not a defect.
    quantized: Dict[str, Dict[str, int]] = {}
    for u in matched:
        if not u.dtype.startswith(("s8", "u8", "f8")):
            continue
        d = quantized.setdefault(u.axis, {"ops": 0, "bytes": 0})
        d["ops"] += 1
        d["bytes"] += u.nbytes
    for axis, d in sorted(quantized.items()):
        findings.append(Finding(
            rule="comms.quantized",
            message=(
                f"quantized collective pattern verified over {axis!r}: "
                f"{d['ops']} 8-bit-payload op(s), {d['bytes']} wire "
                f"payload bytes, all matched to ledger predictions"
            ),
            site=site0, severity=SEV_INFO, target=target,
            data={"axis": axis, "ops": d["ops"], "bytes": d["bytes"]},
        ))

    # stage 6 — POSITIVE confirmation of async -start/-done emission:
    # matched collectives XLA split into start/done pairs are overlappable
    # by its latency-hiding scheduler. Per (axis, op class) so "the
    # prefetched gathers were emitted async with predicted==emitted
    # bytes" is a record in the stream, not the absence of an error.
    async_matched: Dict[Tuple[str, str], Dict[str, int]] = {}
    for u in matched:
        if not u.instr.is_async:
            continue
        d = async_matched.setdefault((u.axis, u.kind), {"ops": 0, "bytes": 0})
        d["ops"] += 1
        d["bytes"] += u.nbytes
    for (axis, kind), d in sorted(async_matched.items()):
        findings.append(Finding(
            rule="comms.async",
            message=(
                f"async overlap pattern verified over {axis!r}: "
                f"{d['ops']} {kind} op(s) emitted as -start/-done pairs, "
                f"{d['bytes']} payload bytes, all matched to ledger "
                f"predictions (predicted == emitted)"
            ),
            site=site0, severity=SEV_INFO, target=target,
            data={"axis": axis, "op": kind, "ops": d["ops"],
                  "bytes": d["bytes"]},
        ))
    return findings


@jaxpr_pass("hlo-comms")
def hlo_comms_pass(ctx) -> List[Finding]:
    """The registered-pass wrapper: reuses the target's shared AOT
    compile AND its parsed module (one ``.lower().compile()`` + one
    text/parse serve donation + both HLO passes)."""
    if ctx.mesh is None:
        return []
    _, compiled = ctx.aot()
    try:
        module = ctx.hlo_module()
    except ValueError:
        module = None  # audit_comms re-parses and reports unverifiable
    return audit_comms(
        ctx.fn, *ctx.args, mesh=ctx.mesh,
        donate_argnums=ctx.donate_argnums, target=ctx.name,
        compiled=compiled, module=module,
    )
