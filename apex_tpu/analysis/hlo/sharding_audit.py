"""Entry-sharding auditor: big replicated buffers on a parallel mesh.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336) is about exactly this failure shape: state
that COULD be sharded across a >1-sized mesh axis sitting fully
replicated on every device, multiplying HBM and (for the weight-update
all-gathers XLA then inserts) wire traffic. The compiled entry
computation states the verdict precisely — every parameter and result
carries its final ``sharding={...}`` — so this pass reads the optimized
HLO (hlo/parser.py) and flags:

- ``sharding.replicated-param``  (warning) — an entry parameter of
  >= ``min_bytes`` (default 1 MiB) left fully replicated although the
  mesh has a >1-sized axis to shard it over;
- ``sharding.replicated-output`` (warning) — same for entry results
  (only when the ROOT carries sharding annotations);
- ``sharding.unverifiable``     (info) — the ROOT carries NO sharding
  annotations while at least one entry result is >= ``min_bytes``:
  output replication was NOT audited. Degrade-loudly, the comms and
  donation passes' convention — "nobody looked" must be
  distinguishable from "clean" (no guessing either way).

Small buffers are exempt on purpose (a replicated layernorm bias is
correct engineering, not a leak), and a mesh with no >1 axis has
nothing to shard over, so the pass is silent there. Intentionally
replicated large state (e.g. non-ZeRO data-parallel optimizer moments)
is exactly what the reason-carrying allowlist is for.
"""

from typing import List

from apex_tpu.analysis.findings import Finding, SEV_INFO, SEV_WARNING
from apex_tpu.analysis.hlo import parser as hlo_parser
from apex_tpu.analysis.passes import jaxpr_pass

__all__ = ["audit_entry_shardings", "hlo_sharding_pass", "DEFAULT_MIN_BYTES"]

#: buffers below this are not worth sharding (threshold shared with the
#: donation auditor's "not worth donating" floor)
DEFAULT_MIN_BYTES = 1 << 20


def audit_entry_shardings(
    module_or_compiled,
    mesh,
    min_bytes: int = DEFAULT_MIN_BYTES,
    target: str = "",
) -> List[Finding]:
    """Flag >= ``min_bytes`` fully-replicated entry params/outputs; see
    the module docstring. ``module_or_compiled`` is a parsed
    :class:`~apex_tpu.analysis.hlo.parser.HloModule`, a ``Compiled``
    stage, or HLO text."""
    if mesh is None:
        return []
    shape = dict(mesh.shape)
    live = [n for n in mesh.axis_names if shape[n] > 1]
    if not live:
        return []  # nothing to shard over
    if isinstance(module_or_compiled, hlo_parser.HloModule):
        module = module_or_compiled
    else:
        try:
            module = hlo_parser.parse_hlo_module(
                hlo_parser.module_text(module_or_compiled)
            )
        except ValueError:
            # absence of evidence, no guessing — the comms differ
            # reports the parse failure loudly (comms.unverifiable)
            return []
    findings: List[Finding] = []
    axes = ",".join(live)
    for p in module.entry_params:
        if p.nbytes < min_bytes:
            continue
        if p.sharding is not None and p.sharding.fully_replicated:
            findings.append(Finding(
                rule="sharding.replicated-param",
                message=(
                    f"entry parameter {p.label or p.name} "
                    f"({p.shape}, {p.nbytes} B) is fully replicated on a "
                    f"mesh with >1-sized axes ({axes}) — shard it or "
                    f"allowlist the replication with its reason"
                ),
                site=f"<hlo:{target or module.name}>",
                severity=SEV_WARNING, target=target,
                data={"param": p.label or p.name, "bytes": p.nbytes,
                      "index": p.index},
            ))
    shardings = module.entry_root_shardings
    if not shardings:
        outs = module.entry_root_shapes
        big = [o for o in outs if o.nbytes >= min_bytes]
        if big:
            findings.append(Finding(
                rule="sharding.unverifiable",
                message=(
                    f"entry ROOT carries no sharding annotations — "
                    f"{len(big)} result(s) >= {min_bytes} B NOT audited "
                    f"for replication (outputs unverified, not clean)"
                ),
                site=f"<hlo:{target or module.name}>",
                severity=SEV_INFO, target=target,
                data={"outputs": len(big)},
            ))
    else:
        outs = module.entry_root_shapes
        # a single sharding annotation on a tuple ROOT applies to all
        if len(shardings) == 1 and len(outs) > 1:
            shardings = shardings * len(outs)
        for oi, (out, sh) in enumerate(zip(outs, shardings)):
            if out.nbytes < min_bytes or sh is None:
                continue
            if sh.fully_replicated:
                findings.append(Finding(
                    rule="sharding.replicated-output",
                    message=(
                        f"entry output #{oi} ({out}, {out.nbytes} B) is "
                        f"fully replicated on a mesh with >1-sized axes "
                        f"({axes}) — shard it or allowlist the "
                        f"replication with its reason"
                    ),
                    site=f"<hlo:{target or module.name}>",
                    severity=SEV_WARNING, target=target,
                    data={"output": oi, "bytes": out.nbytes},
                ))
    return findings


@jaxpr_pass("hlo-sharding")
def hlo_sharding_pass(ctx) -> List[Finding]:
    """Registered-pass wrapper over the shared AOT compile + parse."""
    if ctx.mesh is None:
        return []
    try:
        module = ctx.hlo_module()
    except ValueError:
        return []  # the comms differ reports the parse failure
    return audit_entry_shardings(
        module, ctx.mesh,
        min_bytes=ctx.target.sharding_min_bytes or DEFAULT_MIN_BYTES,
        target=ctx.name,
    )
