"""The repo's single HLO/MLIR text scraper: brace-aware, nesting-safe.

XLA's optimized HLO text is the ground truth of what the compiler
actually emitted — realized donation aliases (donation.py), the real
collective inventory (comms_diff.py), entry parameter/output shardings
(sharding_audit.py). Scraping it with ad-hoc regexes scattered across
passes rots fast (the old ``donation._realized_aliases`` matched the
first ``}`` it saw), so ALL ``.as_text()`` parsing lives here and the
``lint.hlo-text`` rule forbids it anywhere else; callers hand this
module the ``Lowered``/``Compiled`` object (or its text) and get
structured records back.

What the parser understands, and deliberately nothing more:

- module header: ``input_output_alias={...}`` (nesting-safe),
- computations: ``%name (...) -> ... {`` / ``ENTRY %name ... {`` blocks,
  so a collective inside a while-loop body is still found (it appears
  once in text however many times the loop runs — callers own that
  caveat),
- collective instructions (``all-reduce`` / ``all-gather`` /
  ``reduce-scatter`` / ``collective-permute`` / ``all-to-all``, sync or
  ``-start`` async forms; ``-done`` halves are skipped) with operand
  shapes/dtypes, ``replica_groups`` (literal ``{{0,1},{2,3}}`` or iota
  ``[2,2]<=[4]`` form; collective-permute prints
  ``source_target_pairs={{src,dst},...}`` instead and is captured as
  such), ``channel_id``, and the ``metadata={op_name=...
  source_file=... source_line=N}`` provenance XLA carries through,
- entry parameters and the entry ROOT with their ``sharding={...}``
  annotations and jax's human labels (``params['params'][...]``).

Byte conventions match the xray ledger's (the differ depends on it):
a collective's payload is its OPERAND — for all-gather the local shard,
for reduce-scatter the full pre-scatter array. Element counts, not
bytes, are the cross-checking currency: backends legalize dtypes (CPU
XLA widens bf16 collectives to f32) without changing element counts.
"""

import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "HloShape",
    "HloSharding",
    "HloOperand",
    "HloCollective",
    "HloParam",
    "HloModule",
    "COLLECTIVE_KINDS",
    "module_text",
    "parse_hlo_module",
    "balanced",
    "parse_iota_list",
    "realized_aliases",
    "mlir_main_signature",
    "mlir_marked_aliases",
]

#: HLO collective opcodes the parser extracts (the sync spellings; the
#: async ``-start`` forms normalize onto these and ``-done`` is skipped)
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "s32": 4, "u32": 4, "s64": 8, "u64": 8, "f8e4m3fn": 1, "f8e5m2": 1,
    "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "bf16": 2,
    "f16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
}


def module_text(obj) -> str:
    """The HLO/MLIR text of ``obj``: a ``jax.stages.Lowered`` /
    ``Compiled`` (or anything with ``.as_text()``), or a plain string
    passed through. The ONE place ``.as_text`` is called (lint.hlo-text
    pins that)."""
    if isinstance(obj, str):
        return obj
    if not hasattr(obj, "as_text"):
        raise TypeError(
            f"expected HLO text or an object with .as_text(), got "
            f"{type(obj).__name__}"
        )
    return obj.as_text()


def balanced(text: str, start: int, open_ch: str = "{",
             close_ch: str = "}") -> Tuple[str, int]:
    """The contents of the bracketed section whose opener is at
    ``text[start]``, nesting-safe. Returns ``(body, end_index)`` where
    ``end_index`` points at the closer; raises on malformed input.
    Double-quoted strings are opaque: a bracket inside a quoted
    metadata value (e.g. an ``op_name`` from a user ``named_scope``
    containing ``{``, carried verbatim by XLA) neither opens nor
    closes anything."""
    if start >= len(text) or text[start] != open_ch:
        raise ValueError(
            f"expected {open_ch!r} at index {start}, found "
            f"{text[start:start + 1]!r}"
        )
    depth = 0
    i, n = start, len(text)
    while i < n:
        c = text[i]
        if c == '"':
            i += 1
            while i < n and text[i] != '"':
                i += 2 if text[i] == "\\" else 1
        elif c == open_ch:
            depth += 1
        elif c == close_ch:
            depth -= 1
            if depth == 0:
                return text[start + 1:i], i
        i += 1
    raise ValueError(f"unbalanced {open_ch!r} section at index {start}")


def parse_iota_list(dims: Sequence[int], reshape: Sequence[int],
                    transpose: Optional[Sequence[int]] = None) -> List[List[int]]:
    """Expand XLA's iota shorthand ``[dims]<=[reshape]`` (optionally
    ``T(transpose)``): ``iota(prod(reshape)).reshape(reshape)
    .transpose(t).reshape(dims)``, returned as ``dims[0]`` rows of
    ``prod(dims[1:])`` ids each — for ``replica_groups=[G,S]<=[...]``
    that is G groups of S devices."""
    import numpy as np

    n = int(np.prod(reshape, dtype=np.int64))
    arr = np.arange(n).reshape(tuple(reshape))
    if transpose is not None:
        arr = arr.transpose(tuple(transpose))
    arr = arr.reshape(tuple(dims))
    if arr.ndim == 1:
        return [arr.tolist()]
    return arr.reshape(dims[0], -1).tolist()


@dataclasses.dataclass(frozen=True)
class HloShape:
    dtype: str
    dims: Tuple[int, ...]

    @property
    def elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.elements * _DTYPE_BYTES.get(self.dtype, 4)

    def __str__(self) -> str:
        return f"{self.dtype}[{','.join(str(d) for d in self.dims)}]"


_SHAPE_RE = re.compile(r"\b([a-z]\d*[a-z0-9]*)\[([\d,]*)\]")


def _parse_shapes(text: str) -> List[HloShape]:
    out = []
    for m in _SHAPE_RE.finditer(text):
        dtype = m.group(1)
        if dtype not in _DTYPE_BYTES and dtype != "token":
            continue
        dims = tuple(int(d) for d in m.group(2).split(",") if d)
        out.append(HloShape(dtype, dims))
    return out


@dataclasses.dataclass(frozen=True)
class HloSharding:
    """One ``sharding={...}`` annotation, as much as the auditors need:
    is the value fully replicated, and over how many tile dims is it
    actually split."""

    raw: str
    replicated: bool = False
    maximal: bool = False
    tile_dims: Tuple[int, ...] = ()
    last_tile_dim_replicate: bool = False

    @property
    def fully_replicated(self) -> bool:
        """True when every device holds the whole value: ``replicated``,
        or a ``devices=[...]`` assignment whose every data tile dim is 1
        (all the fan-out sits in a trailing replicate dim)."""
        if self.replicated:
            return True
        if self.maximal or not self.tile_dims:
            return False
        data_dims = (
            self.tile_dims[:-1] if self.last_tile_dim_replicate
            else self.tile_dims
        )
        return all(d == 1 for d in data_dims)


_TILE_RE = re.compile(r"devices=\[([\d,]+)\]")


def parse_sharding(raw: str) -> HloSharding:
    raw = raw.strip()
    if raw == "replicated":
        return HloSharding(raw=raw, replicated=True)
    if raw.startswith("maximal"):
        return HloSharding(raw=raw, maximal=True)
    m = _TILE_RE.search(raw)
    dims = tuple(int(d) for d in m.group(1).split(",")) if m else ()
    return HloSharding(
        raw=raw, tile_dims=dims,
        last_tile_dim_replicate="last_tile_dim_replicate" in raw,
    )


@dataclasses.dataclass(frozen=True)
class HloOperand:
    shape: HloShape

    @property
    def elements(self) -> int:
        return self.shape.elements

    @property
    def nbytes(self) -> int:
        return self.shape.nbytes


@dataclasses.dataclass(frozen=True)
class HloCollective:
    """One collective instruction of the module (any computation).

    ``replica_groups`` is how every collective EXCEPT collective-permute
    spells its participants; permutes instead print
    ``source_target_pairs={{src,dst},...}`` (captured in
    ``source_target_pairs``, with ``replica_groups`` left empty).

    ``is_async`` marks the ``-start`` spelling: XLA split the op into a
    ``-start``/``-done`` pair, i.e. the scheduler may overlap its wire
    time with compute between the halves — the emitted-HLO evidence the
    overlap-aware schedules' proof loop reads (the timeline analyzer
    fuses the same pairs into in-flight intervals on the measured
    side)."""

    kind: str  # one of COLLECTIVE_KINDS
    name: str  # %all-reduce.50
    computation: str
    result: HloShape
    operands: Tuple[HloOperand, ...]
    replica_groups: Tuple[Tuple[int, ...], ...]  # () == one group of all
    channel_id: Optional[int]
    op_name: str
    source_file: str
    source_line: int
    line: int  # 1-based line in the module text
    source_target_pairs: Tuple[Tuple[int, int], ...] = ()
    is_async: bool = False  # emitted as a -start/-done pair

    @property
    def group_size(self) -> int:
        """Devices per replica group (0 when the groups are implicit
        'everyone' — the caller supplies the device count)."""
        return len(self.replica_groups[0]) if self.replica_groups else 0

    @property
    def elements(self) -> int:
        """Total operand elements — the ledger-convention payload."""
        return sum(op.elements for op in self.operands)

    @property
    def nbytes(self) -> int:
        return sum(op.nbytes for op in self.operands)


@dataclasses.dataclass(frozen=True)
class HloParam:
    """One entry-computation parameter."""

    index: int  # parameter(N) — the flat input-leaf position
    name: str  # %param.12
    shape: HloShape
    sharding: Optional[HloSharding]
    label: str  # jax's op_name metadata: params['params'][...]
    line: int

    @property
    def nbytes(self) -> int:
        return self.shape.nbytes


@dataclasses.dataclass
class HloModule:
    """The parsed module: what the HLO passes read."""

    name: str
    collectives: List[HloCollective]
    entry_params: List[HloParam]
    entry_root_shapes: List[HloShape]
    entry_root_shardings: Optional[List[HloSharding]]
    input_output_alias: Dict[int, int]  # param index -> output index
    entry_name: str = ""

    def collectives_in_entry(self) -> List[HloCollective]:
        return [c for c in self.collectives if c.computation == self.entry_name]


_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{")
_PAIRS_RE = re.compile(r"source_target_pairs=\{")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_METADATA_RE = re.compile(r"metadata=\{")
_OP_NAME_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')
_SOURCE_FILE_RE = re.compile(r'source_file="((?:[^"\\]|\\.)*)"')
_SOURCE_LINE_RE = re.compile(r"source_line=(\d+)")
_SHARDING_RE = re.compile(r"sharding=\{")

#: instruction opener: ``  %name = type opcode(``  (ROOT optional)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.+)$"
)
_COMPUTATION_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*(?:\([^)]*\))?.*\{\s*$"
)
_PARAM_RE = re.compile(
    r"^\s*%(?P<name>[\w.\-]+)\s*=\s*(?P<type>\S+)\s+parameter\((?P<idx>\d+)\)"
)


def _parse_replica_groups(attrs: str) -> Tuple[Tuple[int, ...], ...]:
    m = _GROUPS_IOTA_RE.search(attrs)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        reshape = [int(d) for d in m.group(2).split(",")]
        transpose = (
            [int(d) for d in m.group(3).split(",")] if m.group(3) else None
        )
        return tuple(
            tuple(g) for g in parse_iota_list(dims, reshape, transpose)
        )
    m = _GROUPS_LITERAL_RE.search(attrs)
    if m is None:
        return ()
    body, _ = balanced(attrs, m.end() - 1)
    groups = []
    for gm in re.finditer(r"\{([\d,\s]*)\}", body):
        ids = tuple(int(x) for x in gm.group(1).split(",") if x.strip())
        groups.append(ids)
    return tuple(groups)


def _parse_source_target_pairs(attrs: str) -> Tuple[Tuple[int, int], ...]:
    """collective-permute's ``source_target_pairs={{src,dst},...}``."""
    m = _PAIRS_RE.search(attrs)
    if m is None:
        return ()
    body, _ = balanced(attrs, m.end() - 1)
    pairs = []
    for gm in re.finditer(r"\{(\d+)\s*,\s*(\d+)\}", body):
        pairs.append((int(gm.group(1)), int(gm.group(2))))
    return tuple(pairs)


def _parse_metadata(attrs: str) -> Tuple[str, str, int]:
    m = _METADATA_RE.search(attrs)
    if m is None:
        return "", "", 0
    body, _ = balanced(attrs, m.end() - 1)
    op = _OP_NAME_RE.search(body)
    sf = _SOURCE_FILE_RE.search(body)
    sl = _SOURCE_LINE_RE.search(body)
    return (
        op.group(1) if op else "",
        sf.group(1) if sf else "",
        int(sl.group(1)) if sl else 0,
    )


def _parse_sharding_attr(attrs: str) -> Optional[HloSharding]:
    m = _SHARDING_RE.search(attrs)
    if m is None:
        return None
    body, _ = balanced(attrs, m.end() - 1)
    return parse_sharding(body)


def _parse_tuple_shardings(attrs: str) -> Optional[List[HloSharding]]:
    """``sharding={{...}, {...}}`` on a tuple-shaped ROOT, or a single
    sharding applied to every leaf."""
    m = _SHARDING_RE.search(attrs)
    if m is None:
        return None
    body, _ = balanced(attrs, m.end() - 1)
    body = body.strip()
    if not body.startswith("{"):
        return [parse_sharding(body)]
    out, i = [], 0
    while i < len(body):
        if body[i] == "{":
            inner, end = balanced(body, i)
            out.append(parse_sharding(inner))
            i = end + 1
        else:
            i += 1
    return out


def realized_aliases(compiled_or_text) -> Dict[int, int]:
    """``{param_index: output_index}`` from the optimized HLO module's
    ``input_output_alias`` header (absent section = nothing realized).
    Nesting-safe: the section is extracted by brace matching, not
    first-``}``-wins."""
    text = module_text(compiled_or_text)
    m = re.search(r"input_output_alias=\{", text)
    if m is None:
        return {}
    section, _ = balanced(text, m.end() - 1)
    realized: Dict[int, int] = {}
    for mm in re.finditer(r"\{([\d ,]*)\}:\s*\((\d+)", section):
        out_idx = int(mm.group(1).split(",")[0]) if mm.group(1).strip() else 0
        realized[int(mm.group(2))] = out_idx
    return realized


def mlir_main_signature(lowered_or_text) -> Optional[str]:
    """The argument list of the lowered MLIR's public ``@main`` func, by
    paren matching (None when there is no such func)."""
    text = module_text(lowered_or_text)
    m = re.search(r"func\.func\s+public\s+@main\s*\(", text)
    if m is None:
        return None
    try:
        body, _ = balanced(text, m.end() - 1, "(", ")")
    except ValueError:
        return None
    return body


def mlir_marked_aliases(
    lowered_or_text,
) -> Tuple[Optional[Dict[int, Optional[int]]], int]:
    """``{param_index: output_index_or_None}`` for parameters jax marked
    donated in the lowered MLIR, plus the entry parameter count. jax
    spells the mark two ways: ``tf.aliasing_output = N`` when it matched
    the donated input to output N itself, or ``jax.buffer_donor = true``
    when it hands XLA the buffer and lets the compiler pick the alias
    (value None). ``(None, 0)`` when the signature cannot be found."""
    sig = mlir_main_signature(lowered_or_text)
    if sig is None:
        return None, 0
    marked: Dict[int, Optional[int]] = {}
    chunks = re.split(r"%arg(\d+)\s*:", sig)
    # chunks: [prefix, idx0, body0, idx1, body1, ...]
    nparams = 0
    for i in range(1, len(chunks) - 1, 2):
        param = int(chunks[i])
        nparams = max(nparams, param + 1)
        m = re.search(r"tf\.aliasing_output\s*=\s*(\d+)", chunks[i + 1])
        if m:
            marked[param] = int(m.group(1))
        elif re.search(r"jax\.buffer_donor\s*=\s*true", chunks[i + 1]):
            marked[param] = None
    return marked, nparams


def _iter_instructions(text: str) -> Iterator[Tuple[str, bool, int, str]]:
    """``(computation_name, in_entry, line_number, instruction_text)``
    tuples. Computation bodies open with ``%name (...) ... {`` or
    ``ENTRY ... {`` at column 0 and close with ``}`` at column 0."""
    comp, in_entry = "", False
    for lineno, line in enumerate(text.splitlines(), start=1):
        if line.startswith(("%", "ENTRY")):
            m = _COMPUTATION_RE.match(line)
            if m:
                comp, in_entry = m.group("name"), bool(m.group("entry"))
                continue
        if line.startswith("}"):
            comp, in_entry = "", False
            continue
        if comp and line.lstrip().startswith(("%", "ROOT")):
            yield (comp, in_entry, lineno, line)


#: opcode right before its operand parens: ``<type> opcode(`` — the type
#: may itself be a parenthesized tuple, so scan for the LAST name token
#: preceding a ``(`` from the front of the instruction body
_OPCODE_RE = re.compile(r"([a-z][\w\-]*)\(")


def _find_opcode(rest: str) -> Tuple[str, int]:
    """``(opcode, paren_index)`` of the instruction body after ``= ``.
    The opcode is the first bare identifier directly attached to a
    ``(``; result-type prefixes (shapes like ``f32[8,16]{1,0}`` or
    tuples of them) never put an identifier directly against a paren,
    so the only guard needed is against a bare dtype token."""
    for m in _OPCODE_RE.finditer(rest):
        tok = m.group(1)
        if tok in _DTYPE_BYTES:
            continue
        return tok, m.end() - 1
    return "", -1


def parse_hlo_module(compiled_or_text) -> HloModule:
    """Parse one HLO module's text into the structured form above."""
    text = module_text(compiled_or_text)
    name_m = re.search(r"HloModule\s+([\w.\-]+)", text)
    module = HloModule(
        name=name_m.group(1) if name_m else "",
        collectives=[],
        entry_params=[],
        entry_root_shapes=[],
        entry_root_shardings=None,
        input_output_alias=realized_aliases(text),
    )
    for comp, in_entry, lineno, instr in _iter_instructions(text):
        if in_entry:
            module.entry_name = comp
        m = _INSTR_RE.match(instr)
        if m is None:
            continue
        rest = m.group("rest")
        opcode, paren = _find_opcode(rest)
        if in_entry:
            pm = _PARAM_RE.match(instr)
            if pm:
                shapes = _parse_shapes(pm.group("type"))
                module.entry_params.append(HloParam(
                    index=int(pm.group("idx")),
                    name=f"%{pm.group('name')}",
                    shape=shapes[0] if shapes else HloShape("f32", ()),
                    sharding=_parse_sharding_attr(instr),
                    label=_parse_metadata(instr)[0],
                    line=lineno,
                ))
                continue
            if instr.lstrip().startswith("ROOT "):
                # the result type between `= ` and the opcode's paren
                module.entry_root_shapes = _parse_shapes(rest[:paren])
                module.entry_root_shardings = _parse_tuple_shardings(instr)
        kind = opcode
        if kind.endswith("-done"):
            continue
        is_async = kind.endswith("-start")
        if is_async:
            kind = kind[: -len("-start")]
        if kind not in COLLECTIVE_KINDS:
            continue
        operand_text, end = balanced(rest, paren, "(", ")")
        attrs = rest[end + 1:]
        op_name, source_file, source_line = _parse_metadata(attrs)
        result_shapes = _parse_shapes(rest[:paren])
        module.collectives.append(HloCollective(
            kind=kind,
            name=f"%{m.group('name')}",
            computation=comp,
            result=result_shapes[0] if result_shapes else HloShape("f32", ()),
            operands=tuple(
                HloOperand(s) for s in _parse_shapes(operand_text)
            ),
            replica_groups=_parse_replica_groups(attrs),
            source_target_pairs=_parse_source_target_pairs(attrs),
            channel_id=(
                int(_CHANNEL_RE.search(attrs).group(1))
                if _CHANNEL_RE.search(attrs) else None
            ),
            op_name=op_name,
            source_file=source_file,
            source_line=source_line,
            line=lineno,
            is_async=is_async,
        ))
    return module
