"""Trace-time static analysis: jaxpr auditors + AST lint, one framework.

The pre-flight validation layer (the TorchTitan-style "fail before the
first step" discipline, PAPERS.md): apex's correctness-by-construction
claims — mixed precision with chosen f32 islands, donation-friendly
state threading, hand-wired collectives — checked STATICALLY, at trace
time, on CPU, without executing a step. Four jaxpr passes over any step
function plus a unified source-lint framework, all reporting structured
:class:`Finding` records through a reason-carrying allowlist and the
shared telemetry schema (``kind="analysis"`` via monitor.MetricRouter):

- ``precision``   — unintended low->f32/f64 promotions (precision.py)
- ``donation``    — donate_argnums vs XLA's realized input/output
  aliasing, missed large donations (donation.py)
- ``collective``  — mesh-axis existence, ppermute permutation validity,
  pipeline-edge pairing (static deadlock), size-1 dead traffic
  (collectives.py)
- ``host-sync``   — callbacks / device->host transfers inside the
  compiled step (host_sync.py)
- ``hlo-comms``   — the ghost-collective differ: collectives in the
  OPTIMIZED HLO vs the xray ledger's trace-time prediction, with
  replica_groups attributed back to mesh axes (hlo/comms_diff.py)
- ``hlo-sharding``— >=1MiB entry params/outputs left fully replicated
  on a >1-sized mesh axis (hlo/sharding_audit.py)
- ``lint``        — raw-collective + registered-taps (migrated from the
  tier-1 tests) + jit-donate + float64 + hlo-text source rules
  (lint.py)
- ``concurrency`` — the static race/deadlock analyzer over the threaded
  host runtime (concurrency/): thread-root inventory, unguarded
  cross-root writes, lock-order cycles + blocking-under-lock,
  signal/atexit handler safety — pure AST, no jax import

CLI: ``python -m apex_tpu.analysis`` runs the AST rules over the tree
and the jaxpr passes over the in-repo GPT/BERT step builders on a CPU
dp2xtp2 mesh, exiting non-zero on unallowlisted findings. See
docs/analysis.md for the pass catalog and how to add a rule.

Attribute access is lazy (PEP 562): importing this package must not
initialize jax, so the CLI can force the 8-device CPU topology first.
"""

_EXPORTS = {
    # findings / allowlist (jax-free)
    "Finding": "findings",
    "AllowlistEntry": "findings",
    "Allowlist": "findings",
    "AnalysisResult": "findings",
    "SEV_ERROR": "findings",
    "SEV_WARNING": "findings",
    "SEV_INFO": "findings",
    "merge_findings": "findings",
    # jaxpr-pass framework
    "JAXPR_PASSES": "passes",
    "jaxpr_pass": "passes",
    "StepTarget": "passes",
    "StepContext": "passes",
    "iter_eqns": "passes",
    "eqn_site": "passes",
    "run_passes": "passes",
    # individual auditors
    "audit_donation": "donation",
    "audit_comms": "hlo",
    "audit_entry_shardings": "hlo",
    # lint framework (jax-free)
    "LINT_RULES": "lint",
    "lint_rule": "lint",
    "LintContext": "lint",
    "run_lint": "lint",
    "collect_sources": "lint",
    "LEDGERED_OPS": "lint",
    # concurrency passes (jax-free)
    "run_concurrency": "concurrency",
    "CONCURRENCY_PASSES": "concurrency",
    # repo allowlist + CLI targets
    "REPO_ALLOWLIST": "allowlist",
    "repo_allowlist": "allowlist",
    "dp2tp2_mesh": "targets",
    "gpt_step_target": "targets",
    "gpt_compressed_step_target": "targets",
    "bert_step_target": "targets",
    "all_targets": "targets",
}

__all__ = sorted(_EXPORTS) + [
    "findings", "passes", "precision", "donation", "collectives",
    "host_sync", "lint", "allowlist", "targets", "hlo", "concurrency",
]

_SUBMODULES = frozenset(__all__) - frozenset(_EXPORTS)


def __getattr__(name):
    import importlib

    if name in _EXPORTS:
        mod = importlib.import_module(f"apex_tpu.analysis.{_EXPORTS[name]}")
        return getattr(mod, name)
    if name in _SUBMODULES:
        return importlib.import_module(f"apex_tpu.analysis.{name}")
    raise AttributeError(f"module 'apex_tpu.analysis' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
