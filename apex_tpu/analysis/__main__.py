"""``python -m apex_tpu.analysis`` — the repo's static-analysis gate.

Runs the AST lint rules over apex_tpu/ + examples/ and the jaxpr passes
(precision / donation / collective-safety / host-sync) PLUS the
compiled-HLO passes (the hlo-comms ghost-collective differ and the
hlo-sharding replication auditor) over the in-repo GPT and BERT step
builders on a CPU dp2xtp2 mesh, PLUS the profiler trace-schema smoke
(a tiny real capture through the timeline analyzer,
analysis/trace_smoke.py — loud failure when a jax upgrade drifts
XProf's export), then applies the documented allowlist
(analysis/allowlist.py). Exit status:

- 0 — clean: every finding suppressed by a reason-carrying entry and no
  entry gone stale;
- 1 — unallowlisted findings (or stale allowlist entries) — the report
  lists each with rule, site, and message. In particular any collective
  in the optimized HLO that is neither matched to a ledger prediction
  nor allowlisted with a reason fails the gate.

No step executes: precision/collective/host-sync work on abstract
traces; the donation and HLO passes share ONE ``.lower().compile()``
per target (seconds for the tiny targets, CPU-safe). The tier-1
self-check (tests/test_analysis.py) runs this exact entry point and
asserts exit 0, so a PR introducing a silent promotion, a broken
donation, a resharding leak, or a stray ``debug.print`` in a step
fails fast.

Flags: ``--verbose`` also prints suppressed findings with their reasons;
``--json PATH`` appends every finding as a ``kind="analysis"`` record to
a jsonl (the shared MetricRouter schema); ``--skip-jaxpr`` /
``--skip-lint`` / ``--skip-timeline`` run part of the gate;
``--target gpt|bert`` restricts the jaxpr half.
"""

import argparse
import os
import sys


def _ensure_cpu_mesh_env():
    """Force the 8-virtual-device CPU topology BEFORE jax initializes its
    backends (the tests/conftest.py pattern): the audit mesh is dp2xtp2
    and must exist on any box, TPU attached or not."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="static analysis: jaxpr auditors + AST lint",
    )
    parser.add_argument("--verbose", "-v", action="store_true",
                        help="also print allowlisted findings with reasons")
    parser.add_argument("--json", default=None,
                        help="append kind='analysis' records to this jsonl")
    parser.add_argument("--skip-lint", action="store_true",
                        help="skip the AST lint rules")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="skip the jaxpr passes over the step targets")
    parser.add_argument("--skip-timeline", action="store_true",
                        help="skip the profiler trace-schema smoke check")
    parser.add_argument("--target",
                        choices=("gpt", "gpt-compressed", "bert", "gpt-pp"),
                        default=None,
                        help="audit only one step builder")
    args = parser.parse_args(argv)

    _ensure_cpu_mesh_env()

    from apex_tpu.analysis import allowlist as allowlist_mod
    from apex_tpu.analysis import lint as lint_mod

    findings = []
    if not args.skip_lint:
        findings.extend(lint_mod.run_lint())
    if not args.skip_jaxpr:
        from apex_tpu.analysis import passes as passes_mod
        from apex_tpu.analysis import targets as targets_mod

        mesh = targets_mod.dp2tp2_mesh()
        builders = {
            "gpt": targets_mod.gpt_step_target,
            # the int8 quantized dp allreduce variant: the differ must
            # CONFIRM the compressed pattern (comms.quantized), not
            # allowlist it away
            "gpt-compressed": targets_mod.gpt_compressed_step_target,
            "bert": targets_mod.bert_step_target,
            # LAST: the zero-bubble pipeline target builds its own
            # dp2xpp2 mesh, re-initializing the global parallel_state —
            # the differ audits its hand-written backward p2p edges and
            # prefetched ZeRO gathers with zero comms suppressions
            "gpt-pp": lambda _mesh: targets_mod.gpt_pp_step_target(),
        }
        names = [args.target] if args.target else list(builders)
        for name in names:
            target = builders[name](mesh)
            print(f"auditing step target {target.name!r} "
                  f"(mesh {dict(target.mesh.shape)})", flush=True)
            findings.extend(passes_mod.run_passes(target))
    if not args.skip_timeline:
        # trace-schema smoke (analysis/trace_smoke.py): a tiny REAL
        # profiler capture through the timeline analyzer, so a jax
        # upgrade that changes XProf's export fails the gate instead of
        # silently blinding every --profile-analyze run
        from apex_tpu.analysis.trace_smoke import timeline_smoke_findings

        print("timeline trace-schema smoke (2-step capture)", flush=True)
        findings.extend(timeline_smoke_findings())

    # stale-entry detection needs the full lint scan (a require_hit entry
    # trivially suppresses nothing when its rule never ran)
    result = allowlist_mod.repo_allowlist().apply(
        findings, check_stale=not args.skip_lint
    )
    print(result.format(verbose=args.verbose), flush=True)
    if args.json:
        from apex_tpu.monitor.router import JsonlSink

        sink = JsonlSink(args.json)
        for rec in result.to_records():
            sink.emit(rec)
        sink.close()
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
