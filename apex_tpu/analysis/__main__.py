"""``python -m apex_tpu.analysis`` — the repo's static-analysis gate.

Runs the AST lint rules over apex_tpu/ + examples/ and the jaxpr passes
(precision / donation / collective-safety / host-sync) PLUS the
compiled-HLO passes (the hlo-comms ghost-collective differ and the
hlo-sharding replication auditor) over the in-repo GPT and BERT step
builders on a CPU dp2xtp2 mesh, PLUS the profiler trace-schema smoke
(a tiny real capture through the timeline analyzer,
analysis/trace_smoke.py — loud failure when a jax upgrade drifts
XProf's export), PLUS the concurrency passes (the static race/deadlock
analyzer over the threaded host runtime, analysis/concurrency — thread
roots, unguarded cross-root writes, lock-order cycles,
blocking-under-lock, signal/atexit handler safety; pure AST, no jax),
then applies the documented allowlist (analysis/allowlist.py). Exit
status:

- 0 — clean: every finding suppressed by a reason-carrying entry and no
  entry gone stale;
- 1 — unallowlisted findings (or stale allowlist entries) — the report
  lists each with rule, site, and message. In particular any collective
  in the optimized HLO that is neither matched to a ledger prediction
  nor allowlisted with a reason fails the gate.

No step executes: precision/collective/host-sync work on abstract
traces; the donation and HLO passes share ONE ``.lower().compile()``
per target (seconds for the tiny targets, CPU-safe). The tier-1
self-check (tests/test_analysis.py) runs this exact entry point and
asserts exit 0, so a PR introducing a silent promotion, a broken
donation, a resharding leak, or a stray ``debug.print`` in a step
fails fast.

Flags: ``--verbose`` also prints suppressed findings with their reasons;
``--json PATH`` appends every finding as a ``kind="analysis"`` record to
a jsonl (the shared MetricRouter schema); ``--skip-jaxpr`` /
``--skip-lint`` / ``--skip-timeline`` / ``--skip-concurrency`` run part
of the gate; ``--target gpt|bert`` restricts the jaxpr half.

``--fix`` runs the AUTOFIX mode instead (analysis/autofix): for every
builder in ``targets.FIXABLE_TARGETS`` (library steps whose specs are
data — deliberately NOT part of the default gate, the seeded one would
fail it) it derives prescriptions from the pass findings, applies the
auto-appliable ones by rebuilding the target with injected specs /
donate tuples, and re-audits to a bounded fixpoint. User-code
prescriptions print as unified diffs, never edits. Exit 0 only when
every fixed target audits clean, nothing remains unapplied, AND the
apply is proven idempotent (the final round derives zero patches — a
second apply is a no-op). With ``--json`` each prescription is appended
as a ``kind="analysis"`` record carrying the machine-applicable
``fix=`` payload, plus a sentinel-gated ``kind="bench"`` twin of the
fixed target's predicted dp-axis wire bytes (``_bytes`` suffix =
lower-is-better for ``python -m apex_tpu.monitor.goodput --check``).
"""

import argparse
import os
import sys


def _ensure_cpu_mesh_env():
    """Force the 8-virtual-device CPU topology BEFORE jax initializes its
    backends (the tests/conftest.py pattern): the audit mesh is dp2xtp2
    and must exist on any box, TPU attached or not."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="static analysis: jaxpr auditors + AST lint",
    )
    parser.add_argument("--verbose", "-v", action="store_true",
                        help="also print allowlisted findings with reasons")
    parser.add_argument("--json", default=None,
                        help="append kind='analysis' records to this jsonl")
    parser.add_argument("--skip-lint", action="store_true",
                        help="skip the AST lint rules")
    parser.add_argument("--skip-jaxpr", action="store_true",
                        help="skip the jaxpr passes over the step targets")
    parser.add_argument("--skip-timeline", action="store_true",
                        help="skip the profiler trace-schema smoke check")
    parser.add_argument("--skip-concurrency", action="store_true",
                        help="skip the static race/deadlock passes over "
                             "the threaded host runtime")
    parser.add_argument("--target",
                        choices=("gpt", "gpt-compressed", "bert", "gpt-pp"),
                        default=None,
                        help="audit only one step builder")
    parser.add_argument("--fix", action="store_true",
                        help="autofix mode: derive + apply prescriptions "
                             "for the fixable step builders to a bounded "
                             "fixpoint (see module docstring)")
    args = parser.parse_args(argv)

    _ensure_cpu_mesh_env()

    if args.fix:
        return _run_fix(args)

    from apex_tpu.analysis import allowlist as allowlist_mod
    from apex_tpu.analysis import lint as lint_mod

    findings = []
    if not args.skip_lint:
        findings.extend(lint_mod.run_lint())
    if not args.skip_concurrency:
        # static race/deadlock passes (analysis/concurrency): pure AST
        # over the whole package — thread-root inventory, shared-state
        # audit, lock-order graph, handler safety. No jax import, no
        # execution; runs before the jaxpr half so a host-runtime race
        # reports even when tracing fails.
        from apex_tpu.analysis.concurrency import run_concurrency

        print("concurrency passes (static race/deadlock analyzer)",
              flush=True)
        findings.extend(run_concurrency())
    if not args.skip_jaxpr:
        from apex_tpu.analysis import passes as passes_mod
        from apex_tpu.analysis import targets as targets_mod

        mesh = targets_mod.dp2tp2_mesh()
        builders = {
            "gpt": targets_mod.gpt_step_target,
            # the int8 quantized dp allreduce variant: the differ must
            # CONFIRM the compressed pattern (comms.quantized), not
            # allowlist it away
            "gpt-compressed": targets_mod.gpt_compressed_step_target,
            "bert": targets_mod.bert_step_target,
            # LAST: the zero-bubble pipeline target builds its own
            # dp2xpp2 mesh, re-initializing the global parallel_state —
            # the differ audits its hand-written backward p2p edges and
            # prefetched ZeRO gathers with zero comms suppressions
            "gpt-pp": lambda _mesh: targets_mod.gpt_pp_step_target(),
        }
        names = [args.target] if args.target else list(builders)
        for name in names:
            target = builders[name](mesh)
            print(f"auditing step target {target.name!r} "
                  f"(mesh {dict(target.mesh.shape)})", flush=True)
            findings.extend(passes_mod.run_passes(target))
    if not args.skip_timeline:
        # trace-schema smoke (analysis/trace_smoke.py): a tiny REAL
        # profiler capture through the timeline analyzer, so a jax
        # upgrade that changes XProf's export fails the gate instead of
        # silently blinding every --profile-analyze run
        from apex_tpu.analysis.trace_smoke import timeline_smoke_findings

        print("timeline trace-schema smoke (2-step capture)", flush=True)
        findings.extend(timeline_smoke_findings())

    # stale-entry detection needs the full complete-scan halves (a
    # require_hit entry trivially suppresses nothing when its rule never
    # ran) — both the lint rules and the concurrency passes are
    # whole-package scans with require_hit entries
    result = allowlist_mod.repo_allowlist().apply(
        findings, check_stale=not (args.skip_lint or args.skip_concurrency)
    )
    print(result.format(verbose=args.verbose), flush=True)
    if args.json:
        from apex_tpu.monitor.router import JsonlSink

        sink = JsonlSink(args.json)
        for rec in result.to_records():
            sink.emit(rec)
        sink.close()
    return 0 if result.ok else 1


def _run_fix(args) -> int:
    """The ``--fix`` leg: autofix every FIXABLE_TARGETS builder to its
    audit fixpoint. Exit contract (the idempotence gate): 0 iff every
    target ends clean with no unapplied prescriptions and the final
    round proved a second apply is a no-op."""
    from apex_tpu.analysis import allowlist as allowlist_mod
    from apex_tpu.analysis import targets as targets_mod
    from apex_tpu.analysis.autofix import apply_fixes, render_user_diff

    allow = allowlist_mod.repo_allowlist()
    mesh = targets_mod.dp2tp2_mesh()
    ok = True
    records = []
    for name, builder in targets_mod.FIXABLE_TARGETS.items():
        target = builder(mesh)
        print(f"autofixing step target {target.name!r} "
              f"(mesh {dict(target.mesh.shape)})", flush=True)
        report = apply_fixes(target, allowlist=allow)
        for line in report.describe():
            print(line, flush=True)
        diff = render_user_diff(report.manual)
        if diff:
            print(diff, flush=True)
        if not report.ok or report.manual:
            ok = False
            why = report.reason or (
                f"{len(report.manual)} prescription(s) remain unapplied"
                if report.manual else
                ("apply did not reach a clean fixpoint"
                 if not report.idempotent else "residual findings")
            )
            print(f"[autofix] {name}: FAILED — {why}", flush=True)
        if args.json:
            fins = [p.to_finding()
                    for p in report.applied + report.manual]
            result = allow.apply(fins, check_stale=False)
            records.extend(result.to_records())
            if report.axis and report.ledger_after:
                from apex_tpu.monitor.router import make_record

                # the sentinel gates "_bytes" lower-is-better: a
                # regression that re-replicates the weight update shows
                # up as this number doubling
                records.append(make_record(
                    "bench", 0,
                    metric=(f"autofix_{target.name.replace('-', '_')}_"
                            f"{report.axis}_ici_bytes"),
                    value=float(report.ledger_after.get("ici_bytes", 0)),
                    unit="B", platform="cpu",
                ))
    if args.json and records:
        from apex_tpu.monitor.router import JsonlSink

        sink = JsonlSink(args.json)
        for rec in records:
            sink.emit(rec)
        sink.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
