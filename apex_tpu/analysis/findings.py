"""Structured findings + reason-carrying allowlist for static analysis.

Every auditor in ``apex_tpu.analysis`` — the jaxpr passes (precision,
donation, collective-safety, host-sync) and the AST/token lint rules —
reports through the same :class:`Finding` record, so one consumer (the
CLI, a test, a jsonl tailer) handles them all uniformly:

    Finding(rule="precision.promotion",
            site="apex_tpu/ops/layer_norm.py:52",
            message="bfloat16 -> float32", ...)

``rule`` is a dotted id (``<pass>.<check>``); ``site`` is a repo-relative
``file.py:line`` (jaxpr findings resolve it from the equation's
source-info traceback, lint findings from the scanned file); ``target``
names the traced step for jaxpr findings ("" for lint).

Suppression is by :class:`Allowlist` only, and every entry CARRIES ITS
REASON — a bare "this is fine" entry is a constructor error. The repo's
own entries live in ``apex_tpu/analysis/allowlist.py``; an entry that no
longer suppresses anything is reported stale (``require_hit=True``), the
same no-rot contract as the registered-taps lint.

Findings export to the shared telemetry schema as ``kind="analysis"``
records (:func:`to_records` -> ``monitor.MetricRouter``), so analysis
results can join the metrics/anomaly/comms stream in one jsonl.
"""

import dataclasses
import fnmatch
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"
_SEVERITIES = (SEV_ERROR, SEV_WARNING, SEV_INFO)

__all__ = [
    "Finding",
    "AllowlistEntry",
    "Allowlist",
    "AnalysisResult",
    "SEV_ERROR",
    "SEV_WARNING",
    "SEV_INFO",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect (or intentional-but-flagged construct) an auditor found.

    ``data`` carries rule-specific structured fields (dtypes, argument
    paths, permutation edges) so tests can assert exact values instead of
    parsing messages. ``count`` folds repeated occurrences of the same
    (rule, site, data) — e.g. one cast line traced once per layer.
    """

    rule: str
    message: str
    site: str = ""
    severity: str = SEV_ERROR
    target: str = ""
    count: int = 1
    data: dict = dataclasses.field(default_factory=dict)
    #: a machine-applicable prescription attached to the finding (the
    #: autofix Patch serialized: kind, argnum/leaf, spec, site, reason,
    #: predicted wire-byte delta). None for plain diagnostics; consumers
    #: that only read ``data`` are unaffected.
    fix: Optional[dict] = None

    def __post_init__(self):
        if self.severity not in _SEVERITIES:
            raise ValueError(
                f"severity must be one of {_SEVERITIES}, got {self.severity!r}"
            )

    @property
    def key(self) -> Tuple:
        """Aggregation identity: same rule at the same site with the same
        structured data (and fix payload) is the same finding (counts
        add)."""
        return (
            self.rule, self.site, self.target,
            tuple(sorted((k, str(v)) for k, v in self.data.items())),
            str(self.fix) if self.fix else "",
        )

    def format(self) -> str:
        mult = f" x{self.count}" if self.count > 1 else ""
        tgt = f" [{self.target}]" if self.target else ""
        return (
            f"{self.severity:7s} {self.rule:28s} {self.site}{tgt}: "
            f"{self.message}{mult}"
        )


def merge_findings(findings: Iterable[Finding]) -> List[Finding]:
    """Fold findings with the same :attr:`Finding.key`, summing counts."""
    merged: Dict[Tuple, Finding] = {}
    for f in findings:
        prev = merged.get(f.key)
        if prev is None:
            merged[f.key] = f
        else:
            merged[f.key] = dataclasses.replace(
                prev, count=prev.count + f.count
            )
    return list(merged.values())


@dataclasses.dataclass(frozen=True)
class AllowlistEntry:
    """One documented suppression.

    - ``rule``: exact rule id, or a ``"prefix.*"`` glob.
    - ``match``: glob matched against the finding's ``site`` (a plain
      substring also works — it is wrapped in ``*...*``).
    - ``reason``: REQUIRED human explanation of why the flagged construct
      is intentional. Empty/whitespace reasons are a constructor error —
      the allowlist is documentation, not a mute button.
    - ``require_hit``: entries guarding a complete scan (the AST lint
      rules see every file every run) must keep suppressing something;
      when they stop, the entry is stale and reported. Jaxpr-pass entries
      default False: whether they fire depends on which step was traced.
    """

    rule: str
    match: str
    reason: str
    require_hit: bool = False

    def __post_init__(self):
        if not self.rule.strip():
            raise ValueError("allowlist entry needs a rule id")
        if not self.match.strip():
            raise ValueError(f"allowlist entry for {self.rule!r} needs a match")
        if not self.reason.strip():
            raise ValueError(
                f"allowlist entry {self.rule!r}/{self.match!r} has no reason "
                f"— bare entries are not allowed; say WHY it is intentional"
            )

    def matches(self, finding: Finding) -> bool:
        if self.rule.endswith(".*"):
            if not finding.rule.startswith(self.rule[:-1]):
                return False
        elif finding.rule != self.rule:
            return False
        pat = self.match if any(c in self.match for c in "*?[") else (
            f"*{self.match}*"
        )
        return fnmatch.fnmatch(finding.site, pat)


@dataclasses.dataclass
class AnalysisResult:
    """The outcome of applying an :class:`Allowlist` to raw findings."""

    findings: List[Finding]  # NOT allowlisted — these fail the run
    suppressed: List[Tuple[Finding, AllowlistEntry]]
    stale_entries: List[AllowlistEntry]

    @property
    def ok(self) -> bool:
        """Clean = no error/warning findings and no stale entries. Info
        findings (e.g. a donation audit that could not map parameters)
        are advisory: printed, never failing."""
        return not self.stale_entries and not any(
            f.severity != SEV_INFO for f in self.findings
        )

    def format(self, verbose: bool = False) -> str:
        lines = []
        for f in sorted(self.findings, key=lambda f: (f.rule, f.site)):
            lines.append(f.format())
        if verbose:
            for f, entry in sorted(
                self.suppressed, key=lambda p: (p[0].rule, p[0].site)
            ):
                lines.append(f"allowed {f.rule:28s} {f.site}: {entry.reason}")
        for entry in self.stale_entries:
            lines.append(
                f"stale   allowlist entry {entry.rule!r} / {entry.match!r} "
                f"suppressed nothing — remove it or restore the construct"
            )
        n_err = sum(1 for f in self.findings)
        lines.append(
            f"analysis: {n_err} finding(s), {len(self.suppressed)} "
            f"allowlisted, {len(self.stale_entries)} stale entr"
            f"{'y' if len(self.stale_entries) == 1 else 'ies'}"
        )
        return "\n".join(lines)

    def to_records(self, step: int = 0) -> List[dict]:
        """``kind="analysis"`` records in the shared MetricRouter schema
        (router.py module docstring) — one per finding, suppressed ones
        flagged with their reason."""
        from apex_tpu.monitor.router import make_record

        records = []
        for f in self.findings:
            extra = {f"data_{k}": v for k, v in f.data.items()}
            if f.fix is not None:
                extra["fix"] = f.fix
            records.append(make_record(
                "analysis", step, rule=f.rule, site=f.site, target=f.target,
                severity=f.severity, message=f.message, count=f.count,
                allowed=False, **extra,
            ))
        for f, entry in self.suppressed:
            extra = {"fix": f.fix} if f.fix is not None else {}
            records.append(make_record(
                "analysis", step, rule=f.rule, site=f.site, target=f.target,
                severity=f.severity, message=f.message, count=f.count,
                allowed=True, reason=entry.reason, **extra,
            ))
        return records


class Allowlist:
    """An ordered set of :class:`AllowlistEntry` applied to findings."""

    def __init__(self, entries: Sequence[AllowlistEntry] = ()):
        self.entries = list(entries)

    def __len__(self) -> int:
        return len(self.entries)

    def extended(self, entries: Sequence[AllowlistEntry]) -> "Allowlist":
        return Allowlist(self.entries + list(entries))

    def match(self, finding: Finding) -> Optional[AllowlistEntry]:
        for entry in self.entries:
            if entry.matches(finding):
                return entry
        return None

    def apply(
        self, findings: Iterable[Finding], check_stale: bool = True
    ) -> AnalysisResult:
        """Partition findings into kept/suppressed and detect stale
        ``require_hit`` entries. ``check_stale=False`` when the findings
        come from a partial run (a single pass or target) where an entry
        legitimately has nothing to suppress."""
        kept: List[Finding] = []
        suppressed: List[Tuple[Finding, AllowlistEntry]] = []
        hits = {id(e): 0 for e in self.entries}
        for f in merge_findings(findings):
            entry = self.match(f)
            if entry is None:
                kept.append(f)
            else:
                suppressed.append((f, entry))
                hits[id(entry)] += 1
        stale = [
            e for e in self.entries
            if check_stale and e.require_hit and hits[id(e)] == 0
        ]
        return AnalysisResult(kept, suppressed, stale)
