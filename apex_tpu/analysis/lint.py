"""Unified AST/token lint framework + the repo's source-level rules.

The source-scan half of ``apex_tpu.analysis``: rules that read the tree
instead of a trace. Same registry shape as the jaxpr passes and the
same :class:`~apex_tpu.analysis.findings.Finding`/allowlist machinery,
so the CLI and tests drive both identically:

    @lint_rule("lint.raw-collective", scopes=("apex_tpu/",))
    def raw_collective(ctx): yield Finding(...)

Rules see a :class:`LintContext` holding every scanned file (repo-
relative path -> source) and filter to their scope; cross-file rules
(registered-taps) see the whole set at once. Tests inject synthetic
``files`` to seed violations without touching disk.

The two tier-1 lints that predate this framework migrated here from
tests/test_monitor.py (which keeps thin wrappers so the test names and
their history stay legible):

- ``lint.raw-collective``  — no call site in apex_tpu/ may invoke
  ``lax.{psum,all_gather,...}`` directly; everything routes through the
  xray ledger wrappers or the comms report silently loses traffic.
  Token-based so docstrings mentioning ``jax.lax.psum`` don't trip it.
- ``lint.registered-taps`` — every ``sow("intermediates", <name>, ...)``
  must be registered in monitor/taps.py, and every registry row must
  still have a live sow site.

Plus the new rules this framework exists to host:

- ``lint.jit-donate`` — no raw ``jax.jit(donate_argnums=...)`` outside
  the audited entrypoints. Donation bugs are silent (see donation.py);
  keeping every donating jit on the audited list is what makes the
  donation auditor's coverage claim true.
- ``lint.float64``    — no ``jnp.float64`` in library code: TPUs emulate
  f64 at a fraction of rate, and a single f64 literal poisons every
  dtype downstream of it. (Host-side ``np.float64`` index math is fine
  and not flagged.)
- ``lint.prefetch-gather`` — no Python-``for``-loop-issued gather
  pipelines (``all_gather``/``psum_scatter`` called inside a ``for``
  body) outside the blessed home,
  ``optimizers/distributed_fused_adam.py``'s ``zero_prefetch_gather``.
  A loop of per-bucket collectives is a hand-rolled prefetch/overlap
  pipeline: its depth is a perf-critical knob that must come from the
  ICI roofline model (``choose_overlap_buckets``), its buckets must
  reconstruct the flat buffer exactly, and its gathers must stay
  ledger-routed — three invariants that drift the moment a second copy
  of the loop appears. Scan/vmap-issued collectives (one traced op) and
  straight-line repeated gathers are not flagged — only the
  loop-of-collectives fingerprint is. Reason-carrying allowlist entries
  only (the home carries a require_hit entry).
- ``lint.compressed-collective`` — no quantize/dequant + collective
  composition outside ``parallel/compress.py`` (the ledger-accounting
  home rule, same shape as ``lint.raw-collective``): a function that
  both calls a quantize/dequantize primitive AND a ledgered collective
  is building its own compressed collective, whose wire bytes/error-
  feedback/found_inf semantics then drift from the audited home.
  CALLING the blessed wrappers (``quantized_psum`` & co.) is fine and
  not flagged — only the composition of the primitives is.
- ``lint.hlo-text``   — no ``.as_text()`` scraping outside
  ``analysis/hlo/parser.py``: the brace-aware parser is the single home
  of HLO/MLIR text parsing (its ``module_text`` helper is the one
  blessed ``.as_text`` call site), so ad-hoc regexes over compiler
  output cannot quietly rot when XLA's printer changes.
- ``lint.memory-api`` — no raw ``.memory_stats()`` /
  ``.memory_analysis()`` outside the blessed hbm homes:
  ``monitor/xray/hbm/live.py`` owns the watermark probe
  (``device_watermarks`` — the one ``memory_stats`` call site, None
  when the backend reports nothing) and ``monitor/xray/hbm/report.py``
  owns the compile-product account (``report_from_compiled`` — the one
  ``memory_analysis`` call site). Scattered calls fork the
  None-vs-fake-zero convention and bypass the record schema the HBM
  x-ray emits; token-based like ``lint.hlo-text`` so a docstring
  naming the API does not trip it.
- ``lint.trace-file`` — no profiler trace-event reading outside
  ``monitor/xray/timeline/``: the ``.trace.json`` literal (the format's
  filename marker) in any string is the tell of an ad-hoc reader of
  ``jax.profiler`` output — the exact rot ``lint.hlo-text`` prevents
  for HLO text, applied to XProf's export. String-token based (a code
  COMMENT mentioning the format is fine; a docstring or glob pattern
  is a reader's fingerprint and routes to the shared parser).
- ``lint.signal-handlers`` — no raw ``signal.signal(...)`` registration
  outside the two blessed homes, ``utils/autoresume.py`` (the
  preemption flag + grace-budget anchor) and ``monitor/router.py`` (the
  best-effort span-flush teardown, which installs only over SIG_DFL so
  AutoResume keeps precedence). Scattered handlers silently overwrite
  each other — the last registration wins the whole process — and break
  the SIG_DFL-precedence contract those two homes coordinate on (PR 7);
  a third registrant must route through one of them.
- ``lint.thread-create`` — no raw ``threading.Thread(...)`` /
  ``threading.Timer(...)`` construction outside the three blessed
  homes: ``monitor/watchdog.py`` (the heartbeat/deadline monitor that
  OWNS thread lifecycle — named daemon threads, join-on-close, the
  ProfilerTrigger handshake), ``resilience/health/responder.py`` (the
  hard-exit escalation timer) and ``utils/checkpoint.py`` (the async
  checkpoint finalizer whose thread handle the autoresume handshake
  tracks). Every thread is a concurrency ROOT the static analyzer
  (``apex_tpu.analysis.concurrency``) must inventory and audit; a
  scattered ``Thread(target=...)`` adds an unaudited root with no
  join/daemon discipline and no allowlist proof. New background work
  routes through the watchdog's monitor loop or the checkpoint
  writer's finalize_async. ``from threading import Thread/Timer`` is
  flagged too (it hides the construction from the attribute match);
  locks, events and ``threading.current_thread`` reads are fine.
- ``lint.silent-except`` — no bare ``except:`` and no broad
  ``except Exception/BaseException:`` whose body does NOTHING (only
  ``pass``/``...``/``continue``) in library code. A silent broad swallow
  is how a failed span flush, a half-written checkpoint, or a dead sink
  becomes an invisible non-event; a broad handler that LOGS (or
  re-raises, or returns a fallback) is fine and not flagged. The two
  deliberate swallows — the router teardown and the profiler-abort
  guard, where failures have nowhere left to report — carry
  ``require_hit`` allowlist entries with exactly that reason.
- ``lint.nondeterminism`` — no unseeded process-global RNG reads
  (``random.random()``-style draws on the stdlib module singleton,
  ``np.random.*`` draws on numpy's global generator) and no wall-clock
  reads (``time.time``/``time.time_ns``) in library code. The replay
  subsystem's bitwise claim (resilience/replay) rests on every
  nondeterminism input being journaled; a stray singleton draw or a
  wall-clock branch inside step-path code is invisible to the journal
  and diverges unreproducibly. Seeded constructors
  (``np.random.RandomState(seed)``, ``random.Random(seed)``,
  ``default_rng``) and seeding calls are fine — they PIN determinism;
  monotonic clocks (``perf_counter``/``monotonic``) are durations, not
  inputs. The legitimate host-side homes — the retry jitter and the
  record-timestamp clock — carry require_hit allowlist entries with
  exactly those reasons.
- ``lint.process-exit`` — no raw ``os._exit(...)`` / ``sys.exit(...)``
  (or ``from os import _exit`` / ``from sys import exit``) in library
  code outside the blessed homes. The exit-code TAXONOMY is closed
  (``resilience/exit_codes.py``: incident 43, remediation restart
  44 / halt 45, replay divergence 2) and a supervisor BRANCHES on it —
  a stray exit call invents an undocumented code and, worse, ends the
  process without the teardown discipline (span flush, pending-save
  tombstone) the blessed paths guarantee. The exemption is structural
  for the CLI convention — a ``sys.exit`` lexically inside an
  ``if __name__ == "__main__":`` gate is how every ``__main__`` module
  returns its documented code — and allowlisted (require_hit, with the
  reason) for the one deliberate hard-exit home,
  ``resilience/health/responder.py``'s coordinated self-termination.
- ``lint.serving-clock`` — no bare ``time.monotonic()``/``time.time()``
  calls in ``apex_tpu/serving/`` scheduling paths: the serving stack's
  clock is INJECTED (``time_fn=`` on the engine and the fleet router) so
  deadline math, drain budgets and failover detection are drivable by a
  fake clock in tests and replayable in drills. A bare clock call
  splits time into two sources — the injected one the tests control and
  a hidden one they cannot — which is exactly how a deadline test goes
  flaky. Referencing ``time.monotonic`` as a DEFAULT (``time_fn=
  time.monotonic``) is the injection idiom and fine; ``perf_counter``
  duration measurements (EMA timings) are fine; ``time.sleep`` is not a
  clock read and fine.
- ``lint.trace-emit`` — no ad-hoc construction of ``kind="trace"`` /
  ``kind="slo"`` records outside the two blessed homes,
  ``serving/trace/emit.py`` (the span schema:
  trace/span/parent/phase/start/dur_s/attempt/site) and
  ``serving/trace/slo.py`` (the burn-rate row). The offline analyzer
  rebuilds causal trees and re-adds a digit-exact partition identity
  from those records — a second construction site would fork the
  schema, and the fork's spans would silently fail tree completeness
  or corrupt the partition. Flags ``event(...)``/``make_record(...)``
  calls whose kind is the literal ``"trace"``/``"slo"`` (positional or
  ``kind=``) and dict literals carrying ``"kind": "trace"/"slo"``;
  READING the kinds (comparisons, sink filters) is fine and not
  flagged. The homes carry require_hit allowlist entries.
- ``lint.span-phases`` — every goodput span call site
  (``span``/``begin_span``/``Span``/``emit_span`` and their import
  aliases) must name its phase with literals from the CLOSED registry
  ``monitor.goodput.spans.PHASES``. The goodput partition is only
  comparable across runs when every run buckets wall time the same way;
  an ad-hoc phase string fragments the taxonomy (the accountant would
  silently skip it), and a variable phase defeats the review-time
  check, so both are errors.
"""

import ast
import io
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from apex_tpu.analysis.findings import Finding, SEV_ERROR

__all__ = [
    "LINT_RULES",
    "lint_rule",
    "LintContext",
    "run_lint",
    "collect_sources",
    "LEDGERED_OPS",
    "SOW_RE",
]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: directories scanned by default, relative to the repo root
DEFAULT_SCAN_DIRS = ("apex_tpu", "examples")

#: registered rules: name -> (fn, scopes)
LINT_RULES: Dict[str, Tuple[Callable, Tuple[str, ...]]] = {}

#: collectives the xray ledger instruments (monitor/xray/ledger.py) — the
#: ops the raw-collective rule polices
LEDGERED_OPS = frozenset({
    "psum", "psum_scatter", "all_gather", "all_to_all", "ppermute",
    "pmean", "pmax", "pmin",
})

SOW_RE = re.compile(
    r"""\.sow\(\s*['"]intermediates['"]\s*,\s*['"](?P<name>\w+)['"]"""
)


def lint_rule(name: str, scopes: Tuple[str, ...] = ("apex_tpu/",)):
    """Register a rule (decorator). ``scopes`` are path prefixes the rule
    applies to — the single source of truth: ``run_lint`` hands the rule
    a context containing ONLY files under them, so rule bodies iterate
    ``ctx.files`` without re-filtering."""

    def register(fn):
        LINT_RULES[name] = (fn, scopes)
        return fn

    return register


class LintContext:
    """The scanned file set a rule reads."""

    def __init__(self, files: Dict[str, str]):
        #: repo-relative posix path -> source text
        self.files = files

    def files_in(self, *prefixes: str) -> Iterator[Tuple[str, str]]:
        for rel in sorted(self.files):
            if any(rel.startswith(p) for p in prefixes):
                yield rel, self.files[rel]

    @staticmethod
    def tokens(source: str):
        """NAME/OP tokens of ``source`` (the docstring-safe scan basis)."""
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        return [t for t in toks if t.type in (tokenize.NAME, tokenize.OP)]


def collect_sources(
    root: Optional[str] = None,
    scan_dirs: Sequence[str] = DEFAULT_SCAN_DIRS,
) -> Dict[str, str]:
    """All ``.py`` sources under ``root``'s scan dirs, as repo-relative
    posix paths."""
    root = root or _REPO_ROOT
    files: Dict[str, str] = {}
    for sub in scan_dirs:
        base = os.path.join(root, sub)
        for dirpath, _, names in os.walk(base):
            for fn in names:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    files[rel] = f.read()
    return files


def run_lint(
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    files: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Run ``rules`` (default all) over ``files`` (default: scan the repo)
    and return raw findings — apply an Allowlist afterwards, exactly like
    the jaxpr passes."""
    names = list(rules) if rules is not None else sorted(LINT_RULES)
    unknown = [n for n in names if n not in LINT_RULES]
    if unknown:
        raise KeyError(
            f"unknown lint rule(s) {unknown}; registered: "
            f"{sorted(LINT_RULES)}"
        )
    all_files = files if files is not None else collect_sources(root)
    findings: List[Finding] = []
    for name in names:
        fn, scopes = LINT_RULES[name]
        # the registry's scopes are the single source of truth: each rule
        # sees ONLY its scoped slice of the tree (rules don't re-filter)
        ctx = LintContext({
            rel: src for rel, src in all_files.items()
            if any(rel.startswith(p) for p in scopes)
        })
        findings.extend(fn(ctx))
    return findings


# -- rules -------------------------------------------------------------------


@lint_rule("lint.raw-collective", scopes=("apex_tpu/",))
def raw_collective(ctx: LintContext) -> Iterable[Finding]:
    """``lax.<collective>`` call sites that bypass the xray ledger."""
    for rel, src in sorted(ctx.files.items()):
        toks = ctx.tokens(src)
        for i in range(len(toks) - 2):
            if (
                toks[i].type == tokenize.NAME
                and toks[i].string == "lax"
                and toks[i + 1].string == "."
                and toks[i + 2].string in LEDGERED_OPS
            ):
                yield Finding(
                    rule="lint.raw-collective",
                    message=(
                        f"raw lax.{toks[i + 2].string} bypasses the xray "
                        f"comms ledger — use the "
                        f"apex_tpu.monitor.xray.ledger wrapper (or "
                        f"allowlist with a reason)"
                    ),
                    site=f"{rel}:{toks[i].start[0]}",
                    severity=SEV_ERROR,
                    data={"op": toks[i + 2].string},
                )


@lint_rule("lint.registered-taps", scopes=("apex_tpu/",))
def registered_taps(ctx: LintContext) -> Iterable[Finding]:
    """sow("intermediates", ...) names vs monitor.REGISTERED_TAPS, both
    directions (unregistered tap / stale registry row)."""
    from apex_tpu.monitor import REGISTERED_TAPS

    sown: Dict[str, str] = {}
    for rel, src in sorted(ctx.files.items()):
        for m in SOW_RE.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            sown.setdefault(m.group("name"), f"{rel}:{line}")
    for name in sorted(set(sown) - set(REGISTERED_TAPS)):
        yield Finding(
            rule="lint.registered-taps",
            message=(
                f"sow tap {name!r} is not registered in monitor/taps.py "
                f"REGISTERED_TAPS — a layer refactor could silently drop "
                f"the metric"
            ),
            site=sown[name], severity=SEV_ERROR, data={"tap": name},
        )
    for name in sorted(set(REGISTERED_TAPS) - set(sown)):
        yield Finding(
            rule="lint.registered-taps",
            message=(
                f"REGISTERED_TAPS entry {name!r} has no sow site left in "
                f"apex_tpu/ — remove it or restore the tap"
            ),
            site="apex_tpu/monitor/taps.py:1", severity=SEV_ERROR,
            data={"tap": name, "stale": True},
        )


@lint_rule("lint.jit-donate", scopes=("apex_tpu/", "examples/"))
def jit_donate(ctx: LintContext) -> Iterable[Finding]:
    """Any call passing donate_argnums/donate_argnames outside the audited
    entrypoints (allowlist). AST-based: keyword position is what matters,
    whether spelled ``jax.jit(...)`` or ``functools.partial(jax.jit,
    ...)``."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.jit-donate",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            # only jit-shaped calls: jax.jit(...)/pjit(...) directly or
            # through functools.partial(jax.jit, ...) — plain data calls
            # carrying a donate_argnums field (StepTarget, audit_donation)
            # DECLARE donation for auditing rather than performing it
            jit_call = "jit" in ast.unparse(node.func) or any(
                "jit" in ast.unparse(a) for a in node.args
            )
            if not jit_call:
                continue
            for kw in node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    yield Finding(
                        rule="lint.jit-donate",
                        message=(
                            f"{kw.arg} on a jit outside the audited "
                            f"entrypoints — donation failures are silent "
                            f"(donation.py); add the step to the audited "
                            f"list (and allowlist it here with that "
                            f"reason) or drop the donation"
                        ),
                        site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                        data={"keyword": kw.arg},
                    )


@lint_rule("lint.hlo-text", scopes=("apex_tpu/", "examples/"))
def hlo_text(ctx: LintContext) -> Iterable[Finding]:
    """``.as_text`` attribute access outside the blessed parser.

    Token-based so a docstring MENTIONING ``.as_text()`` (this one, the
    parser's) does not trip it; the rule keys on the NAME token
    preceded by a ``.`` operator."""
    for rel, src in sorted(ctx.files.items()):
        toks = ctx.tokens(src)
        for i in range(1, len(toks)):
            if (
                toks[i].type == tokenize.NAME
                and toks[i].string == "as_text"
                and toks[i - 1].string == "."
            ):
                yield Finding(
                    rule="lint.hlo-text",
                    message=(
                        "ad-hoc .as_text() scraping outside "
                        "apex_tpu/analysis/hlo/parser.py — hand the "
                        "Lowered/Compiled object to the shared parser "
                        "(module_text / parse_hlo_module / "
                        "realized_aliases) so HLO text parsing has one "
                        "nesting-safe home"
                    ),
                    site=f"{rel}:{toks[i].start[0]}",
                    severity=SEV_ERROR,
                )


@lint_rule("lint.memory-api", scopes=("apex_tpu/", "examples/"))
def memory_api(ctx: LintContext) -> Iterable[Finding]:
    """Raw device/compile memory-API access outside the hbm package.

    Token-based (the ``lint.hlo-text`` shape): keys on the NAME tokens
    ``memory_stats`` / ``memory_analysis`` preceded by a ``.`` operator,
    so docstrings MENTIONING the APIs (this one, the hbm package's) do
    not trip it. The rule body spells the names as string literals for
    the same reason."""
    homes = {
        "memory_stats": "apex_tpu/monitor/xray/hbm/live.py",
        "memory_analysis": "apex_tpu/monitor/xray/hbm/report.py",
    }
    for rel, src in sorted(ctx.files.items()):
        toks = ctx.tokens(src)
        for i in range(1, len(toks)):
            if (
                toks[i].type == tokenize.NAME
                and toks[i].string in homes
                and toks[i - 1].string == "."
            ):
                yield Finding(
                    rule="lint.memory-api",
                    message=(
                        f"raw .{toks[i].string}() outside "
                        f"{homes[toks[i].string]} — route through the "
                        "hbm package (device_watermarks / "
                        "report_from_compiled) so the "
                        "None-not-fake-number convention and the "
                        "memory record schema have one home"
                    ),
                    site=f"{rel}:{toks[i].start[0]}",
                    severity=SEV_ERROR,
                )


# string-literal token types: 3.12+ tokenizes f-strings as FSTRING_*
# (the literal text lands in FSTRING_MIDDLE), not STRING — without them
# an f"{host}.trace.json.gz" reader would slip past on newer pythons
_STRING_TOKEN_TYPES = frozenset(
    t for t in (
        tokenize.STRING,
        getattr(tokenize, "FSTRING_START", None),
        getattr(tokenize, "FSTRING_MIDDLE", None),
    ) if t is not None
)


@lint_rule("lint.trace-file", scopes=("apex_tpu/", "examples/"))
def trace_file(ctx: LintContext) -> Iterable[Finding]:
    """``.trace.json`` in any string/docstring outside the blessed
    timeline parser package — the fingerprint of ad-hoc profiler-trace
    reading (see the module docstring)."""
    for rel, src in sorted(ctx.files.items()):
        try:
            toks = tokenize.generate_tokens(io.StringIO(src).readline)
            strings = [t for t in toks if t.type in _STRING_TOKEN_TYPES]
        except (tokenize.TokenError, SyntaxError) as e:
            yield Finding(
                rule="lint.trace-file",
                message=f"untokenizable file: {e}",
                site=f"{rel}:1", severity=SEV_ERROR,
            )
            continue
        for t in strings:
            if ".trace.json" in t.string:
                yield Finding(
                    rule="lint.trace-file",
                    message=(
                        "profiler trace-event reading outside "
                        "apex_tpu/monitor/xray/timeline/ — the timeline "
                        "parser is the one blessed home of the "
                        "*.trace.json[.gz] format (parse_logdir / "
                        "parse_trace_file return structured events); "
                        "ad-hoc readers rot when XProf's exporter "
                        "changes"
                    ),
                    site=f"{rel}:{t.start[0]}", severity=SEV_ERROR,
                )


@lint_rule("lint.signal-handlers", scopes=("apex_tpu/", "examples/"))
def signal_handlers(ctx: LintContext) -> Iterable[Finding]:
    """Raw signal-handler registration outside the blessed homes.

    AST-based: flags ``<mod>.signal(...)`` calls where ``<mod>`` is the
    stdlib module's conventional names (``signal`` or the repo's
    ``import signal as _signal`` alias), and ``from signal import
    signal`` imports (which would hide the call sites from the attribute
    match). ``signal.getsignal`` / ``SIGTERM`` attribute reads are fine
    — only REGISTRATION rewires process-global dispatch."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.signal-handlers",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.ImportFrom) and node.module == "signal"
                    and any(a.name == "signal" for a in node.names)):
                yield Finding(
                    rule="lint.signal-handlers",
                    message=(
                        "'from signal import signal' hides handler "
                        "registration from review — spell it "
                        "signal.signal(...) in one of the blessed homes "
                        "(utils/autoresume.py, monitor/router.py)"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                )
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "signal"
                and isinstance(func.value, ast.Name)
                and func.value.id in ("signal", "_signal")
            ):
                yield Finding(
                    rule="lint.signal-handlers",
                    message=(
                        "raw signal.signal(...) registration outside "
                        "utils/autoresume.py and monitor/router.py — the "
                        "last registration silently wins the whole "
                        "process and breaks the SIG_DFL-precedence "
                        "contract the two blessed homes coordinate on; "
                        "route through AutoResume (preemption) or the "
                        "router teardown (span flush) instead"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                )


#: the threading constructors that create a new concurrency ROOT (locks,
#: events, barriers merely coordinate existing ones and are fine)
_THREAD_CTORS = frozenset({"Thread", "Timer"})


@lint_rule("lint.thread-create", scopes=("apex_tpu/",))
def thread_create(ctx: LintContext) -> Iterable[Finding]:
    """Raw thread construction outside the blessed homes.

    AST-based: flags ``threading.Thread(...)`` / ``threading.Timer(...)``
    calls (including the repo's ``import threading as _threading`` alias
    spelling) and ``from threading import Thread/Timer`` (which would
    hide the construction sites from the attribute match). Every thread
    is a concurrency root the static analyzer inventories; the three
    homes that may mint one — monitor/watchdog.py,
    resilience/health/responder.py, utils/checkpoint.py — carry
    require_hit allowlist entries naming their lifecycle discipline.
    Lock/Event/Condition construction is coordination, not a root, and
    is not flagged."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.thread-create",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.ImportFrom)
                    and node.module == "threading"
                    and any(a.name in _THREAD_CTORS for a in node.names)):
                yield Finding(
                    rule="lint.thread-create",
                    message=(
                        "'from threading import Thread' hides thread "
                        "construction from review — spell it "
                        "threading.Thread(...) in one of the blessed "
                        "homes (monitor/watchdog.py, "
                        "resilience/health/responder.py, "
                        "utils/checkpoint.py)"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                )
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _THREAD_CTORS
                and isinstance(func.value, ast.Name)
                and func.value.id in ("threading", "_threading")
            ):
                yield Finding(
                    rule="lint.thread-create",
                    message=(
                        f"raw threading.{func.attr}(...) outside the "
                        "blessed homes (monitor/watchdog.py, "
                        "resilience/health/responder.py, "
                        "utils/checkpoint.py) — every thread is a "
                        "concurrency root the static analyzer must "
                        "inventory and audit; scattered construction "
                        "adds an unaudited root with no join/daemon "
                        "discipline. Route background work through the "
                        "watchdog monitor loop or the checkpoint "
                        "writer's finalize_async instead"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                )


#: the broad exception names lint.silent-except polices when the handler
#: body is empty (bare ``except:`` is flagged regardless of body)
_BROAD_EXCEPTIONS = frozenset({"Exception", "BaseException"})


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    """True when the except body does nothing: only ``pass``,
    ``continue``, or bare constant expressions (``...``, a string)."""
    return all(
        isinstance(stmt, (ast.Pass, ast.Continue))
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant))
        for stmt in handler.body
    )


@lint_rule("lint.silent-except", scopes=("apex_tpu/",))
def silent_except(ctx: LintContext) -> Iterable[Finding]:
    """Bare ``except:`` / do-nothing broad ``except Exception:`` swallows
    (module docstring). AST-based: the handler TYPE and BODY are what
    matter, not spelling — ``except Exception as e: pass`` and
    ``except BaseException: ...`` both count, a handler that logs or
    returns a fallback does not."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.silent-except",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            if t is None:
                yield Finding(
                    rule="lint.silent-except",
                    message=(
                        "bare 'except:' catches BaseException — "
                        "KeyboardInterrupt and SystemExit included; name "
                        "the exception class (and if the swallow is "
                        "deliberate, allowlist it with the reason)"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"form": "bare"},
                )
                continue
            # tuple handlers count too: `except (Exception,):` is the
            # same swallow wearing parentheses
            exprs = t.elts if isinstance(t, ast.Tuple) else [t]
            names = {
                e.id if isinstance(e, ast.Name)
                else e.attr if isinstance(e, ast.Attribute)
                else None
                for e in exprs
            }
            if (names & _BROAD_EXCEPTIONS) and _handler_is_silent(node):
                name = sorted(names & _BROAD_EXCEPTIONS)[0]
                yield Finding(
                    rule="lint.silent-except",
                    message=(
                        f"'except {name}:' with a do-nothing body "
                        f"silently swallows EVERY failure — log it, "
                        f"narrow the exception, or allowlist the site "
                        f"with the reason the swallow is safe"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"form": "silent"},
                )


def _main_gate_spans(tree: ast.Module) -> List[Tuple[int, int]]:
    """(start, end) line spans of top-level ``if __name__ == "__main__":``
    blocks — the one structural exemption lint.process-exit grants."""
    spans: List[Tuple[int, int]] = []
    for node in tree.body:
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "__name__"
                and any(isinstance(c, ast.Constant)
                        and c.value == "__main__"
                        for c in test.comparators)):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return spans


@lint_rule("lint.process-exit", scopes=("apex_tpu/",))
def process_exit(ctx: LintContext) -> Iterable[Finding]:
    """Raw ``os._exit``/``sys.exit`` usage outside the blessed homes
    (module docstring). AST-based: flags the ATTRIBUTE usage, not just
    calls — ``exit_fn = os._exit`` rewires the same authority — plus
    the ``from os import _exit`` / ``from sys import exit`` imports
    that would hide the attribute from review."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.process-exit",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        gates = _main_gate_spans(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module in (
                    "os", "sys"):
                for a in node.names:
                    if (node.module, a.name) in (("os", "_exit"),
                                                 ("sys", "exit")):
                        yield Finding(
                            rule="lint.process-exit",
                            message=(
                                f"'from {node.module} import {a.name}' "
                                f"hides a process-exit call site from "
                                f"review — spell it "
                                f"{node.module}.{a.name}(...) in a "
                                f"blessed home"
                            ),
                            site=f"{rel}:{node.lineno}",
                            severity=SEV_ERROR,
                        )
                continue
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)):
                continue
            pair = (node.value.id, node.attr)
            if pair not in (("os", "_exit"), ("sys", "exit")):
                continue
            if pair == ("sys", "exit") and any(
                    lo <= node.lineno <= hi for lo, hi in gates):
                continue  # the __main__-gate CLI convention
            yield Finding(
                rule="lint.process-exit",
                message=(
                    f"raw {node.value.id}.{node.attr} outside the "
                    f"blessed homes — exit codes are a CLOSED taxonomy "
                    f"(resilience/exit_codes.py) that supervisors branch "
                    f"on, and the blessed paths (the __main__ gates, the "
                    f"incident responder's coordinated self-termination) "
                    f"own the teardown discipline; return an ExitCode "
                    f"from main() or route through the responder"
                ),
                site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                data={"call": f"{node.value.id}.{node.attr}"},
            )


#: goodput span constructors -> position of their ``phase`` argument
#: (emit_span takes the router first). Aliased imports are caught by the
#: ``*_span`` suffix match in :func:`span_phases`.
_SPAN_CALLEES = {"span": 0, "begin_span": 0, "Span": 0, "emit_span": 1}


@lint_rule("lint.span-phases", scopes=("apex_tpu/", "examples/"))
def span_phases(ctx: LintContext) -> Iterable[Finding]:
    """Goodput span call sites whose phase is not a registry literal.

    AST-based: matches calls whose terminal name is a span constructor
    (``goodput.span(...)``, ``begin_span(...)``, ``Span(...)``,
    ``emit_span(...)``) or an import alias ending in ``_span``; the
    phase argument's string constants must ALL be in
    ``monitor.goodput.spans.PHASES`` (a conditional of two literals is
    fine), and a phase expression with no string constant at all is a
    variable phase — unverifiable, flagged. Calls with no phase argument
    or a non-string constant one (``m.span(1)`` on a regex match) are
    not span-ledger calls and are skipped."""
    from apex_tpu.monitor.goodput.spans import PHASES

    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.span-phases",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None
            )
            if name in _SPAN_CALLEES:
                pos = _SPAN_CALLEES[name]
            elif name is not None and name.endswith("_span"):
                pos = 0  # import alias: `from ... import span as _x_span`
            else:
                continue
            phase_expr = None
            for kw in node.keywords:
                if kw.arg == "phase":
                    phase_expr = kw.value
            if phase_expr is None and len(node.args) > pos:
                arg = node.args[pos]
                if not isinstance(arg, ast.Starred):
                    phase_expr = arg
            if phase_expr is None:
                continue  # no phase argument: not a span-ledger call
            if (isinstance(phase_expr, ast.Constant)
                    and not isinstance(phase_expr.value, str)):
                continue  # m.span(1): a regex match-group, not a phase
            strings = [
                n.value for n in ast.walk(phase_expr)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            ]
            if not strings:
                yield Finding(
                    rule="lint.span-phases",
                    message=(
                        f"span call {name!r} passes a non-literal phase — "
                        f"the closed taxonomy (goodput.spans.PHASES) is "
                        f"only enforceable on literals; name the phase "
                        f"inline (or allowlist the forwarding helper "
                        f"with its reason)"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"callee": name},
                )
                continue
            for s in strings:
                if s not in PHASES:
                    yield Finding(
                        rule="lint.span-phases",
                        message=(
                            f"unknown span phase {s!r} — the taxonomy is "
                            f"closed (goodput.spans.PHASES: "
                            f"{', '.join(PHASES)}); an ad-hoc phase "
                            f"fragments the goodput partition across runs"
                        ),
                        site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                        data={"callee": name, "phase": s},
                    )


#: quantize/dequantize primitive call names the compressed-collective
#: rule keys on: the compress module's own primitives plus any same-
#: prefixed ad-hoc reimplementation. The PUBLIC wrappers
#: (quantized_psum / quantized_psum_scatter / quantized_all_gather) are
#: deliberately NOT in this set — call sites composing with them are the
#: intended use, not a new compression home.
_QUANT_PRIMITIVE_PREFIXES = ("quantize_", "dequantize_")


@lint_rule("lint.compressed-collective", scopes=("apex_tpu/",))
def compressed_collective(ctx: LintContext) -> Iterable[Finding]:
    """Functions composing quantize/dequant primitives with ledgered
    collectives outside parallel/compress.py (module docstring).

    AST-based, function granularity: for every FunctionDef, collect the
    terminal names of all calls; a function calling BOTH a
    ``quantize_*``/``dequantize_*`` primitive and a collective from
    ``LEDGERED_OPS`` is a compressed-collective composition and belongs
    in the audited home (compress.py carries the require_hit allowlist
    entry)."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.compressed-collective",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            quant = None
            coll = None
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                func = call.func
                name = (
                    func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None
                )
                if name is None:
                    continue
                if name.startswith(_QUANT_PRIMITIVE_PREFIXES):
                    quant = quant or name
                elif name in LEDGERED_OPS:
                    coll = coll or name
            if quant and coll:
                yield Finding(
                    rule="lint.compressed-collective",
                    message=(
                        f"{quant} composed with {coll} outside "
                        f"parallel/compress.py — quantized collectives "
                        f"have ONE audited home (wire-byte accounting, "
                        f"error feedback, found_inf poison semantics); "
                        f"use compress.quantized_psum/"
                        f"quantized_psum_scatter/quantized_all_gather "
                        f"or move the composition there"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"quant": quant, "collective": coll,
                          "function": node.name},
                )


#: the gather collectives lint.prefetch-gather polices inside for-loops
#: (psum/ppermute in a loop are schedule edges, not bucket pipelines)
_PREFETCH_GATHER_OPS = frozenset({"all_gather", "psum_scatter"})


@lint_rule("lint.prefetch-gather", scopes=("apex_tpu/", "examples/"))
def prefetch_gather(ctx: LintContext) -> Iterable[Finding]:
    """Python-for-loop gather pipelines outside the blessed prefetch
    home (module docstring). AST-based, function granularity: a
    ``for``/``async for`` whose body (not a nested function's) calls a
    terminal ``all_gather``/``psum_scatter`` is the bucketed-prefetch
    fingerprint — the loop traces one collective per iteration, i.e. a
    hand-rolled overlap pipeline whose depth/reconstruction/ledger
    invariants belong in ``zero_prefetch_gather``."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.prefetch-gather",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            hit = None
            # manual walk that PRUNES nested function defs: a call
            # inside a closure defined in the loop traces when the
            # closure runs, not per loop iteration
            stack = list(ast.iter_child_nodes(node))
            while stack and hit is None:
                sub = stack.pop()
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(sub))
                if not isinstance(sub, ast.Call):
                    continue
                func = sub.func
                name = (
                    func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None
                )
                if name in _PREFETCH_GATHER_OPS:
                    hit = name
            if hit:
                yield Finding(
                    rule="lint.prefetch-gather",
                    message=(
                        f"{hit} issued inside a Python for-loop — a "
                        f"hand-rolled bucketed gather pipeline; route "
                        f"through optimizers.zero_prefetch_gather (the "
                        f"one home where overlap depth is roofline-"
                        f"derived and the bucket reconstruction is "
                        f"exact), or allowlist the site with the reason "
                        f"it is not a prefetch pipeline"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"op": hit},
                )


#: stdlib ``random`` draw functions the nondeterminism rule polices when
#: called through the module singleton (seeding and seeded-instance
#: construction are exempt — they establish determinism, not break it)
_STDLIB_RANDOM_DRAWS = frozenset({
    "random", "uniform", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "vonmisesvariate", "paretovariate",
    "weibullvariate", "lognormvariate", "getrandbits", "randbytes",
})

#: ``np.random`` attributes that are NOT singleton draws: seeded
#: constructors/classes and state plumbing
_NP_RANDOM_SEEDED = frozenset({
    "RandomState", "default_rng", "Generator", "SeedSequence", "PCG64",
    "Philox", "MT19937", "SFC64", "BitGenerator", "get_state",
    "set_state", "seed",
})


@lint_rule("lint.nondeterminism", scopes=("apex_tpu/",))
def nondeterminism(ctx: LintContext) -> Iterable[Finding]:
    """Unseeded singleton RNG draws and wall-clock reads in library code
    (module docstring). AST-based:

    - a call whose attribute is a stdlib draw name and whose base
      expression mentions the bare name ``random`` (so
      ``random.uniform(...)`` AND ``(rng or random).random(...)`` are
      caught, while ``jax.random.uniform`` — whose base is the
      attribute ``jax.random``, not the name — is not);
    - a call on ``np.random``/``numpy.random`` whose attribute is not a
      seeded constructor;
    - ``time.time(...)`` / ``time.time_ns(...)`` calls.
    """
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.nondeterminism",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            attr = func.attr
            base = func.value
            # time.time / time.time_ns on the stdlib module name
            if (attr in ("time", "time_ns")
                    and isinstance(base, ast.Name)
                    and base.id == "time"):
                yield Finding(
                    rule="lint.nondeterminism",
                    message=(
                        f"wall-clock read time.{attr}() in library code "
                        f"— unreproducible input the replay journal "
                        f"cannot capture; use time.monotonic/"
                        f"perf_counter for durations, or allowlist the "
                        f"site with the reason the value never feeds "
                        f"step math"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"call": f"time.{attr}"},
                )
                continue
            # np.random.<draw> / numpy.random.<draw> on the singleton
            if (isinstance(base, ast.Attribute) and base.attr == "random"
                    and isinstance(base.value, ast.Name)
                    and base.value.id in ("np", "numpy")
                    and attr not in _NP_RANDOM_SEEDED):
                yield Finding(
                    rule="lint.nondeterminism",
                    message=(
                        f"np.random.{attr}() draws from numpy's GLOBAL "
                        f"generator — unseeded, process-shared, invisible "
                        f"to the replay journal; construct a seeded "
                        f"np.random.RandomState/default_rng instead"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"call": f"np.random.{attr}"},
                )
                continue
            # stdlib singleton draws: a bare name `random` in the base
            # expression (catches `(rng or random).random()`) — but NOT
            # one inside a nested Call, which is a seeded-instance
            # construction (`random.Random(3).random()` is exactly what
            # this rule's message recommends, not a violation)
            in_call = set()
            for sub in ast.walk(base):
                if isinstance(sub, ast.Call):
                    for n2 in ast.walk(sub):
                        if isinstance(n2, ast.Name):
                            in_call.add(id(n2))
            if attr in _STDLIB_RANDOM_DRAWS and any(
                isinstance(n, ast.Name) and n.id == "random"
                and id(n) not in in_call
                for n in ast.walk(base)
            ):
                yield Finding(
                    rule="lint.nondeterminism",
                    message=(
                        f"random.{attr}() draws from the stdlib module "
                        f"singleton — unseeded and process-shared; use a "
                        f"seeded random.Random(seed) instance (or "
                        f"allowlist the host-side site with its reason)"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"call": f"random.{attr}"},
                )


#: the clock reads lint.serving-clock polices in serving/ (perf_counter
#: is a duration probe, sleep is not a read — neither feeds deadline
#: math, so neither is in this set)
_SERVING_CLOCK_READS = frozenset({"monotonic", "time", "time_ns",
                                  "monotonic_ns"})


@lint_rule("lint.serving-clock", scopes=("apex_tpu/serving/",))
def serving_clock(ctx: LintContext) -> Iterable[Finding]:
    """Bare clock CALLS in serving scheduling paths (module docstring).

    AST-based: flags ``time.monotonic()``/``time.time()`` (and the
    ``_ns`` variants) called through the stdlib module name or its
    conventional ``_time`` alias, plus ``from time import monotonic/
    time`` imports that would hide those call sites behind bare names.
    A bare ATTRIBUTE reference (``time_fn=time.monotonic`` — the
    injection default idiom) is not a call and not flagged."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.serving-clock",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.ImportFrom) and node.module == "time"):
                for a in node.names:
                    if a.name in _SERVING_CLOCK_READS:
                        yield Finding(
                            rule="lint.serving-clock",
                            message=(
                                f"'from time import {a.name}' hides bare "
                                f"clock reads from review in serving code "
                                f"— take the clock from the injected "
                                f"``time_fn`` instead"
                            ),
                            site=f"{rel}:{node.lineno}",
                            severity=SEV_ERROR,
                            data={"import": a.name},
                        )
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _SERVING_CLOCK_READS
                and isinstance(func.value, ast.Name)
                and func.value.id in ("time", "_time")
            ):
                yield Finding(
                    rule="lint.serving-clock",
                    message=(
                        f"bare time.{func.attr}() in serving code — the "
                        f"serving clock is INJECTED (time_fn= on "
                        f"ServingEngine/FleetRouter) so deadlines, drain "
                        f"budgets and failover detection are drivable by "
                        f"a fake clock; read ``self.time_fn()`` (or "
                        f"thread a ``now`` parameter) instead"
                    ),
                    site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                    data={"call": f"time.{func.attr}"},
                )


#: the record kinds whose CONSTRUCTION is fenced to the trace package
#: (emit.py builds "trace", slo.py builds "slo"); the analyzer's derived
#: offline kind "trace_decomp" is not a span and deliberately not fenced
_FENCED_TRACE_KINDS = frozenset({"trace", "slo"})

#: record-constructor callee names lint.trace-emit inspects (the shared
#: schema's two mouths: MetricRouter.event and make_record)
_RECORD_CONSTRUCTORS = frozenset({"event", "make_record"})


@lint_rule("lint.trace-emit", scopes=("apex_tpu/", "examples/"))
def trace_emit(ctx: LintContext) -> Iterable[Finding]:
    """Ad-hoc ``kind="trace"``/``"slo"`` record construction outside the
    blessed trace-package homes (module docstring). AST-based: flags
    ``event``/``make_record`` calls whose kind argument (first
    positional, or ``kind=``) is one of the fenced literals, and dict
    literals whose ``"kind"`` key maps to one — both are records
    entering the stream; comparisons and sink filters merely read."""
    for rel, src in sorted(ctx.files.items()):
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            yield Finding(
                rule="lint.trace-emit",
                message=f"unparseable file: {e}",
                site=f"{rel}:{e.lineno or 1}", severity=SEV_ERROR,
            )
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                func = node.func
                name = (
                    func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None
                )
                if name not in _RECORD_CONSTRUCTORS:
                    continue
                kind = None
                if node.args:
                    a0 = node.args[0]
                    if (isinstance(a0, ast.Constant)
                            and isinstance(a0.value, str)):
                        kind = a0.value
                for kw in node.keywords:
                    if (kw.arg == "kind"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        kind = kw.value.value
                if kind in _FENCED_TRACE_KINDS:
                    yield Finding(
                        rule="lint.trace-emit",
                        message=(
                            f'{name}(kind="{kind}") outside the blessed '
                            f"home — {kind!r} records have ONE "
                            f"construction site (serving/trace/"
                            f"{'emit' if kind == 'trace' else 'slo'}.py) "
                            f"so the span schema the critical-path "
                            f"analyzer re-adds its identity from cannot "
                            f"fork; route through TraceEmitter/SLOMonitor"
                        ),
                        site=f"{rel}:{node.lineno}", severity=SEV_ERROR,
                        data={"kind": kind, "callee": name},
                    )
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant) and k.value == "kind"
                            and isinstance(v, ast.Constant)
                            and v.value in _FENCED_TRACE_KINDS):
                        yield Finding(
                            rule="lint.trace-emit",
                            message=(
                                f'hand-built record dict with "kind": '
                                f'"{v.value}" — trace/slo records have '
                                f"ONE construction site (serving/trace/) "
                                f"so their schema cannot fork; route "
                                f"through TraceEmitter/SLOMonitor"
                            ),
                            site=f"{rel}:{node.lineno}",
                            severity=SEV_ERROR,
                            data={"kind": v.value, "form": "dict"},
                        )


@lint_rule("lint.float64", scopes=("apex_tpu/",))
def float64_literals(ctx: LintContext) -> Iterable[Finding]:
    """``jnp.float64`` (and ``jax.numpy.float64``) in library code.

    Only the jax spellings: a bare ``numpy.float64`` is host-side index
    math and exempt, exactly as the module docstring promises — so
    ``numpy`` only matches when preceded by ``jax.``."""
    for rel, src in sorted(ctx.files.items()):
        toks = ctx.tokens(src)
        for i in range(len(toks) - 2):
            if (
                toks[i].type == tokenize.NAME
                and toks[i + 1].string == "."
                and toks[i + 2].string == "float64"
                and (
                    toks[i].string == "jnp"
                    or (
                        toks[i].string == "numpy"
                        and i >= 2
                        and toks[i - 2].string == "jax"
                        and toks[i - 1].string == "."
                    )
                )
            ):
                yield Finding(
                    rule="lint.float64",
                    message=(
                        "jnp.float64 in library code: TPUs emulate f64 at "
                        "a fraction of native rate and one f64 value "
                        "poisons every dtype downstream"
                    ),
                    site=f"{rel}:{toks[i].start[0]}", severity=SEV_ERROR,
                )
