"""In-repo step builders the CLI audits: tiny GPT and BERT train steps.

These are the library's own flagship step shapes (the pretrain_gpt /
standalone_bert composition) shrunk to trace-and-compile in seconds on
the CPU test mesh: bf16 compute, tensor parallelism (+ sequence
parallelism for GPT) over a dp2 x tp2 mesh, dynamic loss scaling, fused
Adam, dp gradient allreduce, and donated params/opt/scaler state. Every
auditor has something real to chew on: low-precision regions for the
precision pass, tp/dp collectives for the collective validator, donation
intent for the donation auditor, and (deliberately) nothing for the
host-sync detector to find.

``python -m apex_tpu.analysis`` runs all registered passes over both
targets and must exit clean — the tier-1 self-check pins that, so a PR
that introduces a silent promotion, breaks a donation, or leaves a
``debug.print`` in the step path fails fast.
"""

import functools
from typing import List

import jax
import jax.numpy as jnp

from apex_tpu.analysis.passes import StepTarget

__all__ = [
    "dp2tp2_mesh",
    "dp2pp2_mesh",
    "gpt_step_target",
    "gpt_compressed_step_target",
    "gpt_pp_step_target",
    "gpt_zero_naive_step_target",
    "bert_step_target",
    "all_targets",
    "FIXABLE_TARGETS",
]


def dp2tp2_mesh():
    """The acceptance mesh: dp=2 x tp=2 over the first four devices (the
    CPU test topology provides 8 via xla_force_host_platform_device_count;
    the CLI sets that up before jax initializes)."""
    from apex_tpu.parallel import parallel_state

    devices = jax.devices()
    if len(devices) < 4:
        raise RuntimeError(
            f"the dp2xtp2 audit mesh needs >= 4 devices, found "
            f"{len(devices)} — run via `python -m apex_tpu.analysis` (which "
            f"forces the 8-device CPU topology) or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, devices=devices[:4]
    )


def dp2pp2_mesh():
    """The pipeline audit mesh: dp=2 x pp=2 over the first four devices.
    NOTE: re-initializes the global parallel_state — build (and audit)
    the dp2xtp2 targets first; the CLI's builder order does."""
    from apex_tpu.parallel import parallel_state

    devices = jax.devices()
    if len(devices) < 4:
        raise RuntimeError(
            f"the dp2xpp2 audit mesh needs >= 4 devices, found "
            f"{len(devices)} — run via `python -m apex_tpu.analysis` (which "
            f"forces the 8-device CPU topology) or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    return parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size=2, devices=devices[:4]
    )


def _tiny_cfg(**overrides):
    from apex_tpu.transformer import TransformerConfig

    base = dict(
        num_layers=2, hidden_size=16, num_attention_heads=2, vocab_size=32,
        max_position_embeddings=8, hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16,
    )
    base.update(overrides)
    return TransformerConfig(**base)


def gpt_step_target(mesh=None, compression=None, *, in_specs=None,
                    out_specs=None, donate_argnums=(0, 1, 2)) -> StepTarget:
    """The GPT dp2xtp2 train step: bf16 + SP over tp, GradScaler, fused
    Adam, dp grad allreduce, donated (params, opt_state, scaler_state).

    ``compression`` (a ``parallel.compress.CompressionConfig``) swaps the
    dp grad allreduce for the quantized decomposition — the acceptance
    target of the compressed-collective work: the ledger predicts the
    int8 wire bytes and the hlo-comms differ must confirm the emitted
    pattern (``gpt_compressed_step_target`` registers it with the CLI
    gate). Stateless here (no error-feedback residual): the auditors
    trace one step; EF only matters across steps.

    Specs are data (the autofix contract): ``in_specs``/``out_specs``
    override the boundary PartitionSpecs and ``donate_argnums`` the
    donation intent — None keeps the flagship layout below."""
    import optax

    from apex_tpu.amp import GradScaler
    from apex_tpu.compat import shard_map
    from apex_tpu.models import GPTModel, gpt_loss_fn
    from apex_tpu.monitor.xray import ledger as xlax
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.parallel.ddp import all_reduce_gradients
    from jax.sharding import PartitionSpec as P

    mesh = mesh or dp2tp2_mesh()
    cfg = _tiny_cfg(sequence_parallel=True)
    model = GPTModel(config=cfg)
    opt = fused_adam(lr=1e-3, weight_decay=0.01)
    scaler = GradScaler(loss_scale="dynamic")
    b, s = 2, cfg.max_position_embeddings
    tokens = jnp.zeros((b, s), jnp.int32)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    )
    def init(tokens):
        return model.init(jax.random.PRNGKey(0), tokens)

    # abstract state: the auditors only need avals (make_jaxpr and
    # .lower() both take ShapeDtypeStructs), so nothing here executes —
    # keeps the CLI/self-check seconds instead of paying real init
    # compiles on the CPU mesh
    params = jax.eval_shape(init, tokens)
    opt_state = jax.eval_shape(opt.init, params)
    scaler_state = jax.eval_shape(scaler.init)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=in_specs or (P(), P(), P(), P("dp"), P("dp")),
        out_specs=out_specs or (P(), P(), P(), P()),
        check_vma=False,
    )
    def gpt_train_step(params, opt_state, scaler_state, tokens, labels):
        def scaled_loss(p):
            return scaler.scale(
                scaler_state, gpt_loss_fn(model.apply(p, tokens, labels=labels))
            )

        loss, grads = jax.value_and_grad(scaled_loss)(params)
        grads = all_reduce_gradients(
            grads, axis_name="dp", compression=compression
        )
        grads, found_inf = scaler.unscale(scaler_state, grads)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        unscaled = xlax.pmean(loss / scaler_state.scale, "dp")
        return new_params, new_opt_state, new_scaler_state, unscaled

    return StepTarget(
        name="gpt-dp2tp2" if compression is None else "gpt-dp2tp2-int8",
        fn=gpt_train_step,
        args=(params, opt_state, scaler_state, tokens, tokens),
        mesh=mesh,
        donate_argnums=tuple(donate_argnums) if donate_argnums else None,
        hbm=_gpt_hbm_prediction(cfg, b=b, s=s, tp=2, dp=2),
    )


def _gpt_hbm_prediction(cfg, *, b, s, tp, dp):
    """The analytic HBM ledger for the dp2xtp2 GPT step — built from
    the SAME config numbers the step builder uses, so the ``hlo-memory``
    differ reconciles a genuine closed-form prediction (params and
    fused-Adam state digit-for-digit) against ``memory_analysis()``."""
    from apex_tpu.monitor.xray.hbm import model as hbm_model

    return hbm_model.predict_train_memory(
        hbm_model.TransformerDims.from_config(cfg),
        tp=tp,
        params_dtype="float32",
        compute_dtype="bfloat16",
        microbatch_size=b // dp,
        seq_len=s,
        optimizer="fused_adam",
        grad_scaler=True,
        remat="none",
        label="gpt-dp2tp2",
    )


def gpt_compressed_step_target(mesh=None) -> StepTarget:
    """The GPT step with the int8 quantized dp gradient allreduce
    (parallel/compress.py) — the third CLI-gate target, so every pass
    (precision, donation, collective safety, host-sync, hlo-comms,
    hlo-sharding) audits the compressed wire pattern on every run."""
    from apex_tpu.parallel.compress import CompressionConfig

    return gpt_step_target(mesh, compression=CompressionConfig())


def gpt_pp_step_target(mesh=None) -> StepTarget:
    """The pp-enabled GPT CLI-gate target (dp2 x pp2): the ZERO-BUBBLE
    pipeline schedule + the prefetched ZeRO optimizer, so the comms
    differ, donation, and sharding passes audit pipeline p2p traffic on
    every run.

    Deliberately the fully-ledger-visible composition: the zero-bubble
    schedule hand-writes its backward edges through the p2p wrappers
    (no transpose-synthesized permutes for the differ to flag), and
    ``distributed_fused_adam(param_gather_buckets=2)`` routes the
    bucketed prefetch gathers through the ledger — this target must
    audit clean with ZERO comms-allowlist suppressions beyond the
    positive-confirmation rules (pinned by tests/test_analysis.py)."""
    import optax

    from apex_tpu.compat import shard_map
    from apex_tpu.models.gpt_pipeline import build_gpt_pipeline
    from apex_tpu.monitor.xray import ledger as xlax
    from apex_tpu.optimizers import distributed_fused_adam, zero_state_specs
    from apex_tpu.parallel.pipeline import (
        forward_backward_zero_bubble_with_pre_post,
    )
    from jax.sharding import PartitionSpec as P

    mesh = mesh or dp2pp2_mesh()
    pp, dp = 2, 2
    cfg = _tiny_cfg()
    parts = build_gpt_pipeline(cfg, pp)
    opt = distributed_fused_adam(
        lr=1e-3, axis_name="dp", axis_size=dp, average_grads=True,
        param_gather_buckets=2,
    )
    num_micro, mb, seq = 2, 2, cfg.max_position_embeddings
    tokens = jnp.zeros((num_micro, mb * dp, seq), jnp.int32)
    sspec = zero_state_specs("dp")

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    )
    def init(tokens):
        key = jax.random.PRNGKey(0)
        pre = parts.embed.init(key, tokens[0])["params"]
        h = parts.pre_fn(pre, tokens[0])
        stage = parts.chunk.init(jax.random.fold_in(key, 7), h)["params"]
        return {
            "pre": pre,
            # leading pp dim: the boundary layout of per-stage params
            "stages": jax.tree_util.tree_map(
                lambda a: jnp.stack([a] * pp), stage
            ),
            "post": parts.init_post(jax.random.fold_in(key, 9)),
        }

    # abstract state, as in gpt_step_target: avals only, no execution
    params = jax.eval_shape(init, tokens)
    pspec = jax.tree_util.tree_map(lambda _: P("pp"), params["stages"])
    io_spec = {"pre": P(), "stages": pspec, "post": P()}

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=sspec,
        check_vma=False,
    )
    def init_opt(local_params):
        return opt.init(local_params)

    local_shape = dict(params)
    local_shape["stages"] = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype),
        params["stages"],
    )
    opt_state = jax.eval_shape(init_opt, local_shape)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(io_spec, sspec, P(None, "dp"), P(None, "dp")),
        out_specs=(io_spec, sspec, P(), P()),
        check_vma=False,
    )
    def gpt_pp_train_step(params, opt_state, tokens, labels):
        local = dict(params)
        local["stages"] = jax.tree_util.tree_map(
            lambda a: a[0], params["stages"]
        )
        # per-microbatch losses are a REAL output (training loops log
        # them) — returning them keeps their pp publication psum live,
        # so the differ sees no vanished traffic on this target
        loss, losses, grads = forward_backward_zero_bubble_with_pre_post(
            parts.pre_fn, parts.stage_fn, parts.post_loss_fn, local,
            tokens, labels, axis_name="pp",
        )
        # the ZeRO reduce-scatter over dp IS the gradient sync; the
        # bucketed param all-gather prefetch rides the same update
        updates, new_opt_state = opt.update(grads, opt_state, local)
        new_local = optax.apply_updates(local, updates)
        new_params = dict(new_local)
        new_params["stages"] = jax.tree_util.tree_map(
            lambda a: a[None], new_local["stages"]
        )
        return (new_params, new_opt_state, xlax.pmean(loss, "dp"),
                xlax.pmean(losses, "dp"))

    return StepTarget(
        name="gpt-dp2pp2",
        fn=gpt_pp_train_step,
        args=(params, opt_state, tokens, tokens),
        mesh=mesh,
        donate_argnums=(0, 1),
    )


def bert_step_target(mesh=None, *, in_specs=None, out_specs=None,
                     donate_argnums=(0, 1)) -> StepTarget:
    """The BERT masked-LM step on the same mesh: bf16, tp2 vocab/tensor
    parallel heads, fused Adam, donated (params, opt_state). Specs are
    data, as in :func:`gpt_step_target`: ``in_specs``/``out_specs``/
    ``donate_argnums`` inject boundary layouts (None = defaults)."""
    import optax

    from apex_tpu.compat import shard_map
    from apex_tpu.models import BertModel
    from apex_tpu.monitor.xray import ledger as xlax
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.parallel.ddp import all_reduce_gradients
    from jax.sharding import PartitionSpec as P

    mesh = mesh or dp2tp2_mesh()
    cfg = _tiny_cfg()
    model = BertModel(config=cfg, add_binary_head=False)
    opt = fused_adam(lr=1e-3)
    b, s = 2, cfg.max_position_embeddings
    tokens = jnp.zeros((b, s), jnp.int32)
    mask = jnp.ones((b, s), jnp.int32)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    )
    def init(tokens, mask):
        return model.init(jax.random.PRNGKey(0), tokens, mask)

    # abstract state, as in gpt_step_target: avals only, no execution
    params = jax.eval_shape(init, tokens, mask)
    opt_state = jax.eval_shape(opt.init, params)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=in_specs or (P(), P(), P("dp"), P("dp")),
        out_specs=out_specs or (P(), P(), P()),
        check_vma=False,
    )
    def bert_train_step(params, opt_state, tokens, labels):
        def loss_fn(p):
            losses, _ = model.apply(
                p, tokens, jnp.ones_like(tokens), lm_labels=labels
            )
            return jnp.mean(losses)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = all_reduce_gradients(grads, axis_name="dp")
        updates, new_opt_state = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt_state, xlax.pmean(loss, "dp")

    return StepTarget(
        name="bert-dp2tp2",
        fn=bert_train_step,
        args=(params, opt_state, tokens, tokens),
        mesh=mesh,
        donate_argnums=tuple(donate_argnums) if donate_argnums else None,
    )


def _flat_adam(p, m, v, g, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
    """One Adam step over a flat fp32 buffer (no bias correction: the
    auditors trace a single step, there is no step counter to carry)."""
    import jax.numpy as jnp

    new_m = b1 * m + (1.0 - b1) * g
    new_v = b2 * v + (1.0 - b2) * (g * g)
    return p - lr * new_m / (jnp.sqrt(new_v) + eps), new_m, new_v


def gpt_zero_naive_step_target(mesh=None, *, state_spec=None,
                               donate_argnums=()) -> StepTarget:
    """The DELIBERATELY naively-sharded GPT step — the autofix proof
    target (ROADMAP item 2a, arXiv:2004.13336's baseline anti-pattern).

    The optimizer state is the ZeRO flat-buffer convention (one padded
    fp32 buffer each for Adam's m and v, laid out by ``flatten_pytree``),
    but in the seeded configuration (``state_spec=None`` -> ``P()``)
    that state crosses the step boundary FULLY REPLICATED and the weight
    update runs replicated on every dp rank: a full-payload grad
    allreduce, a full-buffer Adam on all ranks, and the defensive param
    resync allreduce replicated updates drag along (replicas drift under
    nondeterministic reduction order, so naive codebases re-broadcast).
    Nothing is donated either. The auditors flag all of it:
    ``sharding.replicated-param`` on m/v, ``donation.missed`` on m/v.

    With ``state_spec=P("dp")`` — exactly what the autofix derivation
    prescribes — the SAME builder composes the proper ZeRO-2 update
    (the ``distributed_fused_adam`` shape): reduce-scatter the flat
    grads, Adam on this rank's param shard against the LOCAL m/v shards,
    all-gather the updated params. The gather is the sync, so the
    resync allreduce disappears structurally and the predicted dp-axis
    weight-update wire bytes drop by exactly the dp factor
    (tests/test_autofix.py pins the ledger totals digit-for-digit).

    Specs are data: the step body branches on whether the injected spec
    shards the state, so a ``Patch`` is literally a PartitionSpec (and
    donate-tuple) change — same args, same global shapes, same name.
    """
    from apex_tpu.compat import shard_map
    from apex_tpu.models import GPTModel, gpt_loss_fn
    from apex_tpu.monitor.xray import ledger as xlax
    from apex_tpu.ops import flatten_pytree, unflatten_pytree
    from jax.sharding import PartitionSpec as P

    mesh = mesh or dp2tp2_mesh()
    spec = state_spec if state_spec is not None else P()
    sharded = bool(tuple(spec))
    dp = int(dict(mesh.shape)["dp"])
    donate = tuple(donate_argnums or ())
    cfg = _tiny_cfg()
    model = GPTModel(config=cfg)
    b, s = 2, cfg.max_position_embeddings
    tokens = jnp.zeros((b, s), jnp.int32)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    )
    def init(tokens):
        return model.init(jax.random.PRNGKey(0), tokens)

    # abstract state, as in gpt_step_target: avals only, no execution
    params = jax.eval_shape(init, tokens)
    flat = jax.eval_shape(
        lambda p: flatten_pytree(p, dtype=jnp.float32)[0], params
    )
    if flat.shape[0] % dp:
        raise ValueError(
            f"flat buffer length {flat.shape[0]} not divisible by dp={dp} "
            f"— the ZeRO flat-buffer convention pads to a chunk multiple, "
            f"keep dp a divisor of the chunk size"
        )
    m = jax.ShapeDtypeStruct(flat.shape, jnp.float32)
    v = jax.ShapeDtypeStruct(flat.shape, jnp.float32)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), spec, spec, P("dp"), P("dp")),
        out_specs=(P(), spec, spec, P()),
        check_vma=False,
    )
    def gpt_zero_naive_train_step(params, m, v, tokens, labels):
        def loss_fn(p):
            return gpt_loss_fn(model.apply(p, tokens, labels=labels))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        gflat, _ = flatten_pytree(grads, dtype=jnp.float32)
        pflat, pspec = flatten_pytree(params, dtype=jnp.float32)
        if sharded:
            # ZeRO-2: the reduce-scatter IS the grad sync, the update
            # touches 1/dp of the state, the all-gather IS the resync
            gshard = xlax.psum_scatter(
                gflat, "dp", scatter_dimension=0, tiled=True
            ) / dp
            shard_len = pflat.shape[0] // dp
            idx = jax.lax.axis_index("dp")
            pshard = jax.lax.dynamic_slice(
                pflat, (idx * shard_len,), (shard_len,)
            )
            new_pshard, new_m, new_v = _flat_adam(pshard, m, v, gshard)
            new_pflat = xlax.all_gather(new_pshard, "dp", tiled=True)
        else:
            # seeded anti-pattern: full-payload allreduce, replicated
            # full-buffer update, defensive full-payload param resync
            gmean = xlax.pmean(gflat, "dp")
            new_pflat, new_m, new_v = _flat_adam(pflat, m, v, gmean)
            new_pflat = xlax.pmean(new_pflat, "dp")
        new_params = unflatten_pytree(new_pflat, pspec)
        return new_params, new_m, new_v, xlax.pmean(loss, "dp")

    return StepTarget(
        name="gpt-zero-naive",
        fn=gpt_zero_naive_train_step,
        args=(params, m, v, tokens, tokens),
        mesh=mesh,
        donate_argnums=donate,
        # the tiny config's flat buffers are 256 KiB — far under the
        # auditors' 1 MiB production floors; the target-level floors
        # keep the seeded defects visible without a slow big model
        sharding_min_bytes=1 << 16,
        donation_min_bytes=1 << 16,
        builder=gpt_zero_naive_step_target,
        build_overrides={"state_spec": spec, "donate_argnums": donate},
        spec_slots={1: "state_spec", 2: "state_spec"},
        donate_slot="donate_argnums",
    )


#: step builders the autofix applier may rebuild with injected specs
#: (``python -m apex_tpu.analysis --fix`` iterates exactly these)
FIXABLE_TARGETS = {
    "gpt-zero-naive": gpt_zero_naive_step_target,
}


def all_targets(mesh=None) -> List[StepTarget]:
    mesh = mesh or dp2tp2_mesh()
    return [
        gpt_step_target(mesh),
        gpt_compressed_step_target(mesh),
        bert_step_target(mesh),
        # LAST: building it re-initializes parallel_state to dp2xpp2
        gpt_pp_step_target(),
    ]
