"""Timeline smoke check: does THIS jax still write traces we can read?

The timeline analyzer (``apex_tpu.monitor.xray.timeline``) parses the
trace-event JSON XProf exports — a format jax does not version. If a
jax upgrade changes the exporter (renames ``args.hlo_op``, stops
stringifying ``step_num``, moves the step markers off the host lane),
the analyzer would silently degrade: no steps segmented, every capture
"one undifferentiated span". This module makes that drift LOUD in the
``python -m apex_tpu.analysis`` gate: capture a real (tiny) profiler
trace of a jitted step under a ``step_annotation``, run the full
parse -> segment -> classify -> partition path over it, and report a
``profile.trace-schema`` finding when any link breaks.

This is the one analysis pass that executes device code — two jitted
matmuls, milliseconds on CPU — because schema drift is a property of
the RUNNING jax's exporter, unreachable from synthetic fixtures (those
pin the math in tests/test_timeline.py; this pins the wire format).
"""

import os
import tempfile
from typing import List

from apex_tpu.analysis.findings import Finding, SEV_ERROR

__all__ = ["timeline_smoke_findings"]

_SITE = "apex_tpu/monitor/xray/timeline/parser.py:1"
_RULE = "profile.trace-schema"
_STEPS = 2


def _drift(message: str, **data) -> Finding:
    return Finding(
        rule=_RULE,
        message=(
            f"{message} — the XProf trace-event schema this container's "
            f"jax writes no longer matches what the timeline parser "
            f"understands; fix the parser (the one blessed reader) "
            f"before any capture-based claim is trusted"
        ),
        site=_SITE,
        severity=SEV_ERROR,
        data=data,
    )


def timeline_smoke_findings() -> List[Finding]:
    """Capture + analyze a two-step trace; findings on any schema drift.

    Empty list = the exporter still writes step markers the analyzer
    segments on, op events it classifies, and a per-step partition that
    sums to the step span.
    """
    import jax
    import jax.numpy as jnp

    from apex_tpu.monitor.xray import timeline
    from apex_tpu.utils.timers import step_annotation, trace

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64), jnp.float32)
    step(x).block_until_ready()  # compile OUTSIDE the capture
    with tempfile.TemporaryDirectory(prefix="apex_tpu_trace_smoke_") as d:
        try:
            with trace(d):
                for i in range(_STEPS):
                    with step_annotation(i):
                        step(x).block_until_ready()
        except Exception as e:  # profiler itself unusable here
            return [_drift(f"jax.profiler capture failed: {e!r}")]
        try:
            tl, files = timeline.parse_logdir(d)
        except FileNotFoundError:
            return [_drift(
                "capture produced no trace-event file under the "
                "plugins/profile layout"
            )]
        except ValueError as e:
            return [_drift(f"trace file unparseable: {e}")]
        report = timeline.analyze(tl)

    findings: List[Finding] = []
    spans = tl.step_spans()
    if len(spans) < _STEPS:
        findings.append(_drift(
            f"segmented {len(spans)} step(s) from a capture of {_STEPS} "
            f"annotated steps (StepTraceAnnotation markers missing or "
            f"their step_num arg unreadable)",
            steps_found=len(spans),
            files=[os.path.basename(f) for f in files],
        ))
    if report.n_device_ops == 0:
        findings.append(_drift(
            "no XLA op events recognized (args.hlo_op / device-lane "
            "detection both came up empty for a jitted matmul)"
        ))
    for s in report.steps:
        resid = abs(
            s.compute_us + s.exposed_collective_us + s.exposed_memcpy_us
            + s.idle_us - s.span_us
        )
        if resid > 1e-6 * max(s.span_us, 1.0):
            findings.append(_drift(
                f"step {s.step} partition does not sum to its span "
                f"(residual {resid:.6f}us of {s.span_us:.3f}us)",
                step=s.step,
            ))
    return findings
