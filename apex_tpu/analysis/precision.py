"""Precision auditor: unintended low->high dtype promotions, any f64.

The invariant (PAPER.md / amp design): in a bf16/fp16 step the wide-dtype
islands are CHOSEN — master weights and optimizer moments, norm and
softmax statistics, loss/CE math — and everything else stays in the
compute dtype. A stray ``.astype(jnp.float32)`` (or an op that silently
promotes) on a hidden-sized tensor doubles that tensor's bandwidth and
memory; on the (s, b, 4h) MLP activation it is the classic 2x
activation-memory regression that arXiv:2004.13336 measures. Those casts
are invisible at runtime — loss curves match — so this pass hunts them
statically in the traced jaxpr:

- ``precision.promotion``: ``convert_element_type`` from a low dtype
  (bf16/fp16 by default) to f32/f64. Backward-pass converts synthesized
  by transposition inherit the forward cast's source line (see
  ``passes.eqn_site``) — so a kernel cast ``w.astype(bf16)`` whose
  transpose promotes the gradient to f32 (the master-grad path) is
  reported AT the forward cast site, and allowlisted there with the
  master-weight reason.
- ``precision.f64``: any equation producing an f64 value, promotions or
  literals — nothing in this library should compute in double precision
  (TPUs emulate f64 at ~1/10th rate; a single f64 op usually means a
  Python float leaked into a trace).

Intentional sites are suppressed by documented allowlist entries
(``apex_tpu/analysis/allowlist.py``), each carrying its numerical
reason. No bare entries.
"""

import collections
from typing import Iterable

import numpy as np

from apex_tpu.analysis.findings import Finding, SEV_ERROR
from apex_tpu.analysis.passes import eqn_site, jaxpr_pass

__all__ = ["precision_pass"]

_WIDE = (np.dtype(np.float32), np.dtype(np.float64))
_F64 = np.dtype(np.float64)


def _out_dtypes(eqn):
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is not None:
            yield np.dtype(dt)


@jaxpr_pass("precision")
def precision_pass(ctx) -> Iterable[Finding]:
    low = set(ctx.low_dtypes)
    promos = collections.Counter()
    f64s = collections.Counter()
    for eqn in ctx.iter_eqns():
        name = eqn.primitive.name
        if name == "convert_element_type":
            old = np.dtype(eqn.invars[0].aval.dtype)
            new = np.dtype(eqn.params["new_dtype"])
            if old in low and new in _WIDE:
                promos[(eqn_site(eqn), str(old), str(new))] += 1
                continue
        if any(dt == _F64 for dt in _out_dtypes(eqn)):
            f64s[(eqn_site(eqn), name)] += 1
    for (site, old, new), count in sorted(promos.items()):
        yield ctx.finding(
            "precision.promotion",
            f"{old} -> {new} promotion in a low-precision step",
            site=site, severity=SEV_ERROR, count=count,
            data={"from": old, "to": new},
        )
    for (site, prim), count in sorted(f64s.items()):
        yield ctx.finding(
            "precision.f64",
            f"float64 value produced by '{prim}' "
            f"(double precision is never intentional here)",
            site=site, severity=SEV_ERROR, count=count,
            data={"primitive": prim},
        )
